"""Preemption-proof elastic training (ISSUE 8): trainer death is a
non-event, proven bitwise.

The contract under test (incubate/checkpoint.py integrity tier +
io data-resume + distributed/elastic.py Supervisor + PSClient replay
persistence):

- THE proof: a PS-backed, pipelined (static PipelineRunner) training
  subprocess SIGKILLed — no grace, not SIGTERM — at a seeded mid-epoch
  step and restarted by the supervisor ends with final params AND every
  server's `table.applied` counters bitwise-equal to the uninterrupted
  run (re-sent in-doubt pushes dedupe under the checkpoint-persisted
  replay identity);
- SIGKILL racing an async checkpoint save leaves a restorable directory;
- a truncated/corrupted newest checkpoint is caught by manifest
  verification, quarantined, and restore lands on the previous verified
  step;
- `restore_into` on a model whose parameter shapes changed raises a
  clear per-param error, not a broadcast crash;
- `train_epoch_range` killed between the yield and its post-epoch save
  REDOES the interrupted epoch;
- `DataLoader.state_dict()` resumes mid-epoch at the exact batch with
  the exact shuffle;
- the Supervisor kills and restarts a trainer whose heartbeat beats but
  whose step counter stalls, and `_reap` escalates TERM -> KILL for a
  child that ignores SIGTERM.
"""
import glob
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

pytestmark = pytest.mark.chaos

CHILD_ENV = dict(os.environ, JAX_PLATFORMS="cpu",
                 PALLAS_AXON_POOL_IPS="",
                 PYTHONPATH=f"{os.path.join(REPO, 'tools')}:{REPO}")


# ------------------------------------------------- THE acceptance proof

def test_sigkill_midepoch_supervised_restart_bitwise_equal(tmp_path):
    """SIGKILL a PS-backed pipelined trainer at the seeded mid-epoch
    step; the supervisor restarts it; the resumed run must be
    indistinguishable — params bitwise, per-server applied counters
    exact (zero lost, zero double-applied), >=1 server-side replay
    actually exercised."""
    import elastic_drill as drill
    from paddle_tpu.core import monitor

    ref = drill.run_supervised(str(tmp_path), kill=False)
    # fault-free supervisor saw zero restarts
    assert ref[4] == []

    replays0 = monitor.stat_get("ps.rpc.replays")
    chaos = drill.run_supervised(str(tmp_path), kill=True)

    # the kill actually happened (SIGKILL, supervised restart)
    assert any("rc=-9" in e[2] for e in chaos[4]), chaos[4]
    kill_marker = os.path.join(str(tmp_path), "killed_chaos")
    assert os.path.exists(kill_marker)
    kill_step = int(open(kill_marker).read())
    assert kill_step == drill.kill_step_for(drill.DRILL_SEED)
    assert 0 < kill_step < drill.DRILL_STEPS  # mid-epoch, seeded

    # ...and left in-doubt pushes that were REPLAYED, not re-applied
    assert monitor.stat_get("ps.rpc.replays") - replays0 >= 1

    # bitwise: dense-model params (through the pipelined executor +
    # checkpoint restore)...
    assert set(ref[0]) == set(chaos[0])
    for k in ref[0]:
        np.testing.assert_array_equal(ref[0][k], chaos[0][k],
                                      err_msg=f"param {k}")
    # ...the PS tables themselves...
    np.testing.assert_array_equal(ref[1], chaos[1])
    np.testing.assert_array_equal(ref[2], chaos[2])
    # ...and the exactly-once observable: per-server applied counters.
    # dense0 is owned by one shard: its owner applied EXACTLY one push
    # per step — a single lost or double-applied in-doubt push breaks it
    assert ref[3] == chaos[3]
    assert max(s["dense0"] for s in chaos[3].values()) \
        == drill.DRILL_STEPS


# ---------------------------------------- checkpoint integrity tier

def _save_steps(directory, steps, async_save=False):
    from paddle_tpu.incubate.checkpoint import TrainingCheckpoint
    ck = TrainingCheckpoint(directory, keep=4, async_save=async_save)
    for s in steps:
        ck.save(s, {"w": np.arange(64, dtype="float32") * s,
                    "step": s})
    ck.wait()
    return ck


def test_truncated_newest_checkpoint_falls_back_to_verified(tmp_path):
    from paddle_tpu.core import monitor
    from paddle_tpu.incubate.checkpoint import (CheckpointCorruptError,
                                                TrainingCheckpoint)
    d = str(tmp_path / "ck")
    _save_steps(d, (1, 2)).close()

    # truncate/garble the newest step's payload blobs on disk
    blobs = glob.glob(os.path.join(d, "2", "default", "**", "d", "*"),
                      recursive=True)
    assert blobs, "no ocdbt data blobs found — layout changed?"
    for fp in blobs:
        with open(fp, "r+b") as f:
            sz = os.path.getsize(fp)
            f.truncate(max(sz // 2, 1))

    ck = TrainingCheckpoint(d, keep=4, async_save=False)
    # explicit-step restore: structured error, not garbage
    with pytest.raises(CheckpointCorruptError) as ei:
        ck.restore(2)
    assert ei.value.step == 2

    # latest-restore: quarantine + counter + walk back to verified step 1
    before = monitor.stat_get("ckpt.corrupt_skipped")
    st = ck.restore()
    assert int(st["step"]) == 1
    np.testing.assert_array_equal(st["w"],
                                  np.arange(64, dtype="float32"))
    assert monitor.stat_get("ckpt.corrupt_skipped") == before + 1
    q = os.path.join(d, ".quarantine")
    assert os.path.isdir(q) and any(n.startswith("2")
                                    for n in os.listdir(q))
    # the bad step is OUT of the walk: a fresh manager restores 1 clean
    st2 = TrainingCheckpoint(d, keep=4, async_save=False).restore()
    assert int(st2["step"]) == 1


def test_hash_mismatch_names_the_leaf(tmp_path):
    """A silent bit-flip (size-preserving, so the store layer may not
    notice) is caught by the per-leaf sha256 and NAMES the leaf."""
    from paddle_tpu.incubate.checkpoint import (CheckpointCorruptError,
                                                TrainingCheckpoint,
                                                build_manifest)
    d = str(tmp_path / "ck")
    ck = _save_steps(d, (3,))
    # forge the manifest as if leaf "w" had different bytes: simulates
    # stored-data corruption the reader cannot see structurally
    man = build_manifest(3, {"w": np.zeros(64, "float32"),
                             "step": np.asarray(3)})
    with open(os.path.join(d, "manifest_3.json"), "w") as f:
        json.dump(man, f)
    with pytest.raises(CheckpointCorruptError) as ei:
        ck.restore(3)
    assert ei.value.leaf == "w"
    assert "sha256" in ei.value.reason


def test_sigkill_during_async_save_leaves_restorable_dir(tmp_path):
    """Kill the trainer WHILE an async checkpoint is writing: the
    directory must stay restorable (the previous committed step; or the
    new one if the commit won the race) — never a crash, never garbage."""
    d = str(tmp_path / "ck")
    child = textwrap.dedent(f"""
        import os, numpy as np
        import jax; jax.config.update("jax_platforms", "cpu")
        from paddle_tpu.incubate.checkpoint import TrainingCheckpoint
        ck = TrainingCheckpoint({d!r}, keep=3, async_save=True)
        ck.save(1, {{"w": np.full((1 << 10,), 1, "float32"), "step": 1}})
        ck.wait()
        # a BIG step 2 so the async write is still in flight at kill
        ck.save(2, {{"w": np.ones((1 << 22,), "float32"), "step": 2}})
        os.kill(os.getpid(), 9)
    """)
    proc = subprocess.run([sys.executable, "-c", child], env=CHILD_ENV,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    from paddle_tpu.incubate.checkpoint import TrainingCheckpoint
    ck = TrainingCheckpoint(d, keep=3, async_save=False)
    st = ck.restore()
    assert st is not None, "SIGKILL during async save lost ALL state"
    step = int(st["step"])
    assert step in (1, 2)
    np.testing.assert_array_equal(
        np.asarray(st["w"])[:4], np.full((4,), step, "float32"))


def test_restore_into_shape_mismatch_names_param(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.incubate.checkpoint import TrainingCheckpoint

    def build(in_dim):
        net = nn.Sequential(nn.Linear(in_dim, 3), nn.Linear(3, 1))
        model = paddle.Model(net)
        model.prepare(optimizer=optimizer.Adam(
            learning_rate=0.01, parameters=net.parameters()),
            loss=nn.MSELoss())
        return model

    d = str(tmp_path / "ck")
    ck = TrainingCheckpoint(d, keep=2, async_save=False)
    ck.save(5, ck.capture(build(4), 0, 4, 5))
    ck.wait()

    with pytest.raises(ValueError, match="shape mismatch") as ei:
        ck.restore_into(build(6))   # first Linear grew: [4,3] -> [6,3]
    msg = str(ei.value)
    assert "[4, 3]" in msg and "[6, 3]" in msg
    # the offending parameter is NAMED
    assert ".w_" in msg or "weight" in msg, msg


def test_train_epoch_range_killed_before_commit_redoes_epoch(tmp_path):
    """Killed between the yield (body done) and the post-epoch save:
    the interrupted epoch must be REDONE on restart, never skipped."""
    from paddle_tpu.incubate.checkpoint import train_epoch_range
    d = str(tmp_path / "er")
    gen = train_epoch_range(4, directory=d)
    assert next(gen) == 0
    assert next(gen) == 1    # resuming the iterator commits epoch 0...
    gen.close()              # ...then death lands before epoch 1 commits
    assert list(train_epoch_range(4, directory=d)) == [1, 2, 3]


# -------------------------------------------------- exact data resume

class _IdxDataset:
    def __init__(self, n):
        self.n = n

    def __getitem__(self, i):
        return np.asarray([i], np.int64)

    def __len__(self):
        return self.n


def _batch_ids(batches):
    return [tuple(int(v) for v in np.asarray(b).ravel()) for b in batches]


def test_dataloader_exact_midepoch_resume_with_shuffle():
    from paddle_tpu.io import DataLoader

    ref = DataLoader(_IdxDataset(12), batch_size=3, shuffle=True,
                     shuffle_seed=42)
    sched = [_batch_ids(ref) for _ in range(3)]   # 3 uninterrupted epochs
    assert sched[0] != sched[1]                   # reshuffles per epoch

    run = DataLoader(_IdxDataset(12), batch_size=3, shuffle=True,
                     shuffle_seed=42)
    _batch_ids(run)                               # epoch 0
    it = iter(run)
    consumed = [next(it), next(it)]               # 2 batches of epoch 1
    assert _batch_ids(consumed) == sched[1][:2]
    sd = run.state_dict()
    assert sd["epoch"] == 1 and sd["batch"] == 2

    # a FRESH loader (new process, different default seed) + state
    res = DataLoader(_IdxDataset(12), batch_size=3, shuffle=True,
                     shuffle_seed=7)
    res.load_state_dict(sd)
    assert _batch_ids(res) == sched[1][2:]        # exact mid-epoch tail
    assert _batch_ids(res) == sched[2]            # next epoch exact too


def test_dataloader_completed_epoch_state_rolls_forward():
    from paddle_tpu.io import DataLoader
    ref = DataLoader(_IdxDataset(8), batch_size=2, shuffle=True,
                     shuffle_seed=3)
    sched = [_batch_ids(ref) for _ in range(2)]

    run = DataLoader(_IdxDataset(8), batch_size=2, shuffle=True,
                     shuffle_seed=3)
    it = iter(run)
    for _ in range(4):
        next(it)                     # consume ALL of epoch 0...
    sd = run.state_dict()            # ...but the epoch never rolled
    assert sd["epoch"] == 0 and sd["batch"] == 4

    res = DataLoader(_IdxDataset(8), batch_size=2, shuffle=True,
                     shuffle_seed=99)
    res.load_state_dict(sd)
    assert _batch_ids(res) == sched[1]   # auto-rolls into epoch 1, exact


def test_checkpoint_carries_data_section_roundtrip(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.incubate.checkpoint import TrainingCheckpoint
    from paddle_tpu.io import DataLoader

    net = nn.Sequential(nn.Linear(2, 1))
    model = paddle.Model(net)
    model.prepare(optimizer=optimizer.Adam(learning_rate=0.01,
                                           parameters=net.parameters()),
                  loss=nn.MSELoss())
    loader = DataLoader(_IdxDataset(10), batch_size=2, shuffle=True,
                        shuffle_seed=5)
    it = iter(loader)
    next(it), next(it), next(it)
    data_state = loader.state_dict()     # position: epoch 0, batch 3
    expect_tail = _batch_ids(it)         # rest of the epoch

    ck = TrainingCheckpoint(str(tmp_path / "ck"), keep=2,
                            async_save=False)
    ck.save(3, ck.capture(model, 0, 2, 3, data_state=data_state))
    ck.wait()

    loader2 = DataLoader(_IdxDataset(10), batch_size=2, shuffle=True,
                         shuffle_seed=5)
    counters = ck.restore_into(model, data_loader=loader2)
    assert counters["data_resumed"] is True
    assert counters == {**counters, "epoch": 0, "step": 2,
                        "global_step": 3}
    # loader2 was mid-epoch-armed: wait, loader above consumed 3 batches
    got = _batch_ids(loader2)
    assert got == expect_tail


def test_train_from_dataset_start_batch_resumes_exact(tmp_path):
    """Executor.train_from_dataset(start_batch=N) — the two halves of a
    split run produce the same final params as the whole run."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, ops, optimizer, static

    def build(tag):
        paddle.seed(0)
        prog = static.Program(f"tfd_{tag}")
        with static.program_guard(prog):
            x = static.data("x", [-1, 4], "float32")
            y = static.data("y", [-1, 1], "float32")
            loss = ops.mse_loss(nn.Linear(4, 1)(x), y)
            optimizer.SGD(learning_rate=0.1).minimize(loss)
        return prog, loss

    class _Feeds:
        def __init__(self, n):
            self.n = n

        def batches(self, start_batch=0):
            rng = np.random.RandomState(5)
            all_ = [{"x": rng.rand(4, 4).astype("float32"),
                     "y": rng.rand(4, 1).astype("float32")}
                    for _ in range(self.n)]
            yield from all_[int(start_batch):]

    paddle.enable_static()
    try:
        exe = static.Executor()
        prog, _ = build("whole")
        exe.train_from_dataset(prog, _Feeds(6))
        want = [np.asarray(static.global_scope().get(n))
                for n in prog.persist_ids]

        prog2, _ = build("split")
        exe.train_from_dataset(prog2, _Feeds(3))     # first 3 batches
        exe.train_from_dataset(prog2, _Feeds(6), start_batch=3)
        got = [np.asarray(static.global_scope().get(n))
               for n in prog2.persist_ids]
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w, g)
    finally:
        paddle.disable_static()


def test_fit_resume_at_epoch_boundary_stays_bitwise(tmp_path):
    """A checkpoint saved exactly at an epoch boundary (freq divides the
    epoch length, steps=None) must resume into the NEXT epoch — not
    re-train one extra loader epoch under a stale epoch label."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.io import DataLoader

    class DS:
        def __getitem__(self, i):
            r = np.random.RandomState(i)
            return (r.rand(4).astype("float32"),
                    r.rand(1).astype("float32"))

        def __len__(self):
            return 12

    def build():
        paddle.seed(9)
        net = nn.Sequential(nn.Linear(4, 3), nn.Linear(3, 1))
        model = paddle.Model(net)
        model.prepare(optimizer=optimizer.Adam(
            learning_rate=0.01, parameters=net.parameters()),
            loss=nn.MSELoss())
        return model, net

    def loader():
        return DataLoader(DS(), batch_size=2, shuffle=True,
                          shuffle_seed=13)

    def params(net):
        return {k: np.asarray(v._value if hasattr(v, "_value") else v)
                for k, v in net.state_dict().items()}

    ref_model, ref_net = build()
    ref_model.fit(train_data=loader(), epochs=3, verbose=0)
    want = params(ref_net)

    # epoch length 6, freq 6: the save lands exactly at epoch 0's end
    # with data cursor batch == len(loader); fit(epochs=1) then ends —
    # the same on-disk state a kill right after that save leaves
    d = str(tmp_path / "ck")
    m1, _ = build()
    m1.fit(train_data=loader(), epochs=1, verbose=0,
           auto_checkpoint_dir=d, auto_checkpoint_freq=6)

    m2, net2 = build()
    m2.fit(train_data=loader(), epochs=3, verbose=0,
           auto_checkpoint_dir=d, auto_checkpoint_freq=6)
    got = params(net2)
    assert set(want) == set(got)
    for k in want:
        np.testing.assert_array_equal(want[k], got[k], err_msg=k)


# ------------------------------------------------- supervisor behavior

_STALL_SCRIPT = textwrap.dedent("""
    import json, os, sys, time
    hb, cnt = sys.argv[1], sys.argv[2]
    n = int(open(cnt).read()) if os.path.exists(cnt) else 0
    with open(cnt, "w") as f:
        f.write(str(n + 1))
    if n >= 1:
        sys.exit(0)          # restarted attempt: healthy, done
    os.makedirs(hb, exist_ok=True)
    t0 = time.time()
    while time.time() - t0 < 60:
        tmp = os.path.join(hb, "heartbeat_0.json.tmp")
        with open(tmp, "w") as f:       # beats keep coming...
            json.dump({"rank": 0, "step": 5,    # ...step NEVER advances
                       "time": time.time()}, f)
        os.replace(tmp, os.path.join(hb, "heartbeat_0.json"))
        time.sleep(0.05)
""")


def test_supervisor_restarts_stalled_trainer(tmp_path):
    from paddle_tpu.core import monitor
    from paddle_tpu.distributed.elastic import Supervisor
    script = tmp_path / "stall.py"
    script.write_text(_STALL_SCRIPT)
    hb = str(tmp_path / "hb")
    cnt = str(tmp_path / "attempts")

    def start(rank):
        return subprocess.Popen([sys.executable, str(script), hb, cnt],
                                env=dict(os.environ))

    stalls0 = monitor.stat_get("elastic.stalls")
    sup = Supervisor(start, nranks=1, heartbeat_dir=hb, max_restarts=2,
                     backoff_s=0.05, heartbeat_timeout_s=30.0,
                     stall_timeout_s=1.0, poll_s=0.1)
    assert sup.run() == 0
    assert any("stalled" in e[2] for e in sup.events), sup.events
    assert monitor.stat_get("elastic.stalls") > stalls0
    assert int(open(cnt).read()) == 2    # original + one restart


def test_supervisor_exhausted_budget_raises(tmp_path):
    from paddle_tpu.distributed.elastic import Supervisor

    def start(rank):
        return subprocess.Popen([sys.executable, "-c",
                                 "import sys; sys.exit(3)"])

    sup = Supervisor(start, nranks=1, max_restarts=1, backoff_s=0.01,
                     poll_s=0.05)
    with pytest.raises(SystemExit) as ei:
        sup.run()
    assert ei.value.code == 3
    assert sup.restarts[0] == 2          # budget burned, then gave up


def test_reap_escalates_term_to_kill():
    """Satellite: a child that ignores SIGTERM must not hang or leak
    through the launcher teardown — bounded wait, then KILL."""
    from paddle_tpu.distributed.elastic import _reap
    p = subprocess.Popen([sys.executable, "-c", textwrap.dedent("""
        import signal, time
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        print("armed", flush=True)
        time.sleep(120)
    """)], stdout=subprocess.PIPE, text=True)
    assert p.stdout.readline().strip() == "armed"
    t0 = time.monotonic()
    _reap([p], grace_s=1.0)
    assert time.monotonic() - t0 < 30
    assert p.poll() == -signal.SIGKILL


def test_supervisor_ignores_previous_incarnation_beats(tmp_path):
    """A stale beat file left by a killed incarnation (or a previous
    job in the same dir) must not storm the restart budget: the
    supervisor grants the restarted child its startup window instead of
    re-declaring staleness every poll."""
    from paddle_tpu.distributed.elastic import Supervisor
    hb = str(tmp_path / "hb")
    os.makedirs(hb)
    with open(os.path.join(hb, "heartbeat_0.json"), "w") as f:
        json.dump({"rank": 0, "step": 3, "time": time.time() - 1000}, f)

    script = textwrap.dedent("""
        import json, os, sys, time
        hb = sys.argv[1]
        time.sleep(0.5)     # several poll cycles with only the stale beat
        tmp = os.path.join(hb, "heartbeat_0.json.tmp")
        with open(tmp, "w") as f:
            json.dump({"rank": 0, "step": 1, "time": time.time()}, f)
        os.replace(tmp, os.path.join(hb, "heartbeat_0.json"))
    """)

    def start(rank):
        return subprocess.Popen([sys.executable, "-c", script, hb],
                                env=dict(os.environ))

    sup = Supervisor(start, nranks=1, heartbeat_dir=hb, max_restarts=3,
                     backoff_s=0.05, heartbeat_timeout_s=2.0,
                     stall_timeout_s=300.0, poll_s=0.05)
    assert sup.run() == 0
    assert sup.events == [], sup.events   # zero restarts burned


def test_armed_loader_state_dict_returns_restored_position():
    """A grace save taken BEFORE the first resumed batch must re-save
    the restored cursor, not the loader's stale local counters."""
    from paddle_tpu.io import DataLoader
    run = DataLoader(_IdxDataset(12), batch_size=3, shuffle=True,
                     shuffle_seed=42)
    it = iter(run)
    next(it), next(it)
    sd = run.state_dict()

    res = DataLoader(_IdxDataset(12), batch_size=3, shuffle=True,
                     shuffle_seed=7)
    res.load_state_dict(sd)
    armed = res.state_dict()             # before ANY resumed iteration
    assert armed["epoch"] == sd["epoch"]
    assert armed["batch"] == sd["batch"]
    np.testing.assert_array_equal(
        armed["sampler"]["sampler"]["rng"]["key"],
        sd["sampler"]["sampler"]["rng"]["key"])


def test_roll_resumed_epoch_starts_next_epoch_fresh():
    """fit(steps=N) truncates epochs at a batch count the loader can't
    see; rolling the armed resume must advance the shuffle stream past
    the truncated epoch and start the next one fresh — not replay the
    truncated epoch's tail."""
    from paddle_tpu.io import DataLoader
    ref = DataLoader(_IdxDataset(12), batch_size=3, shuffle=True,
                     shuffle_seed=21)
    sched = [_batch_ids(ref) for _ in range(2)]

    run = DataLoader(_IdxDataset(12), batch_size=3, shuffle=True,
                     shuffle_seed=21)
    it = iter(run)
    next(it), next(it)                   # steps=2 cap: epoch truncated
    sd = run.state_dict()

    res = DataLoader(_IdxDataset(12), batch_size=3, shuffle=True,
                     shuffle_seed=99)
    res.load_state_dict(sd)
    res.roll_resumed_epoch()
    assert _batch_ids(res) == sched[1]   # fresh epoch-1 permutation


def test_heartbeat_beat_thread_writes_live_step(tmp_path):
    """Satellite: the beat thread must carry the LIVE step (step_fn /
    notify_step), not the last update(step=...) snapshot."""
    from paddle_tpu.core import monitor
    from paddle_tpu.distributed import elastic
    step = {"n": 0}
    hb = elastic.Heartbeat(str(tmp_path), rank=0, interval_s=0.05,
                           step_fn=lambda: step["n"]).start()
    try:
        step["n"] = 41
        deadline = time.monotonic() + 5
        path = os.path.join(str(tmp_path), "heartbeat_0.json")
        got = None
        while time.monotonic() < deadline:
            with open(path) as f:
                got = json.load(f)["step"]
            if got == 41:
                break
            time.sleep(0.02)
        assert got == 41, "beat thread kept re-writing a stale step"
        # the supervisor-side age gauge publishes on check()
        assert elastic.Heartbeat.check(str(tmp_path), timeout_s=60) == []
        assert monitor.stat_get("elastic.heartbeat_age_s") >= 0
    finally:
        hb.stop()


def test_notify_step_reaches_registered_listeners(tmp_path):
    from paddle_tpu.distributed import elastic
    mon = elastic.StallMonitor(timeout_s=300.0).start()
    hb = elastic.Heartbeat(str(tmp_path), rank=0,
                           interval_s=60.0).start()
    try:
        before = mon._last
        time.sleep(0.01)
        elastic.notify_step(17)
        assert mon._last > before
        assert hb._step == 17
    finally:
        mon.stop()
        hb.stop()


def test_stall_monitor_default_flight_records(tmp_path, monkeypatch):
    """Satellite: the default on_stall counts elastic.stalls and writes
    a flight-recorder dump (reason=stall)."""
    from paddle_tpu.core import monitor
    from paddle_tpu.distributed.elastic import StallMonitor
    monkeypatch.setenv("PADDLE_TPU_DUMP_DIR", str(tmp_path))
    before = monitor.stat_get("elastic.stalls")
    m = StallMonitor(timeout_s=300.0)
    m.on_stall(12.5)
    assert monitor.stat_get("elastic.stalls") == before + 1
    assert glob.glob(os.path.join(str(tmp_path), "obsdump_stall_*"))
