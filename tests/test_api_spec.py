"""API-surface guard (reference tools/ API.spec approval discipline):
the live public surface must match the committed snapshot, so removals
and signature changes are deliberate. Regenerate with
`python tools/gen_api_spec.py --update`."""
import os
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_api_surface_matches_spec():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import gen_api_spec
    live = gen_api_spec.collect()
    with open(os.path.join(REPO, "API.spec")) as f:
        committed = f.read()
    if live != committed:
        live_set = set(live.splitlines())
        comm_set = set(committed.splitlines())
        removed = sorted(comm_set - live_set)[:20]
        added = sorted(live_set - comm_set)[:20]
        raise AssertionError(
            "public API surface drifted from API.spec — if intentional, "
            "run `python tools/gen_api_spec.py --update`.\n"
            f"removed/changed: {removed}\nadded/changed: {added}")
    assert "MISSING" not in committed
