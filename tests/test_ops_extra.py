"""Extended math + detection op families vs numpy/scipy references
(reference golden-op discipline, unittests/op_test.py:232)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops
from op_test import check_output, numeric_grad

T = paddle.to_tensor


def test_special_functions():
    import scipy.special as sp
    x = np.abs(np.random.RandomState(0).randn(8)).astype("float32") + 0.5
    np.testing.assert_allclose(ops.gammaln(T(x)).numpy(), sp.gammaln(x),
                               rtol=1e-5)
    np.testing.assert_allclose(ops.i0(T(x)).numpy(), sp.i0(x), rtol=1e-5)
    np.testing.assert_allclose(ops.i1e(T(x)).numpy(), sp.i1e(x), rtol=1e-5)
    np.testing.assert_allclose(ops.igamma(T(x), T(x)).numpy(),
                               sp.gammainc(x, x), rtol=1e-5)
    np.testing.assert_allclose(ops.polygamma(T(x), n=1).numpy(),
                               sp.polygamma(1, x), rtol=2e-4)


def test_elementwise_extras():
    rng = np.random.RandomState(1)
    x = rng.randn(6).astype("float32")
    y = rng.randn(6).astype("float32")
    check_output(ops.hypot, np.hypot, [x, y])
    check_output(ops.copysign, np.copysign, [x, y])
    check_output(ops.sinc, np.sinc, [x])
    assert (ops.signbit(T(x)).numpy() == np.signbit(x)).all()
    np.testing.assert_allclose(ops.fix(T(x * 3)).numpy(), np.trunc(x * 3))
    m, e = ops.frexp(T(x))
    np.testing.assert_allclose(m.numpy() * (2.0 ** e.numpy()), x,
                               rtol=1e-6)


def test_trapezoid_and_cumulative():
    y = np.array([1.0, 2.0, 3.0, 4.0], "float32")
    np.testing.assert_allclose(ops.trapezoid(T(y)).numpy(),
                               np.trapezoid(y))
    ct = ops.cumulative_trapezoid(T(y)).numpy()
    np.testing.assert_allclose(ct, [1.5, 4.0, 7.5])


def test_cummax_cummin():
    x = np.array([[1.0, 3.0, 2.0], [4.0, 1.0, 5.0]], "float32")
    vals, idx = ops.cummax(T(x), axis=1)
    np.testing.assert_allclose(vals.numpy(), [[1, 3, 3], [4, 4, 5]])
    np.testing.assert_array_equal(idx.numpy(), [[0, 1, 1], [0, 0, 2]])
    vals, idx = ops.cummin(T(x), axis=1)
    np.testing.assert_allclose(vals.numpy(), [[1, 1, 1], [4, 1, 1]])


def test_indexing_ops():
    x = np.zeros((4, 3), "float32")
    out = ops.index_add(T(x), T(np.array([0, 2])), 0,
                        T(np.ones((2, 3), "float32")))
    assert out.numpy()[0].sum() == 3 and out.numpy()[2].sum() == 3
    out = ops.index_fill(T(x), T(np.array([1])), 0, 7.0)
    assert (out.numpy()[1] == 7).all()
    out = ops.bucketize(T(np.array([0.5, 3.5, 9.0])),
                        T(np.array([1.0, 2.0, 4.0])))
    np.testing.assert_array_equal(out.numpy(), [0, 2, 3])
    sc = ops.select_scatter(T(x), T(np.full(3, 5.0, "float32")), 0, 2)
    assert (sc.numpy()[2] == 5).all()
    ms = ops.masked_scatter(T(x), T(x == 0),
                            T(np.arange(12, dtype="float32")))
    np.testing.assert_allclose(ms.numpy().reshape(-1), np.arange(12))


def test_distances_and_stats():
    import scipy.spatial.distance as sd
    rng = np.random.RandomState(2)
    a = rng.randn(5, 3).astype("float32")
    b = rng.randn(4, 3).astype("float32")
    np.testing.assert_allclose(ops.cdist(T(a), T(b)).numpy(),
                               sd.cdist(a, b), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ops.pdist(T(a)).numpy(), sd.pdist(a),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ops.cov(T(a)).numpy(), np.cov(a),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ops.corrcoef(T(a)).numpy(), np.corrcoef(a),
                               rtol=1e-4, atol=1e-5)


def test_lu_roundtrip_and_cholesky_solve():
    rng = np.random.RandomState(3)
    A = rng.randn(4, 4).astype("float32")
    A = A @ A.T + 4 * np.eye(4, dtype="float32")
    lu_mat, piv = ops.lu(T(A))
    P, L, U = ops.lu_unpack(lu_mat, piv)
    np.testing.assert_allclose(P.numpy() @ L.numpy() @ U.numpy(), A,
                               atol=1e-4)
    c = np.linalg.cholesky(A).astype("float32")
    bvec = rng.randn(4, 1).astype("float32")
    xs = ops.cholesky_solve(T(bvec), T(c))
    np.testing.assert_allclose(A @ xs.numpy(), bvec, atol=1e-3)


def test_fold_inverts_unfold():
    rng = np.random.RandomState(4)
    x = rng.randn(2, 3, 8, 8).astype("float32")
    cols = ops.unfold(T(x), kernel_sizes=2, strides=2)
    back = ops.fold(cols, output_sizes=(8, 8), kernel_sizes=2, strides=2)
    np.testing.assert_allclose(back.numpy(), x, atol=1e-6)


def test_random_extras():
    g = ops.standard_gamma(T(np.full(2000, 3.0, "float32")))
    assert abs(float(g.numpy().mean()) - 3.0) < 0.3
    b = ops.binomial(T(np.full(2000, 10.0)), T(np.full(2000, 0.5)))
    assert abs(float(np.asarray(b.numpy()).mean()) - 5.0) < 0.5


# ------------------------------ detection ---------------------------------

def test_iou_similarity():
    a = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], "float32")
    iou = ops.iou_similarity(T(a), T(a)).numpy()
    np.testing.assert_allclose(np.diag(iou), [1.0, 1.0])
    np.testing.assert_allclose(iou[0, 1], 1.0 / 7.0, rtol=1e-5)


def test_box_coder_roundtrip():
    priors = np.array([[0, 0, 4, 4], [2, 2, 8, 8]], "float32")
    targets = np.array([[1, 1, 3, 3]], "float32")
    enc = ops.box_coder(T(priors), None, T(targets),
                        code_type="encode_center_size").numpy()  # [1,2,4]
    dec = ops.box_coder(T(priors), None,
                        T(enc.astype("float32")),
                        code_type="decode_center_size", axis=0).numpy()
    np.testing.assert_allclose(dec[0, 0], targets[0], atol=1e-4)


def test_prior_box_shapes():
    feat = np.zeros((1, 8, 4, 4), "float32")
    img = np.zeros((1, 3, 32, 32), "float32")
    boxes, var = ops.prior_box(T(feat), T(img), min_sizes=[8.0],
                               aspect_ratios=[1.0, 2.0], flip=True)
    assert boxes.numpy().shape == (4, 4, 3, 4)
    assert var.numpy().shape == (4, 4, 3, 4)
    assert np.isfinite(boxes.numpy()).all()


def test_yolo_box_shapes():
    n, anchors, C, h = 1, [10, 13, 16, 30], 2, 4
    x = np.random.RandomState(5).randn(
        n, 2 * (5 + C), h, h).astype("float32")
    img = np.array([[64, 64]], "int32")
    boxes, scores = ops.yolo_box(T(x), T(img), anchors, C)
    assert boxes.numpy().shape == (1, 2 * h * h, 4)
    assert scores.numpy().shape == (1, 2 * h * h, C)


def test_nms_and_multiclass():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     "float32")
    scores = np.array([0.9, 0.8, 0.7], "float32")
    keep = ops.nms(T(boxes), 0.5, scores=T(scores)).numpy()
    np.testing.assert_array_equal(keep, [0, 2])
    s = np.zeros((1, 2, 3), "float32")
    s[0, 1] = scores
    out, nums = ops.multiclass_nms(T(boxes[None]), T(s),
                                   score_threshold=0.1, nms_threshold=0.5)
    assert int(nums.numpy()[0]) == 2
    assert out.numpy().shape == (2, 6)


def test_bipartite_match():
    d = np.array([[0.9, 0.1], [0.2, 0.8]], "float32")
    idx, dist = ops.bipartite_match(T(d))
    np.testing.assert_array_equal(idx.numpy(), [0, 1])
    np.testing.assert_allclose(dist.numpy(), [0.9, 0.8])


def test_roi_align_and_pool():
    x = np.arange(2 * 1 * 8 * 8, dtype="float32").reshape(2, 1, 8, 8)
    boxes = np.array([[0, 0, 4, 4], [2, 2, 6, 6]], "float32")
    out = ops.roi_align(T(x), T(boxes), boxes_num=[1, 1], output_size=2)
    assert out.numpy().shape == (2, 1, 2, 2)
    assert np.isfinite(out.numpy()).all()
    # differentiable: grads flow to the feature map
    xt = T(x)
    xt.stop_gradient = False
    ops.roi_align(xt, T(boxes), boxes_num=[1, 1],
                  output_size=2).sum().backward()
    assert np.abs(np.asarray(xt.grad._value)).sum() > 0
    out = ops.roi_pool(T(x), T(boxes), boxes_num=[1, 1], output_size=2)
    assert out.numpy().shape == (2, 1, 2, 2)
    # roi_pool of a monotone ramp: max of each bin is its bottom-right
    assert float(out.numpy()[0, 0, 1, 1]) >= float(out.numpy()[0, 0, 0, 0])


def test_grad_check_selected_extras():
    rng = np.random.RandomState(6)
    x = rng.rand(3, 3).astype("float64") + 0.5
    g_an = paddle.to_tensor(x)
    g_an.stop_gradient = False
    ops.gammaln(g_an).sum().backward()
    g_num = numeric_grad(ops.gammaln, [x], 0)
    np.testing.assert_allclose(np.asarray(g_an.grad._value), g_num,
                               rtol=5e-3, atol=1e-3)


def test_ctc_loss_matches_torch():
    """Golden test vs torch.nn.functional.ctc_loss (CPU torch is the
    reference implementation of the same warpctc semantics), values AND
    gradients, with variable input/label lengths."""
    import torch
    import torch.nn.functional as tF

    rng = np.random.RandomState(0)
    Tm, B, C, S = 12, 3, 5, 4
    logits = rng.randn(Tm, B, C).astype("float32")
    labels = rng.randint(1, C, (B, S)).astype("int64")  # no blanks inside
    in_lens = np.array([12, 9, 7], "int64")
    lab_lens = np.array([4, 3, 1], "int64")

    # torch reference (expects log_probs)
    t_logits = torch.tensor(logits, requires_grad=True)
    t_lp = tF.log_softmax(t_logits, dim=-1)
    t_loss = tF.ctc_loss(t_lp, torch.tensor(labels),
                         torch.tensor(in_lens), torch.tensor(lab_lens),
                         blank=0, reduction="mean", zero_infinity=False)
    t_loss.backward()

    x = paddle.to_tensor(logits)
    x.stop_gradient = False
    loss = ops.ctc_loss(x, paddle.to_tensor(labels),
                        paddle.to_tensor(in_lens),
                        paddle.to_tensor(lab_lens), blank=0,
                        reduction="mean")
    np.testing.assert_allclose(float(loss.numpy()), float(t_loss),
                               rtol=1e-4)
    loss.backward()
    np.testing.assert_allclose(np.asarray(x.grad._value),
                               t_logits.grad.numpy(), atol=2e-4)

    # torch 'mean' divides per-sample by label_length then averages; also
    # check the sum reduction path and the layer wrapper
    from paddle_tpu import nn as pnn
    layer = pnn.CTCLoss(blank=0, reduction="sum")
    l2 = layer(paddle.to_tensor(logits), paddle.to_tensor(labels),
               paddle.to_tensor(in_lens), paddle.to_tensor(lab_lens))
    t_sum = tF.ctc_loss(tF.log_softmax(torch.tensor(logits), -1),
                        torch.tensor(labels), torch.tensor(in_lens),
                        torch.tensor(lab_lens), blank=0, reduction="sum")
    np.testing.assert_allclose(float(l2.numpy()), float(t_sum), rtol=1e-4)


def test_linear_chain_crf_vs_bruteforce():
    """CRF NLL and viterbi vs exhaustive path enumeration (reference
    linear_chain_crf_op.cc, crf_decoding_op.cc), incl. ragged lengths."""
    import itertools
    rng = np.random.RandomState(0)
    B, Tm, N = 2, 3, 3
    em = rng.randn(B, Tm, N).astype("float32")
    trans = rng.randn(N + 2, N).astype("float32")
    start, stop, pair = trans[0], trans[1], trans[2:]
    labels = rng.randint(0, N, (B, Tm)).astype("int64")
    lengths = np.array([3, 2], "int64")

    def path_score(b, path):
        s = start[path[0]] + em[b, 0, path[0]]
        for t in range(1, len(path)):
            s += pair[path[t - 1], path[t]] + em[b, t, path[t]]
        return s + stop[path[-1]]

    want_nll, want_path = [], []
    for b in range(B):
        L = int(lengths[b])
        scores = {p: path_score(b, p)
                  for p in itertools.product(range(N), repeat=L)}
        logZ = np.logaddexp.reduce(np.array(list(scores.values())))
        gold = path_score(b, tuple(labels[b, :L]))
        want_nll.append(logZ - gold)
        want_path.append(max(scores, key=scores.get))

    nll = ops.linear_chain_crf(T(em), T(trans), T(labels),
                               T(lengths)).numpy()
    np.testing.assert_allclose(nll, want_nll, rtol=1e-5)

    scores, paths = ops.viterbi_decode(T(em), T(trans), T(lengths))
    p = paths.numpy()
    for b in range(B):
        L = int(lengths[b])
        np.testing.assert_array_equal(p[b, :L], want_path[b])

    # differentiable: grads flow to emissions and transitions
    e_t, tr_t = T(em), T(trans)
    e_t.stop_gradient = tr_t.stop_gradient = False
    ops.linear_chain_crf(e_t, tr_t, T(labels), T(lengths)).sum().backward()
    assert np.isfinite(np.asarray(e_t.grad._value)).all()
    assert np.isfinite(np.asarray(tr_t.grad._value)).all()

    # regression: a NON-constant best path (random seeds above happened to
    # have constant optima, which masked a backtrack emit bug that dropped
    # tag0 and duplicated the final tag)
    em2 = np.full((1, 3, 3), -5.0, "float32")
    em2[0, 0, 0] = em2[0, 1, 1] = em2[0, 2, 2] = 5.0
    t2 = np.zeros((5, 3), "float32")
    _, p2 = ops.viterbi_decode(T(em2), T(t2))
    np.testing.assert_array_equal(p2.numpy()[0], [0, 1, 2])


def test_grid_sample_and_affine_grid_vs_torch():
    """Golden vs torch grid_sample/affine_grid (CPU torch implements the
    same grid_sampler_op semantics)."""
    import torch
    import torch.nn.functional as tF
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 5, 7).astype("float32")
    theta = rng.randn(2, 2, 3).astype("float32") * 0.3

    for align in (True, False):
        grid_t = tF.affine_grid(torch.tensor(theta), (2, 3, 4, 6),
                                align_corners=align).numpy()
        grid_m = ops.affine_grid(T(theta), (2, 3, 4, 6),
                                 align_corners=align).numpy()
        np.testing.assert_allclose(grid_m, grid_t, atol=1e-5)

        for mode in ("bilinear", "nearest"):
            for pad in ("zeros", "border"):
                want = tF.grid_sample(torch.tensor(x),
                                      torch.tensor(grid_t), mode=mode,
                                      padding_mode=pad,
                                      align_corners=align).numpy()
                got = ops.grid_sample(T(x), T(grid_t), mode=mode,
                                      padding_mode=pad,
                                      align_corners=align).numpy()
                np.testing.assert_allclose(got, want, atol=1e-4,
                                           err_msg=f"{mode}/{pad}/{align}")


def test_channel_shuffle_and_pixel_unshuffle():
    import torch
    import torch.nn.functional as tF
    rng = np.random.RandomState(1)
    x = rng.randn(2, 8, 4, 4).astype("float32")
    got = ops.channel_shuffle(T(x), 2).numpy()
    want = tF.channel_shuffle(torch.tensor(x), 2).numpy()
    np.testing.assert_allclose(got, want)
    got = ops.pixel_unshuffle(T(x), 2).numpy()
    want = tF.pixel_unshuffle(torch.tensor(x), 2).numpy()
    np.testing.assert_allclose(got, want)
    # round trip with the existing pixel_shuffle
    back = ops.pixel_shuffle(T(got), 2).numpy()
    np.testing.assert_allclose(back, x)
