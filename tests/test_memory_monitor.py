"""Memory introspection (reference memory/ stats surface) and the
monitor StatRegistry (reference platform/monitor.h:77)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import memory
from paddle_tpu.core import monitor


def test_live_accounting_tracks_allocations():
    base = memory.memory_allocated()
    big = paddle.to_tensor(np.zeros((256, 1024), "float32"))  # 1 MB
    now = memory.memory_allocated()
    assert now >= base + 1_000_000, (base, now)
    s = memory.summary()
    assert "live arrays" in s and "float32" in s
    del big
    memory.empty_cache()  # parity no-op, must not raise


def test_stats_surface():
    st = memory.stats()
    assert isinstance(st, dict)  # may be empty on CPU PJRT
    assert memory.max_memory_allocated() >= 0
    assert memory.memory_reserved() >= 0
    keep = paddle.to_tensor(np.ones((4,), "float32"))
    assert memory.live_tensor_count() >= 1
    del keep


def test_monitor_stat_registry():
    monitor.reset()
    monitor.stat_add("unit/x")
    monitor.stat_add("unit/x", 4)
    monitor.stat_set("unit/y", 2.5)
    assert monitor.stat_get("unit/x") == 5
    assert monitor.stats()["unit/y"] == 2.5
    monitor.reset("unit/x")
    assert monitor.stat_get("unit/x") == 0


def test_runtime_counters_bump():
    import paddle_tpu.static as static
    from paddle_tpu import ops
    monitor.reset()
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            y = ops.sum(x)
        exe = static.Executor()
        for _ in range(3):
            exe.run(main, feed={"x": np.ones(2, "float32")},
                    fetch_list=[y])
    finally:
        paddle.disable_static()
    assert monitor.stat_get("executor/lowerings") == 1  # cached after first
    assert monitor.stat_get("executor/runs") == 3
