"""C-ABI predictor (VERDICT r03 item 9 / N32 client story; reference
inference/capi/, go/paddle/predictor.go): build libpaddle_tpu_capi.so,
compile a real C client against the public header, run it in a fresh
process over a jit.save artifact, and check its output matches the
in-process Python Predictor bit for bit (f32)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    d = tmp_path_factory.mktemp("capi_model")
    paddle.seed(7)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    net.eval()
    prefix = str(d / "model")
    from paddle_tpu import jit
    from paddle_tpu.hapi.model import InputSpec
    jit.save(net, prefix, input_spec=[InputSpec([2, 4], "float32")])
    x = np.random.RandomState(0).randn(2, 4).astype("float32")
    from paddle_tpu import inference
    pred = inference.create_predictor(inference.Config(prefix))
    (ref,) = pred.run([x])
    return prefix, x, ref


def test_capi_from_c_client(artifact, tmp_path):
    prefix, x, ref = artifact
    from paddle_tpu._native import build_capi, capi_header
    so = build_capi()

    c_src = textwrap.dedent(r"""
        #include <stdio.h>
        #include <stdlib.h>
        #include "paddle_tpu_capi.h"

        int main(int argc, char** argv) {
            PD_Predictor* p = PD_NewPredictor(argv[1], "");
            if (!p) { fprintf(stderr, "create: %s\n", PD_GetLastError());
                      return 2; }
            float in[8];
            FILE* f = fopen(argv[2], "rb");
            if (fread(in, sizeof(float), 8, f) != 8) return 3;
            fclose(f);
            const void* bufs[1] = {in};
            int dtypes[1] = {PD_DTYPE_FLOAT32};
            int64_t shape[2] = {2, 4};
            const int64_t* shapes[1] = {shape};
            int ndims[1] = {2};
            if (PD_PredictorRun(p, bufs, dtypes, shapes, ndims, 1)) {
                fprintf(stderr, "run: %s\n", PD_GetLastError());
                return 4;
            }
            int n = PD_PredictorNumOutputs(p);
            printf("%d\n", n);
            for (int i = 0; i < n; i++) {
                const float* data; const int64_t* oshape; int ondim;
                PD_PredictorOutput(p, i, &data, &oshape, &ondim);
                long long numel = 1;
                for (int d = 0; d < ondim; d++) {
                    printf("%lld ", (long long)oshape[d]);
                    numel *= oshape[d];
                }
                printf("\n");
                for (long long k = 0; k < numel; k++)
                    printf("%.9g\n", data[k]);
            }
            PD_DeletePredictor(p);
            return 0;
        }
    """)
    csrc = tmp_path / "client.c"
    csrc.write_text(c_src)
    exe = tmp_path / "client"
    import sysconfig
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION")
    cmd = ["gcc", "-O1", str(csrc), "-o", str(exe),
           f"-I{os.path.dirname(capi_header())}", so,
           f"-Wl,-rpath,{os.path.dirname(so)}"]
    if libdir:
        cmd += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    cmd += [f"-lpython{ver}", "-ldl", "-lm"]
    subprocess.run(cmd, check=True, capture_output=True)

    xfile = tmp_path / "x.bin"
    xfile.write_bytes(np.ascontiguousarray(x).tobytes())
    env = {**os.environ, "PYTHONPATH": f"{os.environ.get('PYTHONPATH', '')}"
           f":{REPO}", "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    r = subprocess.run([str(exe), prefix, str(xfile)], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"C client failed: {r.stderr}\n{r.stdout}"
    lines = r.stdout.split()
    n = int(lines[0])
    assert n == 1
    shape = (int(lines[1]), int(lines[2]))
    vals = np.array([float(v) for v in lines[3:3 + shape[0] * shape[1]]],
                    np.float32).reshape(shape)
    np.testing.assert_allclose(vals, ref, rtol=1e-6, atol=1e-7)


def test_capi_reports_errors(tmp_path):
    """Bad model prefix surfaces through PD_GetLastError, not a crash."""
    import ctypes

    from paddle_tpu._native import build_capi
    lib = ctypes.CDLL(build_capi())
    lib.PD_NewPredictor.restype = ctypes.c_void_p
    lib.PD_NewPredictor.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.PD_GetLastError.restype = ctypes.c_char_p
    h = lib.PD_NewPredictor(str(tmp_path / "nope").encode(), b"")
    assert not h
    assert b"pdinfer" in lib.PD_GetLastError() or \
        b"not found" in lib.PD_GetLastError()


# ---- C train API (N33; reference train/demo/demo_trainer.cc) -------------

def test_capi_trainer_from_c_client(tmp_path):
    """A real C host trains the linear-regression program: loss must
    decrease across steps and params must persist."""
    from paddle_tpu import static, optimizer
    paddle.enable_static()
    main = static.Program("capi_train")
    with static.program_guard(main):
        x = static.data("x", [-1, 3], "float32")
        y = static.data("y", [-1, 1], "float32")
        net = nn.Linear(3, 1, bias_attr=False)
        loss = paddle.ops.mse_loss(net(x), y)
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    paddle.disable_static()

    from paddle_tpu.static import capi_train
    art = str(tmp_path / "train.pdprog")
    capi_train.save_train_program(main, art)

    rng = np.random.RandomState(0)
    X = rng.rand(64, 3).astype("float32")
    W = rng.randn(3, 1).astype("float32")
    Y = X @ W
    (tmp_path / "x.bin").write_bytes(X.tobytes())
    (tmp_path / "y.bin").write_bytes(Y.tobytes())

    from paddle_tpu._native import build_capi, capi_header
    so = build_capi()
    c_src = textwrap.dedent(r"""
        #include <stdio.h>
        #include <stdlib.h>
        #include "paddle_tpu_capi.h"

        int main(int argc, char** argv) {
            PD_Trainer* t = PD_NewTrainer(argv[1]);
            if (!t) { fprintf(stderr, "new: %s\n", PD_GetLastError());
                      return 2; }
            static float X[64*3], Y[64];
            FILE* f = fopen(argv[2], "rb");
            if (fread(X, 4, 64*3, f) != 64*3) return 3;
            fclose(f);
            f = fopen(argv[3], "rb");
            if (fread(Y, 4, 64, f) != 64) return 3;
            fclose(f);
            const void* bufs[2] = {X, Y};
            int dtypes[2] = {PD_DTYPE_FLOAT32, PD_DTYPE_FLOAT32};
            int64_t sx[2] = {64, 3}, sy[2] = {64, 1};
            const int64_t* shapes[2] = {sx, sy};
            int ndims[2] = {2, 2};
            float first = 0, last = 0;
            for (int i = 0; i < 400; i++) {
                float loss;
                if (PD_TrainerRunStep(t, bufs, dtypes, shapes, ndims, 2,
                                      &loss)) {
                    fprintf(stderr, "step: %s\n", PD_GetLastError());
                    return 4;
                }
                if (i == 0) first = loss;
                last = loss;
            }
            printf("%.9g %.9g\n", first, last);
            if (PD_TrainerSave(t, argv[4])) {
                fprintf(stderr, "save: %s\n", PD_GetLastError());
                return 5;
            }
            PD_DeleteTrainer(t);
            return 0;
        }
    """)
    csrc = tmp_path / "train_client.c"
    csrc.write_text(c_src)
    exe = tmp_path / "train_client"
    import sysconfig
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION")
    cmd = ["gcc", "-O1", str(csrc), "-o", str(exe),
           f"-I{os.path.dirname(capi_header())}", so,
           f"-Wl,-rpath,{os.path.dirname(so)}"]
    if libdir:
        cmd += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    cmd += [f"-lpython{ver}", "-ldl", "-lm"]
    subprocess.run(cmd, check=True, capture_output=True)

    env = {**os.environ, "PYTHONPATH": f"{os.environ.get('PYTHONPATH', '')}"
           f":{REPO}", "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    out_params = str(tmp_path / "trained")
    r = subprocess.run(
        [str(exe), art, str(tmp_path / "x.bin"), str(tmp_path / "y.bin"),
         out_params], env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"C trainer failed: {r.stderr}\n{r.stdout}"
    first, last = (float(v) for v in r.stdout.split())
    assert last < first * 0.05, (first, last)
    # saved params load back and are near the true W
    from paddle_tpu.framework.io import load as fload
    state = fload(out_params + ".pdparams")
    w = next(iter(state.values()))
    np.testing.assert_allclose(np.asarray(w), W, atol=0.25)
