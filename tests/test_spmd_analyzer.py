"""SPMD sharding analyzer (ISSUE 3 tentpole).

Golden paths: the GPT tensor-parallel config must resolve a spec for
every var with ZERO diagnostics and exactly the expected collective set
(qkv column-parallel -> out-proj row-parallel -> one all-reduce per
chain, one per MLP down-proj, one vocab-parallel embedding gather), and
the per-device HBM estimate must shrink accordingly.

Negative corpus: one deliberately broken program per diagnostic in
DIAGNOSTIC_CODES (mirroring the PR-1 verifier corpus), plus the
PADDLE_TPU_VERIFY_SPMD hook failing compilation BEFORE jit.
"""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn, ops, static
from paddle_tpu.core import monitor
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed import sharding
from paddle_tpu.static import spmd_analyzer as spmd
from paddle_tpu.static.spmd_analyzer import (DIAGNOSTIC_CODES,
                                             SpmdLintError,
                                             analyze_params,
                                             analyze_program)

MESH = {"dp": 2, "tp": 2}


@pytest.fixture()
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


@pytest.fixture()
def tp_mesh():
    """A registered tp=2 mesh that IS the default for the test's
    duration (the VERIFY_SPMD hook reads the default mesh), restoring
    whatever default another test left behind."""
    with mesh_mod._lock:
        old = mesh_mod._default_name
    m = mesh_mod.init_mesh({"tp": 2}, name="_spmd_hook_test")
    mesh_mod.set_mesh(m, "_spmd_hook_test")
    yield m
    mesh_mod.reset_mesh("_spmd_hook_test")
    with mesh_mod._lock:
        if old in mesh_mod._meshes:
            mesh_mod._default_name = old


def _linear_program(in_f=8, out_f=4, batch=4):
    main = static.Program("lin")
    with static.program_guard(main):
        x = static.data("x", [batch, in_f], "float32")
        net = nn.Linear(in_f, out_f)
        y = net(x)
    main._jit_fetch_vars = [y]
    return main, net, y


# ---------------------------------------------------------------------------
# golden paths
# ---------------------------------------------------------------------------

def _gpt_program(layers=2):
    from paddle_tpu.text.models.gpt import GPT, GPTConfig
    main = static.Program("gpt")
    with static.program_guard(main):
        ids = static.data("input_ids", [2, 16], "int64")
        net = GPT(GPTConfig(vocab_size=1024, hidden_size=64,
                            num_layers=layers, num_heads=2,
                            intermediate_size=128, max_seq_len=32))
        logits = net(ids)
    main._jit_fetch_vars = [logits]
    return main, net, logits


def test_gpt_tp_golden_path(static_mode):
    layers = 2
    main, net, logits = _gpt_program(layers)
    specs = sharding.named_param_specs(net, {"tp": 2})
    rep = analyze_program(main, mesh={"tp": 2}, param_specs=specs)

    assert rep.diagnostics == [], "\n".join(str(d) for d in rep.diagnostics)
    # every var resolved a spec
    for op in main.ops:
        for oid in op.out_ids:
            assert oid in rep.specs
    ar = [c for c in rep.collectives if c.kind == "all_reduce"]
    # 1 vocab-parallel embedding gather + per block: out-proj + fc2
    assert len(ar) == 2 * layers + 1
    assert all(c.axis == "tp" for c in ar)
    assert ar[0].op_name == "embedding"
    assert all(c.op_name == "matmul" for c in ar[1:])
    # no resharding anywhere, and nothing else on the wire
    assert [c for c in rep.collectives if c.kind != "all_reduce"] == []
    # tied LM head stays column-parallel: logits sharded on vocab
    assert rep.spec_of(logits) == ((), (), ("tp",))
    # per-device HBM strictly below the replicated estimate
    assert rep.hbm["peak_bytes"] < rep.hbm_replicated["peak_bytes"]
    assert rep.hbm["param_bytes"] < rep.hbm_replicated["param_bytes"]


def test_gpt_block_qkv_column_then_rowparallel_one_allreduce(static_mode):
    """The attention chain: qkv column-parallel produces NO collective;
    the row-parallel out-proj implies exactly one all-reduce."""
    from paddle_tpu.text.models.gpt import GPTBlock, GPTConfig
    main = static.Program("blk")
    with static.program_guard(main):
        x = static.data("x", [2, 16, 64], "float32")
        blk = GPTBlock(GPTConfig.tiny())
        y = blk(x)
    main._jit_fetch_vars = [y]
    specs = sharding.named_param_specs(blk, {"tp": 2})
    rep = analyze_program(main, mesh={"tp": 2}, param_specs=specs)
    assert rep.diagnostics == [], "\n".join(str(d) for d in rep.diagnostics)
    ar = [c for c in rep.collectives if c.kind == "all_reduce"]
    assert len(ar) == 2  # attn out-proj + mlp fc2
    assert all(c.axis == "tp" and c.op_name == "matmul" for c in ar)
    # the FIRST matmul (qkv column-parallel) implied nothing: both
    # all-reduces come later in the op list
    first_mm = next(i for i, op in enumerate(main.ops)
                    if op.name == "matmul")
    assert all(c.op_index > first_mm for c in ar)
    # block output is replicated (ready for the residual stream)
    assert rep.spec_of(y) == ((), (), ())


def test_dp_batch_sharding_propagates(static_mode):
    main, net, y = _linear_program()
    rep = analyze_program(main, mesh=MESH, data_specs={"x": P("dp")})
    assert rep.diagnostics == []
    assert rep.collectives == []  # pure DP forward: no comm implied
    assert rep.spec_of(y)[0] == ("dp",)


def test_analyze_params_dygraph_gpt():
    from paddle_tpu.text.models.gpt import GPT, GPTConfig
    layers = 2
    net = GPT(GPTConfig.tiny())
    rep = analyze_params(dict(net.named_parameters()), mesh={"tp": 2},
                         tokens_per_step=2 * 16)
    assert rep.diagnostics == []
    ar = [c for c in rep.collectives if c.kind == "all_reduce"]
    assert len(ar) == 2 * layers + 1  # out_proj + fc2 per block, + wte
    assert all(c.axis == "tp" for c in ar)
    assert all(c.bytes > 0 for c in ar)
    # per-device param bytes beat full replication
    full = sum(int(np.prod(p.shape)) * 4 for _, p in net.named_parameters())
    assert rep.hbm["param_bytes"] < full


# ---------------------------------------------------------------------------
# the broken corpus: one program per diagnostic
# ---------------------------------------------------------------------------

def test_corpus_unbound_axis(static_mode):
    main, net, _ = _linear_program()
    rep = analyze_program(main, mesh=MESH, param_specs={
        net.weight.scope_name: P("mp", None)})
    assert [d.code for d in rep.diagnostics] == ["unbound-axis"]
    d = rep.diagnostics[0]
    assert d.axis == "mp" and d.var == net.weight.scope_name
    assert "mp" in d.message and "dp" in d.message


def test_corpus_duplicate_axis(static_mode):
    main, net, _ = _linear_program()
    rep = analyze_program(main, mesh=MESH, param_specs={
        net.weight.scope_name: P("tp", "tp")})
    assert "duplicate-axis" in [d.code for d in rep.diagnostics]
    d = next(x for x in rep.diagnostics if x.code == "duplicate-axis")
    assert d.axis == "tp"


def test_corpus_non_divisible(static_mode):
    main, net, _ = _linear_program(in_f=7)
    rep = analyze_program(main, mesh=MESH, param_specs={
        net.weight.scope_name: P("tp", None)})
    assert [d.code for d in rep.diagnostics] == ["non-divisible"]
    assert "7" in rep.diagnostics[0].message


def test_corpus_spec_rank(static_mode):
    main, net, _ = _linear_program()
    rep = analyze_program(main, mesh=MESH, param_specs={
        net.bias.scope_name: P(None, "tp")})
    assert [d.code for d in rep.diagnostics] == ["spec-rank"]
    assert net.bias.scope_name == rep.diagnostics[0].var


def test_corpus_reshard_one_sided_contraction(static_mode):
    """A column-parallel activation fed into a replicated weight: the
    contraction dim is sharded on one operand only — implicit all-gather,
    reported with its byte cost."""
    main, net, _ = _linear_program()
    rep = analyze_program(main, mesh=MESH, data_specs={"x": P(None, "tp")})
    assert [d.code for d in rep.diagnostics] == ["reshard"]
    ag = [c for c in rep.collectives if c.kind == "all_gather"]
    assert len(ag) == 1 and ag[0].axis == "tp"
    assert ag[0].bytes == 4 * 8 * 4  # the gathered activation, f32


def test_corpus_collective_divergence_across_cond(static_mode):
    main = static.Program("cf")
    with static.program_guard(main):
        x = static.data("x", [4, 8], "float32")
        yv = static.data("y", [4, 4], "float32")
        w = nn.Linear(8, 4, bias_attr=False)
        pred = ops.less_than(ops.sum(yv), ops.full([], 100.0, "float32"))
        out = static.nn.cond(pred, lambda: ops.matmul(x, w.weight),
                             lambda: ops.exp(yv))
    main._jit_fetch_vars = [out]
    rep = analyze_program(main, mesh=MESH,
                          param_specs={w.weight.scope_name: P("tp", None)},
                          data_specs={"x": P(None, "tp")})
    codes = [d.code for d in rep.diagnostics]
    assert "collective-divergence" in codes
    d = next(x for x in rep.diagnostics
             if x.code == "collective-divergence")
    assert d.op_name == "cond" and "all_reduce" in d.message


def test_corpus_reshard_contraction_on_different_axes(static_mode):
    """Contraction sharded on DIFFERENT axes on each operand: both sides
    must be gathered (and counted) — the output cannot be replicated for
    free."""
    main = static.Program("xx")
    with static.program_guard(main):
        x = static.data("x", [4, 8], "float32")
        w = nn.Linear(8, 4, bias_attr=False)
        y = ops.matmul(x, w.weight)
    main._jit_fetch_vars = [y]
    rep = analyze_program(main, mesh=MESH,
                          param_specs={w.weight.scope_name: P("tp", None)},
                          data_specs={"x": P(None, "dp")})
    assert [d.code for d in rep.diagnostics] == ["reshard"]
    assert "DIFFERENT axes" in rep.diagnostics[0].message
    ag = sorted(c.axis for c in rep.collectives if c.kind == "all_gather")
    assert ag == ["dp", "tp"]  # BOTH operands gathered, both counted


def test_while_loop_with_literal_carry_and_inner_collective(static_mode):
    """A plain-int loop carry must not crash propagation, and a
    row-parallel matmul inside the body is counted once with a
    path-qualified op name."""
    main = static.Program("wl")
    with static.program_guard(main):
        x = static.data("x", [4, 8], "float32")
        w = nn.Linear(8, 8, bias_attr=False)
        n = ops.full([], 3, "int32")
        _, acc = static.nn.while_loop(
            lambda i, a: ops.less_than(i, n),
            lambda i, a: (i + 1, ops.matmul(a, w.weight)),
            [ops.zeros([], "int32"), x])
    main._jit_fetch_vars = [acc]
    rep = analyze_program(main, mesh=MESH,
                          param_specs={w.weight.scope_name: P("tp", None)},
                          data_specs={"x": P(None, "tp")})
    assert rep.diagnostics == []
    ar = [c for c in rep.collectives if c.kind == "all_reduce"]
    assert len(ar) == 1 and ar[0].axis == "tp"
    assert "while_loop#" in ar[0].op_name and "body" in ar[0].op_name


def test_corpus_covers_every_diagnostic_code():
    """Meta-test: the suite above exercises the full catalogue."""
    import inspect
    import sys
    src = inspect.getsource(sys.modules[__name__])
    for code in DIAGNOSTIC_CODES:
        assert f'"{code}"' in src or f"'{code}'" in src


# ---------------------------------------------------------------------------
# the PADDLE_TPU_VERIFY_SPMD hook + monitor gauges
# ---------------------------------------------------------------------------

def test_verify_spmd_env_flag(monkeypatch):
    spmd.set_verify_spmd(None)
    monkeypatch.setenv("PADDLE_TPU_VERIFY_SPMD", "0")
    assert not spmd.verify_spmd_enabled()
    monkeypatch.setenv("PADDLE_TPU_VERIFY_SPMD", "1")
    assert spmd.verify_spmd_enabled()


def test_hook_fails_compilation_before_jit(static_mode, tp_mesh,
                                           monkeypatch):
    """An injected unbound-axis/non-divisible spec must raise at the
    Executor's compile step — before lowering — not at run time."""
    monkeypatch.setenv("PADDLE_TPU_VERIFY_SPMD", "1")
    for bad, code in ((P("mp", None), "unbound-axis"),
                      (P("tp", None), "non-divisible")):
        main, net, y = _linear_program(in_f=7)
        main.spmd_param_specs = {net.weight.scope_name: bad}
        exe = static.Executor()
        before = monitor.stat_get("executor/lowerings")
        with pytest.raises(SpmdLintError) as e:
            exe.run(main, feed={"x": np.ones((4, 7), "float32")},
                    fetch_list=[y])
        assert e.value.code == code
        # nothing was lowered: the finding preceded jit compilation
        assert monitor.stat_get("executor/lowerings") == before


def test_hook_in_apply_pass(static_mode, tp_mesh, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_VERIFY_SPMD", "1")
    main, net, _ = _linear_program()
    main.spmd_param_specs = {net.weight.scope_name: P("zz", None)}
    from paddle_tpu.static.passes import apply_pass
    with pytest.raises(SpmdLintError, match="unbound-axis"):
        apply_pass(main, "eliminate_dead_ops")


def test_hook_clean_program_passes_and_publishes_gauges(static_mode,
                                                        tp_mesh,
                                                        monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_VERIFY_SPMD", "1")
    main, net, y = _linear_program()
    main.spmd_param_specs = {
        net.weight.scope_name: P(None, "tp"),
        net.bias.scope_name: P("tp")}
    exe = static.Executor()
    (out,) = exe.run(main, feed={"x": np.ones((4, 8), "float32")},
                     fetch_list=[y])
    assert out.shape == (4, 4)
    gauges = monitor.stats("spmd.")
    assert gauges["spmd.hbm_estimate"] > 0
    assert gauges["spmd.resharding_count"] == 0


def test_gauges_reflect_collective_bytes(static_mode):
    main, net, _ = _linear_program()
    rep = analyze_program(main, mesh=MESH, param_specs={
        net.weight.scope_name: P("tp", None)},
        data_specs={"x": P(None, "tp")})  # row-parallel TP: one all-reduce
    assert rep.diagnostics == []
    rep.publish()
    assert monitor.stat_get("spmd.collective_bytes") \
        == rep.collective_bytes() > 0


# ---------------------------------------------------------------------------
# satellites: sharding._validate_divisible, MeshGuard, in_spmd_region,
# pipeline schedule accounting
# ---------------------------------------------------------------------------

def test_validate_divisible_counts_and_rejects_long_specs():
    import jax
    mesh = mesh_mod.init_mesh({"dp": 2}, name="vd_test")
    try:
        before = monitor.stat_get("sharding.nondivisible_fallback")
        spec = sharding._validate_divisible(P("dp"), (5,), mesh)
        assert tuple(spec) == (None,)  # fallback preserved...
        assert monitor.stat_get("sharding.nondivisible_fallback") \
            == before + 1  # ...but no longer silent
        # divisible dims don't count
        spec = sharding._validate_divisible(P("dp"), (6,), mesh)
        assert tuple(spec) == ("dp",)
        assert monitor.stat_get("sharding.nondivisible_fallback") \
            == before + 1
        # a spec longer than the tensor's rank used to be zip-truncated
        with pytest.raises(ValueError, match="entries"):
            sharding._validate_divisible(P(None, "dp"), (6,), mesh,
                                         name="w")
    finally:
        mesh_mod.reset_mesh("vd_test")


def test_meshguard_without_mesh_names_registry():
    mesh_mod.reset_mesh("definitely_absent")
    with pytest.raises(RuntimeError) as e:
        mesh_mod.MeshGuard(name="definitely_absent").__enter__()
    msg = str(e.value)
    assert "definitely_absent" in msg and "init_mesh" in msg


def test_meshguard_with_mesh_still_works():
    m = mesh_mod.init_mesh({"dp": 1}, name="mg_ok")
    try:
        with mesh_mod.MeshGuard(name="mg_ok") as got:
            assert got is m
    finally:
        mesh_mod.reset_mesh("mg_ok")


def _probe_spmd_region():
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh
    import jax.numpy as jnp
    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
    seen = {}

    def f():
        seen["dp"] = mesh_mod.in_spmd_region("dp")
        seen["zz"] = mesh_mod.in_spmd_region("zz")
        seen["any"] = mesh_mod.in_spmd_region()
        return jnp.zeros(())

    jax.jit(shard_map(f, mesh=mesh, in_specs=(), out_specs=P()))()
    return seen


def test_in_spmd_region_private_path():
    assert not mesh_mod.in_spmd_region("dp")  # outside any shard_map
    seen = _probe_spmd_region()
    assert seen == {"dp": True, "zz": False, "any": True}


def test_in_spmd_region_public_fallback(monkeypatch):
    """When the private jax accessor vanishes (version drift), the
    public-API probe must still answer CORRECTLY — not silently False."""
    def gone():
        raise ImportError("jax moved the private axis env")

    monkeypatch.setattr(mesh_mod, "_axis_env_names", gone)
    mesh_mod.init_mesh({"dp": 1}, name="fb_test")  # feeds axis=None probe
    try:
        assert not mesh_mod.in_spmd_region("dp")
        seen = _probe_spmd_region()
        assert seen == {"dp": True, "zz": False, "any": True}
    finally:
        mesh_mod.reset_mesh("fb_test")


def test_pipeline_schedule_collectives():
    from paddle_tpu.distributed.pipeline import (schedule_collectives,
                                                 schedule_ticks)
    pc = schedule_collectives(8, 4, hidden_bytes=1024)
    assert pc["kind"] == "ppermute" and pc["axis"] == "pp"
    assert pc["count"] == schedule_ticks(8, 4) == 11
    assert pc["total_bytes"] == 11 * 1024
    pcv = schedule_collectives(8, 4, 1024, schedule="interleaved",
                               num_virtual=2)
    assert pcv["count"] == 2 * 8 + 4 - 1


# ---------------------------------------------------------------------------
# satellites (ISSUE 10): collective dtype dimension + quantized savings,
# cross-dim duplicate-axis pricing, add_tp_rule callable/rank validation
# ---------------------------------------------------------------------------

def test_collective_dtype_recorded_and_bytes_if():
    """Every collective carries its wire dtype; bytes_if re-prices the
    payload under a narrower cast (the EQuARX quantized seam)."""
    paddle.enable_static()
    try:
        main, net, _ = _linear_program()
        rep = analyze_program(main, mesh=MESH, param_specs={
            net.weight.scope_name: P("tp", None)},
            data_specs={"x": P(None, "tp")})  # row-parallel: 1 all-reduce
        assert rep.diagnostics == []
        (ar,) = [c for c in rep.collectives if c.kind == "all_reduce"]
        assert ar.dtype == "float32" and ar.is_float
        assert ar.bytes_if("int8") == ar.bytes // 4
        assert ar.bytes_if("float16") == ar.bytes // 2
        assert ar.bytes_if("float32") == ar.bytes
        # fp8 wire dtypes live in ml_dtypes, not numpy proper — the
        # EQuARX fp8 seam must price, not TypeError out of np.dtype
        assert ar.bytes_if("float8_e4m3fn") == ar.bytes // 4
        assert ar.bytes_if("float8_e5m2") == ar.bytes // 4
    finally:
        paddle.disable_static()


def test_quantized_savings_per_axis_in_render(static_mode):
    from paddle_tpu.text.models.gpt import GPT, GPTConfig
    main = static.Program("q")
    with static.program_guard(main):
        ids = static.data("input_ids", [2, 16], "int64")
        net = GPT(GPTConfig.tiny())
        logits = net(ids)
    main._jit_fetch_vars = [logits]
    specs = sharding.named_param_specs(net, {"tp": 2})
    rep = analyze_program(main, mesh={"tp": 2}, param_specs=specs)
    savings = rep.quantized_savings("int8")
    assert set(savings) == {"tp"}
    row = savings["tp"]
    assert row["bytes"] == rep.collective_bytes() > 0
    assert row["bytes_quantized"] == row["bytes"] // 4  # all-f32 wire
    assert row["saved"] == row["bytes"] - row["bytes_quantized"]
    out = rep.render()
    assert "int8/fp8 quantized collectives would save" in out
    assert f"saves {row['saved']} B" in out


def test_matmul_output_axis_collision_is_priced(static_mode):
    """dp-sharded batch meeting a dp-column-sharded weight: the axis
    cannot shard two output dims — must surface as a PRICED reshard,
    not a silently free drop (the planner would otherwise exploit it)."""
    main, net, y = _linear_program()
    rep = analyze_program(main, mesh=MESH, param_specs={
        net.weight.scope_name: P(None, "dp")},
        data_specs={"x": P("dp")})
    assert "reshard" in [d.code for d in rep.diagnostics]
    d = next(x for x in rep.diagnostics if x.code == "reshard")
    assert "cannot shard two" in d.message and d.axis == "dp"
    ag = [c for c in rep.collectives if c.kind == "all_gather"]
    assert len(ag) == 1 and ag[0].axis == "dp" and ag[0].bytes > 0
    # batch keeps dp; the weight's column sharding lost
    assert rep.spec_of(y) == (("dp",), ())


def test_embedding_vocab_axis_colliding_with_ids_is_priced(static_mode):
    main = static.Program("emb")
    with static.program_guard(main):
        ids = static.data("ids", [4, 8], "int64")
        emb = nn.Embedding(16, 6)
        out = emb(ids)
    main._jit_fetch_vars = [out]
    rep = analyze_program(main, mesh=MESH, param_specs={
        emb.weight.scope_name: P("dp", None)},
        data_specs={"ids": P("dp")})
    codes = [d.code for d in rep.diagnostics]
    assert codes == ["reshard"]
    assert "vocab-sharded" in rep.diagnostics[0].message
    ag = [c for c in rep.collectives if c.kind == "all_gather"]
    assert len(ag) == 1 and ag[0].axis == "dp"
    assert [c for c in rep.collectives if c.kind == "all_reduce"] == []


def test_add_tp_rule_accepts_callable_and_validates_rank():
    meshlike = sharding.mesh_like({"tp": 2})
    # a callable rule serves multiple ranks from one template
    sharding.add_tp_rule(r"my_head\.weight$",
                         lambda ndim: P(*([None] * (ndim - 1) + ["tp"])))
    try:
        assert sharding.param_spec_for("my_head.weight", 2, meshlike) \
            == P(None, "tp")
        assert sharding.param_spec_for("my_head.weight", 3, meshlike) \
            == P(None, None, "tp")
    finally:
        assert sharding.remove_tp_rule(r"my_head\.weight$") == 1
    # a fixed over-rank spec fails AT MATCH TIME, naming the rule —
    # not as a spec-rank crash downstream
    sharding.add_tp_rule(r"tiny\.bias$", P("tp", None))
    try:
        with pytest.raises(ValueError, match="rank-1 param 'tiny.bias'"):
            sharding.param_spec_for("tiny.bias", 1, meshlike)
        # matching rank still works
        assert sharding.param_spec_for("tiny.bias", 2, meshlike) \
            == P("tp", None)
    finally:
        assert sharding.remove_tp_rule(r"tiny\.bias$") == 1


# ---------------------------------------------------------------------------
# two-tier topology: per-tier pricing + the cross-tier diagnostic
# ---------------------------------------------------------------------------

TIERED_MESH = {"pod": {"size": 2, "tier": "dcn"}, "dp": 2, "tp": 2}


def _tiered_gpt(batch=4):
    from paddle_tpu.text.models.gpt import GPT, GPTConfig
    main = static.Program("gpt_tiered")
    with static.program_guard(main):
        ids = static.data("input_ids", [batch, 16], "int64")
        net = GPT(GPTConfig(vocab_size=1024, hidden_size=64,
                            num_layers=2, num_heads=2,
                            intermediate_size=128, max_seq_len=32))
        logits = net(ids)
    main._jit_fetch_vars = [logits]
    return main, net, logits


def test_tiered_mesh_prices_collectives_per_link(static_mode):
    """Declaring link tiers adds tier/cost_us to every collective and a
    per-tier wire-bytes rollup; the good layout (tp intra-pod, batch
    DCN-major on (pod, dp)) carries ZERO diagnostics — the loss-free
    pure-dp crossing is exempt from cross-tier by design."""
    main, net, _ = _tiered_gpt()
    specs = sharding.named_param_specs(net, TIERED_MESH)
    rep = spmd.analyze_program(main, mesh=TIERED_MESH, param_specs=specs,
                               data_specs={"input_ids": P(("pod", "dp"))})
    assert rep.diagnostics == []
    assert rep.mesh_tiers["pod"]["tier"] == "dcn"
    assert rep.mesh_tiers["tp"]["tier"] == "ici"
    ars = [c for c in rep.collectives if c.kind == "all_reduce"]
    assert ars and all(c.tier == "ici" for c in ars)  # tp stays intra-pod
    assert all(c.cost_us > 0 for c in ars)
    tiers = rep.tier_bytes()
    assert tiers.get("ici", 0) == sum(c.bytes for c in rep.collectives
                                      if c.tier == "ici")
    assert "link tiers: pod=dcn" in rep.render()


def test_cross_tier_diagnostic_for_model_parallel_on_dcn(static_mode):
    """A persistable sharded over the slow axis (model parallelism
    crossing pods) raises cross-tier, naming op/var/axis; the same
    layout on a flat mesh does not."""
    main, net, _ = _tiered_gpt()
    specs = sharding.named_param_specs(net, TIERED_MESH)
    specs[net.wte.weight.scope_name] = P("pod", None)  # vocab over DCN
    rep = spmd.analyze_program(main, mesh=TIERED_MESH, param_specs=specs,
                               data_specs={"input_ids": P("dp")})
    xt = [d for d in rep.diagnostics if d.code == "cross-tier"]
    assert xt and xt[0].axis == "pod" and xt[0].var
    assert "slow-tier" in xt[0].message
    # flat mesh, same shapes: no tiers -> no cross-tier, identical render
    flat = {"pod": 2, "dp": 2, "tp": 2}
    rep2 = spmd.analyze_program(main, mesh=flat, param_specs=specs,
                                data_specs={"input_ids": P("dp")})
    assert rep2.mesh_tiers == {}
    assert [d for d in rep2.diagnostics if d.code == "cross-tier"] == []
    assert "link tiers" not in rep2.render()


def test_hierarchical_sync_wire_model(static_mode):
    """The dp gradient-sync pricing: hierarchical ships exactly 1/n of
    the flat inter-pod bytes (n = intra-pod dp size); localsgd divides
    the whole sync by k; the recommendation follows the cost ratio."""
    main, net, _ = _tiered_gpt()
    specs = sharding.named_param_specs(net, TIERED_MESH)
    rep = spmd.analyze_program(main, mesh=TIERED_MESH, param_specs=specs,
                               data_specs={"input_ids": P(("pod", "dp"))})
    B = 4096
    gs = rep.hierarchical_sync(grad_bytes=B)
    assert gs["inner"] == {"axes": ["dp"], "size": 2}
    assert gs["outer"] == {"axes": ["pod"], "size": 2}
    ring = lambda b, s: int(2 * b * (s - 1) // s)  # noqa: E731
    sch = gs["schemes"]
    assert sch["flat"]["wire_bytes"] == {"ici": ring(B, 2),
                                         "dcn": ring(B, 2)}
    assert sch["hierarchical"]["wire_bytes"] == {"ici": ring(B, 2),
                                                 "dcn": ring(B // 2, 2)}
    assert sch["localsgd"]["wire_bytes"]["dcn"] == ring(B, 2) // 4
    assert gs["inter_pod_reduction_x"] == 2.0
    assert gs["recommendation"] == "hierarchical"
    # per-step DCN cost dominates ICI by the bandwidth gap / shard ratio
    assert sch["flat"]["cost_us"]["dcn"] > sch["flat"]["cost_us"]["ici"]
    # flat mesh: nothing to decompose
    rep2 = spmd.analyze_program(main, mesh={"dp": 2, "tp": 2},
                                param_specs=sharding.named_param_specs(
                                    net, {"dp": 2, "tp": 2}),
                                data_specs={"input_ids": P("dp")})
    assert rep2.hierarchical_sync() is None
