"""paddle.save / paddle.load.

Analog of reference python/paddle/fluid/dygraph/checkpoint.py (save_dygraph /
load_dygraph) and framework/save_load_util.cc tensor serialization. Format:
a single pickle file whose tensor leaves are numpy arrays plus a small
header recording the framework version — step-atomic (write temp + rename),
matching the reference's save-op semantics (operators/save_op.cc).
Multi-host sharded checkpointing lives in paddle_tpu.incubate.checkpoint
(orbax-backed).
"""
from __future__ import annotations

import os
import pickle
import tempfile

import numpy as np

from ..core.tensor import Tensor

_MAGIC = "paddle_tpu.checkpoint.v1"


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return _NDArrayLeaf(np.asarray(obj._value), True)
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):  # jax/np array
        return _NDArrayLeaf(np.asarray(obj), False)
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_serializable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


class _NDArrayLeaf:
    __slots__ = ("array", "was_tensor")

    def __init__(self, array, was_tensor):
        self.array = array
        self.was_tensor = was_tensor


def _from_serializable(obj, return_numpy=False):
    if isinstance(obj, _NDArrayLeaf):
        if return_numpy or not obj.was_tensor:
            return obj.array
        return Tensor(obj.array)
    if isinstance(obj, dict):
        return {k: _from_serializable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_serializable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4):
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    payload = {"magic": _MAGIC, "data": _to_serializable(obj)}
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(payload, f, protocol=protocol)
        os.replace(tmp, path)  # atomic publish
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path, return_numpy=False):
    with open(path, "rb") as f:
        payload = pickle.load(f)
    if not (isinstance(payload, dict) and payload.get("magic") == _MAGIC):
        return payload  # foreign pickle; hand back as-is
    return _from_serializable(payload["data"], return_numpy)
