"""Versioned, schema-based Program serialization.

Replaces pickle as the .pdmodel format (VERDICT r04 item 4). Reference
analogs: framework/framework.proto (ProgramDesc + the op-version map at
framework.proto:186) and framework/save_load_util.cc (versioned tensor
headers). Design delta: instead of protobuf, the graph is a JSON document
(ops referenced BY REGISTRY NAME + version, attrs as JSON values, variable
metadata inline) plus one .npz holding every baked array constant — so a
saved model survives internal module renames (nothing resolves by
qualname), loads across framework versions with an explicit op-version
check, and stays hand-inspectable.

Layout for save_program(path):
  {path}.pdmodel      JSON document (format_version, op version map, ops,
                      vars, feeds/fetches)
  {path}.pdmodel.npz  array constants, keyed c0, c1, ...

Control-flow ops (cond/while) serialize structurally: their SubBlocks are
nested op lists in the same schema.
"""
from __future__ import annotations

import json

import numpy as np

__all__ = ["save_program", "load_program", "FORMAT_VERSION",
           "OpVersionError"]

FORMAT_VERSION = 1


class OpVersionError(RuntimeError):
    pass


def _op_version(name):
    from ..ops import OP_REGISTRY
    fn = OP_REGISTRY.get(name)
    return int(getattr(fn, "op_version", 1)) if fn is not None else None


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

class _Encoder:
    def __init__(self):
        self.consts = {}
        self._n = 0

    def const(self, arr):
        key = f"c{self._n}"
        self._n += 1
        self.consts[key] = np.asarray(arr)
        return {"__npz__": key}

    def value(self, v):
        """JSON-encode one attr/arg value."""
        import jax
        from ..static.program import _Ref
        if isinstance(v, _Ref):
            return {"__ref__": v.var_id, "name": v.name}
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        if isinstance(v, (np.bool_, np.integer)):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, np.dtype):
            return {"__dtype__": str(v)}
        if isinstance(v, type) and issubclass(v, np.generic):
            return {"__dtype__": str(np.dtype(v))}
        if isinstance(v, (np.ndarray, jax.Array)):
            return self.const(v)
        if isinstance(v, tuple):
            return {"__tuple__": [self.value(x) for x in v]}
        if isinstance(v, list):
            return [self.value(x) for x in v]
        if isinstance(v, dict):
            return {"__dict__": [[self.value(k), self.value(x)]
                                 for k, x in v.items()]}
        raise TypeError(
            f"Program attr of type {type(v).__name__} is not serializable "
            "in the versioned format (op attrs must be JSON-able values, "
            "arrays, or Variable refs)")

    def var(self, v):
        return {"id": v.var_id, "name": v.name,
                "shape": [int(s) for s in v.aval.shape],
                "dtype": str(np.dtype(v.aval.dtype)),
                "is_data": bool(getattr(v, "is_data", False)),
                "scope_name": getattr(v, "scope_name", None)}

    def op(self, op):
        import jax.tree_util as jtu
        from ..static.control_flow import _CondFn, _WhileFn
        kwargs = jtu.tree_unflatten(op.kw_tree, op.flat[op.n_args:])
        fn = op.fn
        if isinstance(fn, _CondFn):
            fn_doc = {"__cond__": {
                "true": self.subblock(fn.true_block),
                "false": self.subblock(fn.false_block)}}
        elif isinstance(fn, _WhileFn):
            fn_doc = {"__while__": {
                "cond": self.subblock(fn.cond_block),
                "body": self.subblock(fn.body_block),
                "n_loop": fn.n_loop, "max_trip": fn.max_trip}}
        elif hasattr(fn, "op_name"):
            name = fn.op_name
            ver = _op_version(name)
            fn_doc = {"__opreg__": name, "version": ver or 1}
        else:
            raise TypeError(
                f"op '{op.name}' has a kernel that is neither a registry "
                f"op nor a control-flow block ({type(fn).__name__}); it "
                "cannot be saved in the versioned format")
        return {"fn": fn_doc, "name": op.name,
                "args": [self.value(a) for a in op.flat[:op.n_args]],
                "kwargs": self.value(kwargs),
                "out_ids": list(op.out_ids),
                "out_vars": [self.var(v) for v in op.out_vars]}

    def subblock(self, blk):
        return {"ops": [self.op(o) for o in blk.ops],
                "in_ids": list(blk.in_ids),
                "free_ids": list(blk.free_ids),
                "out_ids": list(blk.out_ids)}


def save_program(program, path, feed_names=(), extra=None):
    enc = _Encoder()
    ops_doc = [enc.op(op) for op in program.ops]
    op_versions = {}
    for doc in _walk_op_docs(ops_doc):
        fnd = doc["fn"]
        if "__opreg__" in fnd:
            op_versions[fnd["__opreg__"]] = fnd["version"]
    doc = {
        "format_version": FORMAT_VERSION,
        "name": program.name,
        "op_versions": op_versions,
        "ops": ops_doc,
        "data_vars": [enc.var(v) for v in program.data_vars.values()],
        "persistable_vars": [enc.var(v)
                             for v in program.persistable_vars.values()],
        "persist_ids": dict(program.persist_ids),
        "state_writes": dict(program.state_writes),
        "feed_names": list(feed_names),
        "fetch_ids": [v.var_id for v in
                      getattr(program, "_jit_fetch_vars", [])],
        "extra": extra or {},
    }
    with open(path + ".pdmodel", "w") as f:
        json.dump(doc, f)
    np.savez(path + ".pdmodel.npz", **enc.consts)


def _walk_op_docs(ops_doc):
    for doc in ops_doc:
        yield doc
        fnd = doc["fn"]
        if "__cond__" in fnd:
            yield from _walk_op_docs(fnd["__cond__"]["true"]["ops"])
            yield from _walk_op_docs(fnd["__cond__"]["false"]["ops"])
        if "__while__" in fnd:
            yield from _walk_op_docs(fnd["__while__"]["cond"]["ops"])
            yield from _walk_op_docs(fnd["__while__"]["body"]["ops"])


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

class _Decoder:
    def __init__(self, consts):
        self.consts = consts

    def value(self, v):
        from ..static.program import _Ref
        if isinstance(v, dict):
            if "__ref__" in v:
                r = _Ref.__new__(_Ref)
                r.var_id = v["__ref__"]
                r.name = v.get("name", f"_var_{v['__ref__']}")
                return r
            if "__npz__" in v:
                import jax.numpy as jnp
                return jnp.asarray(self.consts[v["__npz__"]])
            if "__dtype__" in v:
                return np.dtype(v["__dtype__"])
            if "__tuple__" in v:
                return tuple(self.value(x) for x in v["__tuple__"])
            if "__dict__" in v:
                return {self.value(k): self.value(x)
                        for k, x in v["__dict__"]}
        if isinstance(v, list):
            return [self.value(x) for x in v]
        return v

    def var(self, doc, program=None):
        from ..static.program import Variable
        v = Variable.__new__(Variable)
        from ..core.tensor import Tensor
        Tensor.__init__(v, None, stop_gradient=True, _internal=True)
        import jax
        from ..core.dtype import to_jax_dtype
        v.aval = jax.ShapeDtypeStruct(tuple(doc["shape"]),
                                      to_jax_dtype(doc["dtype"]))
        v.var_id = doc["id"]
        v.name = doc["name"]
        v.is_data = doc.get("is_data", False)
        v.scope_name = doc.get("scope_name")
        v.program = program
        return v

    def fn(self, fnd):
        from ..static.control_flow import SubBlock, _CondFn, _WhileFn
        if "__opreg__" in fnd:
            from ..ops import OP_REGISTRY
            name = fnd["__opreg__"]
            if name not in OP_REGISTRY:
                raise OpVersionError(
                    f"saved model uses op '{name}' which this build does "
                    "not register — the model needs a newer framework or "
                    "a compat shim")
            saved_v = int(fnd.get("version", 1))
            cur_v = _op_version(name) or 1
            if saved_v > cur_v:
                raise OpVersionError(
                    f"saved model op '{name}' is version {saved_v} but "
                    f"this build implements version {cur_v}; upgrade the "
                    "framework to load this model")
            return OP_REGISTRY[name].raw
        if "__cond__" in fnd:
            return _CondFn(self.subblock(fnd["__cond__"]["true"]),
                           self.subblock(fnd["__cond__"]["false"]))
        if "__while__" in fnd:
            d = fnd["__while__"]
            return _WhileFn(self.subblock(d["cond"]),
                            self.subblock(d["body"]),
                            d["n_loop"], d["max_trip"])
        raise OpVersionError(f"unknown op kind in saved model: {fnd}")

    def op(self, doc, program):
        import jax.tree_util as jtu
        from ..static.program import OpNode
        op = OpNode.__new__(OpNode)
        op.fn = self.fn(doc["fn"])
        op.name = doc["name"]
        args = [self.value(a) for a in doc["args"]]
        kwargs = self.value(doc["kwargs"]) or {}
        kw_leaves, kw_tree = jtu.tree_flatten(kwargs)
        op.flat = args + kw_leaves
        op.n_args = len(args)
        op.kw_tree = kw_tree
        op.out_vars = [self.var(v, program) for v in doc["out_vars"]]
        op.out_ids = list(doc["out_ids"])
        return op

    def subblock(self, doc):
        from ..static.control_flow import SubBlock
        blk = SubBlock([], doc["in_ids"], doc["free_ids"], doc["out_ids"])
        blk.ops = [self.op(o, None) for o in doc["ops"]]
        return blk


def load_program(path):
    """Load a versioned .pdmodel; returns (program, feed_names)."""
    from ..static.program import Program
    with open(path + ".pdmodel") as f:
        doc = json.load(f)
    fmt = doc.get("format_version")
    if fmt is None or fmt > FORMAT_VERSION:
        raise OpVersionError(
            f"model format_version {fmt} is newer than this build's "
            f"{FORMAT_VERSION}")
    try:
        consts = dict(np.load(path + ".pdmodel.npz").items())
    except FileNotFoundError:
        raise OpVersionError(
            f"'{path}.pdmodel.npz' is missing — the .pdmodel JSON and its "
            ".npz constant sidecar form one artifact; copy both") from None
    dec = _Decoder(consts)
    program = Program(doc.get("name", "loaded"))
    program.ops = [dec.op(o, program) for o in doc["ops"]]
    for vd in doc["data_vars"]:
        v = dec.var(vd, program)
        program.data_vars[v.name] = v
    for vd in doc["persistable_vars"]:
        v = dec.var(vd, program)
        program.persistable_vars[v.scope_name] = v
    program.persist_ids = {k: int(x)
                           for k, x in doc.get("persist_ids", {}).items()}
    program.state_writes = {k: int(x)
                            for k, x in doc.get("state_writes", {}).items()}
    by_id = {}
    for op in program.ops:
        for v in op.out_vars:
            by_id[v.var_id] = v
    for v in list(program.data_vars.values()) \
            + list(program.persistable_vars.values()):
        by_id[v.var_id] = v
    program._jit_fetch_vars = [by_id[i] for i in doc.get("fetch_ids", [])]
    # keep the process-wide Variable id counter ahead of every loaded id,
    # so ops appended to this program later cannot alias loaded SSA ids
    from ..static.program import Variable
    if by_id:
        with Variable._lock:
            Variable._counter[0] = max(Variable._counter[0],
                                       max(by_id) + 1)
    return program, list(doc.get("feed_names", []))
