"""Model artifact encryption.

Analog of reference framework/io/crypto/ (cipher.h CipherFactory,
aes_cipher.cc over cryptopp) + pybind/crypto.cc: inference models shipped
to untrusted hosts are encrypted at rest. Here AES-256-GCM via the
`cryptography` package — authenticated encryption (tamper = loud failure),
fresh 96-bit nonce per file, key from CipherUtils.gen_key or a
user-provided 32-byte secret.
"""
from __future__ import annotations

import os

__all__ = ["Cipher", "CipherFactory", "CipherUtils"]

_MAGIC = b"PTPUENC1"


class Cipher:
    """AES-256-GCM cipher (reference cipher.h Cipher interface)."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("key must be 32 bytes (AES-256)")
        self._key = key

    def encrypt(self, plaintext: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        nonce = os.urandom(12)
        ct = AESGCM(self._key).encrypt(nonce, plaintext, _MAGIC)
        return _MAGIC + nonce + ct

    def decrypt(self, blob: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        if not blob.startswith(_MAGIC):
            raise ValueError("not a paddle_tpu encrypted artifact")
        nonce, ct = blob[len(_MAGIC):len(_MAGIC) + 12], blob[len(_MAGIC) + 12:]
        return AESGCM(self._key).decrypt(nonce, ct, _MAGIC)

    # reference cipher.h file API
    def encrypt_to_file(self, plaintext: bytes, path: str):
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext))

    def decrypt_from_file(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return self.decrypt(f.read())

    def encrypt_file(self, src: str, dst: str):
        with open(src, "rb") as f:
            self.encrypt_to_file(f.read(), dst)

    def decrypt_file(self, src: str, dst: str):
        with open(dst, "wb") as f:
            f.write(self.decrypt_from_file(src))


class CipherFactory:
    """reference CipherFactory::CreateCipher."""

    @staticmethod
    def create_cipher(key: bytes = None):
        return Cipher(key or CipherUtils.gen_key())


class CipherUtils:
    @staticmethod
    def gen_key() -> bytes:
        return os.urandom(32)

    @staticmethod
    def gen_key_to_file(path: str) -> bytes:
        key = CipherUtils.gen_key()
        with open(path, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()
