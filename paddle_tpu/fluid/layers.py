"""fluid.layers — the v1 functional layer API mapped onto 2.0 ops/layers
(reference python/paddle/fluid/layers/nn.py:181 fc, :389 embedding,
loss.py cross_entropy, tensor.py fill_constant/concat/..., control_flow
等). Layers that create parameters (fc/embedding/conv2d/batch_norm) build
the 2.0 Layer under the hood so they work identically in dygraph and
inside a static Program being traced."""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import nn as _nn
from ..nn import functional as F

__all__ = ["fc", "embedding", "conv2d", "pool2d", "batch_norm", "dropout",
           "relu", "softmax", "sigmoid", "tanh", "cross_entropy", "mean",
           "reduce_mean", "reduce_sum", "reduce_max", "square", "sqrt",
           "abs", "elementwise_add", "elementwise_sub", "elementwise_mul",
           "elementwise_div", "elementwise_max", "elementwise_min", "mul",
           "matmul", "concat", "split", "reshape", "transpose", "stack",
           "unsqueeze", "squeeze", "cast", "fill_constant", "zeros",
           "ones", "assign", "shape", "slice", "gather", "scatter",
           "one_hot", "topk", "argmax", "argsort", "accuracy", "auc",
           "l2_normalize", "clip", "clip_by_norm", "label_smooth",
           "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
           "smooth_l1", "log_loss", "lod_reset", "sequence_pool",
           "sequence_softmax", "sequence_expand", "sequence_concat",
           "sequence_reverse", "sequence_pad", "sequence_unpad",
           "increment", "cond", "while_loop"]

_param_layers = {}


def _layer_cached(key, build):
    layer = _param_layers.get(key)
    if layer is None:
        layer = _param_layers[key] = build()
    return layer


def _auto_name(prefix, name):
    """Unnamed v1 layer calls create FRESH parameters per call, named by
    the global unique_name generator exactly like the reference's
    LayerHelper (two anonymous fc() calls are fc_0/fc_1, never shared);
    an explicit name pins and reuses the layer across rebuilds."""
    if name is not None:
        return name
    from ..utils import unique_name
    return unique_name.generate(prefix)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,  # noqa: A002
       act=None, name=None):
    """reference fluid/layers/nn.py:181. Flattens trailing dims, applies a
    Linear (parameters cached per name/shape), optional activation."""
    x = input
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    if len(x.shape) > num_flatten_dims + 1:
        x = ops.reshape(x, list(x.shape[:num_flatten_dims]) + [in_dim])
    name = _auto_name("fc", name)
    layer = _layer_cached(("fc", name, in_dim, size), lambda: _nn.Linear(
        in_dim, size, weight_attr=param_attr, bias_attr=bias_attr))
    out = layer(x)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,  # noqa: A002
              param_attr=None, dtype="float32", name=None):
    name = _auto_name("embedding", name)
    layer = _layer_cached(("emb", name, tuple(size)), lambda: _nn.Embedding(
        size[0], size[1], padding_idx=padding_idx, sparse=is_sparse,
        weight_attr=param_attr))
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None, act=None,
           name=None):
    cin = input.shape[1]
    name = _auto_name("conv2d", name)
    layer = _layer_cached(
        ("conv2d", name, cin, num_filters, filter_size),
        lambda: _nn.Conv2D(cin, num_filters, filter_size, stride=stride,
                           padding=padding, dilation=dilation,
                           groups=groups, weight_attr=param_attr,
                           bias_attr=bias_attr))
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def pool2d(input, pool_size=2, pool_type="max", pool_stride=1,  # noqa: A002
           pool_padding=0, global_pooling=False, ceil_mode=False):
    if global_pooling:
        pool_size = input.shape[2:]
        pool_stride = pool_size
    if pool_type == "max":
        return F.max_pool2d(input, pool_size, stride=pool_stride,
                            padding=pool_padding, ceil_mode=ceil_mode)
    return F.avg_pool2d(input, pool_size, stride=pool_stride,
                        padding=pool_padding, ceil_mode=ceil_mode)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,  # noqa: A002
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, name=None):
    c = input.shape[1]
    name = _auto_name("batch_norm", name)
    layer = _layer_cached(("bn", name, c), lambda: _nn.BatchNorm(
        c, momentum=momentum, epsilon=epsilon, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_layout))
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def dropout(x, dropout_prob=0.5, is_test=False, seed=None):
    return F.dropout(x, p=dropout_prob, training=not is_test)


# -- pure-op aliases -------------------------------------------------------

def _alias(fn):
    return fn


relu = _alias(lambda x: F.relu(x))
softmax = _alias(lambda x, axis=-1: F.softmax(x, axis=axis))
sigmoid = _alias(lambda x: F.sigmoid(x))
tanh = _alias(lambda x: ops.tanh(x))
mean = _alias(lambda x: ops.mean(x))
reduce_mean = _alias(lambda x, dim=None, keep_dim=False:
                     ops.mean(x, axis=dim, keepdim=keep_dim))
reduce_sum = _alias(lambda x, dim=None, keep_dim=False:
                    ops.sum(x, axis=dim, keepdim=keep_dim))
reduce_max = _alias(lambda x, dim=None, keep_dim=False:
                    ops.max(x, axis=dim, keepdim=keep_dim))
square = _alias(lambda x: ops.square(x))
sqrt = _alias(lambda x: ops.sqrt(x))
abs = _alias(lambda x: ops.abs(x))  # noqa: A001
def _elementwise(op):
    """v1 elementwise semantics (reference fluid/layers/nn.py
    elementwise_add: axis aligns y's dims starting at x dim `axis`, act
    applies an activation to the result). axis=-1 means trailing-aligned
    numpy broadcasting; otherwise y is reshaped with trailing singleton
    dims so it broadcasts from dim `axis`."""
    def f(x, y, axis=-1, act=None, name=None):
        xnd = len(x.shape)
        ynd = len(y.shape)
        if axis not in (-1, xnd - 1) and ynd < xnd:
            if axis < 0 or axis + ynd > xnd:
                raise ValueError(
                    f"elementwise axis={axis} invalid for x.ndim={xnd}, "
                    f"y.ndim={ynd}")
            y = ops.reshape(y, list(y.shape) + [1] * (xnd - axis - ynd))
        out = op(x, y)
        if act is not None:
            out = getattr(F, act)(out)
        return out
    return f


elementwise_add = _elementwise(ops.add)
elementwise_sub = _elementwise(ops.subtract)
elementwise_mul = _elementwise(ops.multiply)
elementwise_div = _elementwise(ops.divide)
elementwise_max = _elementwise(ops.maximum)
elementwise_min = _elementwise(ops.minimum)
mul = _alias(lambda x, y: ops.matmul(x, y))
matmul = _alias(lambda x, y, transpose_x=False, transpose_y=False:
                ops.matmul(x, y, transpose_x=transpose_x,
                           transpose_y=transpose_y))
concat = _alias(lambda input, axis=0: ops.concat(input, axis=axis))  # noqa: A002
split = _alias(lambda input, num_or_sections, dim=-1:  # noqa: A002
               ops.split(input, num_or_sections, axis=dim))
reshape = _alias(lambda x, shape: ops.reshape(x, shape))
transpose = _alias(lambda x, perm: ops.transpose(x, perm))
stack = _alias(lambda x, axis=0: ops.stack(x, axis=axis))
unsqueeze = _alias(lambda input, axes: ops.unsqueeze(input, axes))  # noqa: A002
squeeze = _alias(lambda input, axes=None: ops.squeeze(input, axes))  # noqa: A002
cast = _alias(lambda x, dtype: x.astype(dtype))
zeros = _alias(lambda shape, dtype="float32": ops.zeros(shape, dtype))
ones = _alias(lambda shape, dtype="float32": ops.ones(shape, dtype))
assign = _alias(lambda input: ops.assign(input))  # noqa: A002
def shape(input):  # noqa: A002
    from ..core.tensor import to_tensor
    return to_tensor(np.asarray(input.shape, "int32"))
slice = _alias(lambda input, axes, starts, ends:  # noqa: A001,A002
               ops.slice(input, axes, starts, ends))
gather = _alias(lambda input, index: ops.gather(input, index))  # noqa: A002
scatter = _alias(lambda input, index, updates, overwrite=True:  # noqa: A002
                 ops.scatter(input, index, updates, overwrite=overwrite))
one_hot = _alias(lambda input, depth: ops.one_hot(input, depth))  # noqa: A002
topk = _alias(lambda input, k: ops.topk(input, k))  # noqa: A002
argmax = _alias(lambda x, axis=-1: ops.argmax(x, axis=axis))
argsort = _alias(lambda x, axis=-1: ops.argsort(x, axis=axis))
accuracy = _alias(lambda input, label, k=1:  # noqa: A002
                  ops.accuracy(input, label, k=k))
auc = _alias(lambda input, label, num_thresholds=200:  # noqa: A002
             ops.auc(input, label, num_thresholds=num_thresholds))
l2_normalize = _alias(lambda x, axis=-1, epsilon=1e-12:
                      ops.l2_normalize(x, axis=axis, epsilon=epsilon))
clip = _alias(lambda x, min, max: ops.clip(x, min, max))  # noqa: A002
clip_by_norm = _alias(lambda x, max_norm: ops.clip_by_norm(x, max_norm))
label_smooth = _alias(lambda label, epsilon=0.1:
                      ops.label_smooth(label, epsilon=epsilon))
log_loss = _alias(lambda input, label, epsilon=1e-4:  # noqa: A002
                  ops.log_loss(input, label, epsilon))
smooth_l1 = _alias(lambda x, y: ops.smooth_l1_loss(x, y, reduction="none"))
softmax_with_cross_entropy = _alias(
    lambda logits, label, soft_label=False:
    ops.softmax_with_cross_entropy(logits, label, soft_label=soft_label))
sigmoid_cross_entropy_with_logits = _alias(
    lambda x, label: F.binary_cross_entropy_with_logits(
        x, label, reduction="none"))


def fill_constant(shape, dtype, value, name=None):  # noqa: A002
    return ops.full(shape, value, dtype)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):  # noqa: A002
    return ops.cross_entropy(input, label, soft_label=soft_label,
                             ignore_index=ignore_index, reduction="none")


def increment(x, value=1.0, in_place=True):
    out = ops.add(x, ops.full_like(x, value))
    if in_place and hasattr(x, "set_value"):
        x.set_value(out._value)
        return x
    return out


def lod_reset(x, y=None, target_lod=None):
    """reference sequence_ops lod_reset: reattach row_splits."""
    from ..core.ragged import RaggedTensor
    vals = x.values if isinstance(x, RaggedTensor) else \
        (x._value if hasattr(x, "_value") else x)
    if y is not None and isinstance(y, RaggedTensor):
        return RaggedTensor(vals, y.row_splits)
    splits = np.concatenate([[0], np.cumsum(np.asarray(target_lod))])
    return RaggedTensor(vals, splits.astype(np.int32))


# sequence + control-flow re-exports (same implementations)
from ..ops.sequence import (sequence_concat, sequence_expand,  # noqa: E402,F401
                            sequence_pad, sequence_pool, sequence_reverse,
                            sequence_softmax, sequence_unpad)
from ..static.control_flow import cond, while_loop  # noqa: E402,F401
