"""fluid.io — v1 save/load surface (reference python/paddle/fluid/io.py:
save_persistables :620, load_persistables, save/load_inference_model)."""
from __future__ import annotations

from ..static import (load_inference_model, save_inference_model)  # noqa: F401


def save_persistables(executor, dirname, main_program=None, filename=None):
    from ..static import default_main_program, save as _save
    program = main_program or default_main_program()
    import os
    path = os.path.join(dirname, filename or "params")
    _save(program, path)
    return path


def load_persistables(executor, dirname, main_program=None, filename=None):
    from ..static import default_main_program, load as _load
    program = main_program or default_main_program()
    import os
    path = os.path.join(dirname, filename or "params")
    _load(program, path)
