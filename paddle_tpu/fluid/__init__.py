"""paddle.fluid compatibility namespace (v1 API).

The reference's 1.x surface (python/paddle/fluid/__init__.py +
fluid/layers/, ~36k LoC of wrappers) predates the 2.0 API this framework
targets; this module keeps v1 programs loadable by mapping the commonly
used names onto their 2.0 implementations — same redesign-not-port rule:
these are thin adapters over the real ops/layers, not a second op layer.
"""
from __future__ import annotations

from .. import static as _static
from ..static import (Executor, Program, default_main_program,  # noqa: F401
                      default_startup_program, global_scope,
                      program_guard)
from ..static.program import Scope  # noqa: F401
from ..device import CPUPlace, CUDAPlace  # noqa: F401
from ..core import dtype as core  # noqa: F401
from . import layers  # noqa: F401
from . import io  # noqa: F401

__all__ = ["layers", "io", "Executor", "Program", "Scope", "CPUPlace",
           "CUDAPlace", "default_main_program", "default_startup_program",
           "program_guard", "global_scope", "data", "embedding",
           "enable_dygraph", "disable_dygraph"]


def data(name, shape, dtype="float32", lod_level=0):
    return _static.data(name, shape, dtype, lod_level)


def embedding(input, size, is_sparse=False, padding_idx=None,  # noqa: A002
              param_attr=None, dtype="float32"):
    return layers.embedding(input, size, is_sparse=is_sparse,
                            padding_idx=padding_idx, param_attr=param_attr,
                            dtype=dtype)


def enable_dygraph(place=None):
    from .. import disable_static
    disable_static()


def disable_dygraph():
    from .. import enable_static
    enable_static()
