"""Host/device profiler.

TPU-native analog of the reference profiler stack:
- `RecordEvent` scoped host annotations — reference platform/profiler.h:127
  (RAII RecordEvent inserted around the op loop, framework/operator.cc:1074).
- `profiler`/`start_profiler`/`stop_profiler` context + summary tables —
  reference python/paddle/fluid/profiler.py.
- Chrome-trace export — reference platform/profiler.proto + device_tracer.
- Device-side capture: the reference correlates CUPTI kernel records
  (platform/device_tracer.h:43); the TPU equivalent is XLA's xplane
  profiler, exposed here as `xplane_trace` (view in TensorBoard/XProf) —
  compiler-scheduled device activity replaces per-kernel correlation ids.
- `cost_analysis` — achieved-FLOPs accounting from the compiled
  executable, the analog of the reference's per-op cost model
  (platform/monitor.h StatRegistry + op_handle events).

Design delta: ops under `jit` execute as one XLA program, so per-op *host*
events measure Python trace/dispatch (still the right tool for finding
host-side stalls — the reference's RecordEvent measures the same thing);
device time lives in the xplane capture and in whole-step wall clock.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import defaultdict
from typing import Optional

from ..core import trace as _trace

__all__ = ["RecordEvent", "profiler", "start_profiler", "stop_profiler",
           "reset_profiler", "summary", "events", "export_chrome_trace",
           "xplane_trace", "start_xplane", "stop_xplane", "cost_analysis",
           "is_profiler_enabled"]

_lock = threading.Lock()
_events: list = []          # (name, t0, t1, tid)
_enabled = False
_t_origin = _trace._t_origin  # shared clock origin with the span tracer


def is_profiler_enabled() -> bool:
    return _enabled


def _trace_sink(sp):
    """Installed into core/trace: while the host profiler is enabled,
    every finished span (RecordEvent or first-class trace.span site —
    pipeline runner, PS rpc, Pallas dispatch, dataloader) also lands in
    the profiler's aggregate event table, so summary() covers the whole
    runtime without double instrumentation."""
    if _enabled:
        with _lock:
            _events.append((sp.name, sp.t0, sp.t1, sp.tid))


_trace._profiler_sink = _trace_sink


class RecordEvent:
    """Scoped host annotation (reference platform/profiler.h:127), now a
    thin wrapper over a core/trace span: it nests under the ambient span
    and shows up in Chrome-trace exports with ids/parents. Usable as a
    context manager or via explicit begin()/end(). Cheap no-op while the
    profiler is disabled (per-op sites in core/tape.py stay free); use
    core.trace.span directly for always-on (flight-recorded) sites.
    """

    __slots__ = ("name", "_span")

    def __init__(self, name: str):
        self.name = name
        self._span = None

    def begin(self):
        if _enabled:
            # detached: legacy callers (core/tape.py per-op annotations)
            # skip end() on exception — a stack-attached span would then
            # corrupt every later span's parentage on this thread
            self._span = _trace.begin(self.name, _attach=False)
        return self

    def end(self):
        if self._span is not None:
            _trace.end(self._span)  # the sink mirrors it into _events
            self._span = None

    __enter__ = begin

    def __exit__(self, *exc):
        self.end()
        return False


def start_profiler(state: str = "All", tracer_option: str = "Default"):
    """reference fluid/profiler.py start_profiler. `state`/`tracer_option`
    kept for API parity (host events are always captured; use xplane_trace
    for device activity)."""
    global _enabled
    from ..core import flags as _flags
    _flags.set_flags({"FLAGS_enable_profiler": True})
    _enabled = True


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None):
    """Stop, optionally print a summary table and write a chrome trace."""
    global _enabled
    _enabled = False
    from ..core import flags as _flags
    _flags.set_flags({"FLAGS_enable_profiler": False})
    if profile_path:
        export_chrome_trace(profile_path)
    if sorted_key is not None:
        print(summary(sorted_key=sorted_key))


def reset_profiler():
    with _lock:
        _events.clear()


def events():
    with _lock:
        return list(_events)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None):
    """reference fluid/profiler.py profiler() context manager."""
    reset_profiler()
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key=sorted_key, profile_path=profile_path)


def summary(sorted_key: str = "total") -> str:
    """Aggregate event table (reference profiler summary printing)."""
    agg = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])  # n, tot, mn, mx
    for name, t0, t1, _tid in events():
        dt = (t1 - t0) * 1e3
        a = agg[name]
        a[0] += 1
        a[1] += dt
        a[2] = min(a[2], dt)
        a[3] = max(a[3], dt)
    if not agg:
        return "(no profiler events)"
    total_all = sum(a[1] for a in agg.values())
    keyfn = {"total": lambda kv: kv[1][1], "calls": lambda kv: kv[1][0],
             "max": lambda kv: kv[1][3], "min": lambda kv: kv[1][2],
             "ave": lambda kv: kv[1][1] / kv[1][0]}.get(
                 sorted_key, lambda kv: kv[1][1])
    rows = sorted(agg.items(), key=keyfn, reverse=True)
    w = max(len(n) for n in agg) + 2
    out = [f"{'Event':<{w}}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
           f"{'Min(ms)':>10}{'Max(ms)':>10}{'Ratio':>8}"]
    for name, (n, tot, mn, mx) in rows:
        out.append(f"{name:<{w}}{n:>8}{tot:>12.3f}{tot / n:>10.3f}"
                   f"{mn:>10.3f}{mx:>10.3f}{tot / total_all:>8.2%}")
    return "\n".join(out)


def export_chrome_trace(path: str):
    """chrome://tracing JSON (analog of the reference's chrome-trace
    protobuf output, platform/profiler.proto)."""
    trace = [{"name": name, "ph": "X", "pid": 0, "tid": tid,
              "ts": (t0 - _t_origin) * 1e6, "dur": (t1 - t0) * 1e6}
             for name, t0, t1, tid in events()]
    with open(path, "w") as f:
        json.dump({"traceEvents": trace}, f)


# -- device-side capture (XLA xplane; view with TensorBoard/XProf) ---------

def start_xplane(log_dir: str):
    import jax
    jax.profiler.start_trace(log_dir)


def stop_xplane():
    import jax
    jax.profiler.stop_trace()


@contextlib.contextmanager
def xplane_trace(log_dir: str):
    """Capture an XLA device trace (the CUPTI-correlation analog,
    reference platform/device_tracer.h:43)."""
    start_xplane(log_dir)
    try:
        yield
    finally:
        stop_xplane()


# -- achieved-FLOPs accounting ---------------------------------------------

def cost_analysis(jitted_fn, *args, **kwargs):
    """XLA cost analysis of a jitted callable on example args: returns
    {'flops': ..., 'bytes accessed': ..., ...} summed over the module.
    The analog of the reference's per-op cost model feeding its graph
    passes (details/op_handle_base events + monitor StatRegistry)."""
    lowered = jitted_fn.lower(*args, **kwargs)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns one dict per device
        ca = ca[0] if ca else {}
    return dict(ca or {})
