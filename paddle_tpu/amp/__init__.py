"""Automatic mixed precision.

Parity targets:
- `paddle.amp.auto_cast` — reference python/paddle/amp/auto_cast.py
  (op allow/deny lists, O1/O2 levels), applied per-op by the imperative
  tracer (reference paddle/fluid/imperative/tracer.cc:84-87).
- `paddle.amp.GradScaler` — reference python/paddle/amp/grad_scaler.py with
  the device-side semantics of operators/amp/check_finite_and_unscale_op.cc
  and update_loss_scaling_op.cc.
- master weights — reference multi_precision paths in
  operators/optimizers/adam_op.cu etc. (here: an f32 "master" optimizer
  slot, see optimizer/optimizer.py).

TPU design delta: bfloat16 is the native compute dtype (MXU), so the
default amp dtype is bf16 and loss scaling is OPTIONAL for bf16 (its
exponent range matches f32); the scaler degrades to a plain pass-through
when scaling is disabled, exactly like the reference's enable=False mode.
The per-op cast hook lives in core/tape.record_op — the single dispatch
point all three frontends (eager, jitted step, static Program) share.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "white_list", "black_list"]

# -- op lists (analog of fp16_lists.py AutoMixedPrecisionLists) --------------
# MXU-bound ops: always worth bf16
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "einsum", "conv1d", "conv2d", "conv3d",
    "conv2d_transpose", "linear", "addmm",
}
# numerically sensitive ops: keep f32 inputs
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "sqrt", "rsqrt",
    "softmax", "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "sigmoid_cross_entropy_with_logits", "kl_div", "mse_loss", "l1_loss",
    "smooth_l1_loss", "huber_loss", "mean", "sum", "prod", "cumsum",
    "logsumexp", "norm", "p_norm", "erf", "erfinv", "expm1", "sigmoid",
    "cosine_similarity", "softplus", "layer_norm", "batch_norm",
    "instance_norm", "group_norm", "rms_norm", "local_response_norm",
}


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.level = "O1"
        self.dtype = jnp.bfloat16
        self.white = frozenset(WHITE_LIST)
        self.black = frozenset(BLACK_LIST)


_state = _AmpState()


def policy_dtype(name, level, dtype, white=None, black=None):
    """Target dtype for op `name`'s floating inputs under (level, dtype),
    or None to leave them as-is. Shared by the eager auto_cast state and the
    static executor's program-level AMP."""
    black = black if black is not None else BLACK_LIST
    white = white if white is not None else WHITE_LIST
    if name in black:
        return jnp.float32
    if level == "O2":
        return dtype
    if name in white:
        return dtype
    return None  # O1 gray ops: run in whatever dtype arrives


def _amp_dtype_of(name: str):
    if not _state.enabled:
        return None
    return policy_dtype(name, _state.level, _state.dtype,
                        _state.white, _state.black)


def cast_vals(name, vals, level, dtype, white=None, black=None):
    """Static-graph form of cast_inputs: explicit policy, no thread state."""
    dt = policy_dtype(name, level, dtype, white, black)
    if dt is None:
        return vals
    return _cast_list(vals, dt)


def amp_active() -> bool:
    return _state.enabled


def _cast_list(vals, dt):
    """Cast every floating array in `vals` to dt (shared by the eager and
    static cast paths so the predicate can't diverge)."""
    return [v.astype(dt) if hasattr(v, "dtype")
            and jnp.issubdtype(v.dtype, jnp.floating) and v.dtype != dt
            else v for v in vals]


def cast_inputs(op_name: str, vals):
    """Called inside record_op's differentiated region: cast floating array
    inputs per the active policy. The cast is part of the traced function,
    so its vjp re-casts cotangents back to the source dtype (f32 params
    receive f32 grads)."""
    dt = _amp_dtype_of(op_name)
    if dt is None:
        return vals
    return _cast_list(vals, dt)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """reference python/paddle/amp/auto_cast.py auto_cast/amp_guard."""
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"amp level must be O0/O1/O2, got {level!r}")
    prev = (_state.enabled, _state.level, _state.dtype, _state.white,
            _state.black)
    _state.enabled = bool(enable) and level != "O0"
    _state.level = level
    _state.dtype = jnp.bfloat16 if str(dtype) in ("bfloat16", "bf16") \
        else jnp.float16
    white = set(WHITE_LIST)
    black = set(BLACK_LIST)
    if custom_white_list:
        white |= set(custom_white_list)
        black -= set(custom_white_list)
    if custom_black_list:
        black |= set(custom_black_list)
        white -= set(custom_black_list)
    _state.white = frozenset(white)
    _state.black = frozenset(black)
    try:
        yield
    finally:
        (_state.enabled, _state.level, _state.dtype, _state.white,
         _state.black) = prev


amp_guard = auto_cast  # legacy alias (dygraph/amp/auto_cast.py amp_guard)


# -- loss scaling ------------------------------------------------------------

def check_finite_and_unscale(grads: dict, scale):
    """Pure analog of operators/amp/check_finite_and_unscale_op.cc:
    (grads, scale) -> (unscaled_grads, found_inf[bool scalar])."""
    inv = (1.0 / scale).astype(jnp.float32)
    found = jnp.zeros((), jnp.bool_)
    out = {}
    for k, g in grads.items():
        gf = g.astype(jnp.float32) * inv
        found = found | ~jnp.isfinite(gf).all()
        out[k] = gf.astype(g.dtype)
    return out, found


def update_loss_scaling(scale, good_steps, bad_steps, found_inf, *,
                        incr_ratio, decr_ratio, incr_every_n_steps,
                        decr_every_n_nan_or_inf):
    """Pure analog of operators/amp/update_loss_scaling_op.cc."""
    good = jnp.where(found_inf, 0, good_steps + 1)
    bad = jnp.where(found_inf, bad_steps + 1, 0)
    grow = good >= incr_every_n_steps
    shrink = bad >= decr_every_n_nan_or_inf
    new_scale = jnp.where(
        shrink, jnp.maximum(scale * decr_ratio, 1.0),
        jnp.where(grow, scale * incr_ratio, scale))
    good = jnp.where(grow | shrink, 0, good)
    bad = jnp.where(shrink, 0, bad)
    return new_scale.astype(jnp.float32), good.astype(jnp.int32), \
        bad.astype(jnp.int32)


class GradScaler:
    """reference python/paddle/amp/grad_scaler.py.

    Eager usage:
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024)
        with paddle.amp.auto_cast():
            loss = model(x)
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(optimizer)   # unscale + skip-if-nonfinite + opt.step
        scaler.update()

    The same state drives the pure `scale_state()`/`apply_pure()` form that
    hapi/static compiled steps embed (one fused XLA program per step).
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = bool(enable)
        self._scale = jnp.asarray(float(init_loss_scaling), jnp.float32)
        self._good = jnp.asarray(0, jnp.int32)
        self._bad = jnp.asarray(0, jnp.int32)
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._incr_every_n_steps = int(incr_every_n_steps)
        self._decr_every_n_nan_or_inf = int(decr_every_n_nan_or_inf)
        self._dynamic = bool(use_dynamic_loss_scaling)
        self._found_inf = None  # set by unscale_/step

    # -- eager path ----------------------------------------------------------
    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return float(np.asarray(self._scale))

    def set_init_loss_scaling(self, v):
        self._scale = jnp.asarray(float(v), jnp.float32)

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * Tensor(self._scale, _internal=True)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        named = optimizer._collect()
        grads = {k: p.grad._value for k, p in named.items()}
        new_grads, found = check_finite_and_unscale(grads, self._scale)
        for k, p in named.items():
            p.grad = Tensor(new_grads[k], stop_gradient=True, _internal=True)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if self._found_inf is None:
            self.unscale_(optimizer)
        if not bool(np.asarray(self._found_inf)):
            optimizer.step()

    def minimize(self, optimizer, scaled_loss):
        # reference: scaler.minimize == step + update (loss already backward)
        self.step(optimizer)
        self.update()

    def update(self):
        if not (self._enable and self._dynamic):
            self._found_inf = None
            return
        if self._found_inf is None:
            return
        self._scale, self._good, self._bad = update_loss_scaling(
            self._scale, self._good, self._bad, self._found_inf,
            incr_ratio=self._incr_ratio, decr_ratio=self._decr_ratio,
            incr_every_n_steps=self._incr_every_n_steps,
            decr_every_n_nan_or_inf=self._decr_every_n_nan_or_inf)
        self._found_inf = None

    # -- pure path (embedded in compiled train steps) ------------------------
    def scale_state(self):
        return {"scale": self._scale, "good": self._good, "bad": self._bad}

    def load_scale_state(self, st):
        self._scale, self._good, self._bad = st["scale"], st["good"], st["bad"]

    def apply_pure(self, grads, state):
        """(scaled_grads, state) -> (unscaled_grads, found_inf, new_state).
        Embed inside a jitted step; caller gates the param update on
        found_inf (select old params when non-finite)."""
        if not self._enable:
            return grads, jnp.zeros((), jnp.bool_), state
        new_grads, found = check_finite_and_unscale(grads, state["scale"])
        if self._dynamic:
            s, g, b = update_loss_scaling(
                state["scale"], state["good"], state["bad"], found,
                incr_ratio=self._incr_ratio, decr_ratio=self._decr_ratio,
                incr_every_n_steps=self._incr_every_n_steps,
                decr_every_n_nan_or_inf=self._decr_every_n_nan_or_inf)
            state = {"scale": s, "good": g, "bad": b}
        return new_grads, found, state

    def state_dict(self):
        return {
            "scale": np.asarray(self._scale),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_count": int(np.asarray(self._good)),
            "decr_count": int(np.asarray(self._bad)),
            "use_dynamic_loss_scaling": self._dynamic,
        }

    def set_state_dict(self, d):
        self._scale = jnp.asarray(d["scale"], jnp.float32)
        self._good = jnp.asarray(d.get("incr_count", 0), jnp.int32)
        self._bad = jnp.asarray(d.get("decr_count", 0), jnp.int32)


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """reference python/paddle/amp/auto_cast.py decorate (O2 pure-bf16):
    cast model params to the amp dtype; optimizer keeps f32 master weights
    (multi_precision slot)."""
    if level not in ("O1", "O2"):
        raise ValueError("decorate level must be O1 or O2")
    amp_dt = jnp.bfloat16 if str(dtype) in ("bfloat16", "bf16") \
        else jnp.float16
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            for p in m.parameters():
                if jnp.issubdtype(p._value.dtype, jnp.floating) \
                        and p._value.dtype == jnp.float32:
                    p._value = p._value.astype(amp_dt)
                    p._node = None
    if optimizers is None:
        return models
    opt_single = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if opt_single else list(optimizers)
    for opt in opt_list:
        if master_weight is not False:
            opt._multi_precision = True
    return models, optimizers
