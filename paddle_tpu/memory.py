"""Device memory introspection.

Analog of the reference memory subsystem's *observable* surface
(reference paddle/fluid/memory/ allocator facade ~7k LoC: stats in
allocation/allocator_facade.cc, `memory::StatGetCurrentValue`, and the
paddle.device.cuda.memory_allocated/max_memory_allocated APIs).

Design delta: XLA/PJRT owns allocation (BFC-style arena per device), so
the reference's strategy zoo (naive_best_fit / auto_growth / retry)
collapses into PJRT; what remains OURS is instrumentation — per-device
byte counters from the PJRT allocator, live-buffer accounting from the
runtime, and a human-readable summary. On backends whose PJRT plugin
reports no stats (CPU), live-array accounting is the fallback.
"""
from __future__ import annotations

from collections import defaultdict

import numpy as np

__all__ = ["memory_allocated", "max_memory_allocated", "memory_reserved",
           "stats", "live_bytes", "live_tensor_count", "summary",
           "empty_cache"]


def _device(device=None):
    import jax
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    return device


def _stats(device):
    st = device.memory_stats() if hasattr(device, "memory_stats") else None
    return st or {}


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the device (reference
    memory_allocated; PJRT `bytes_in_use`)."""
    d = _device(device)
    st = _stats(d)
    if "bytes_in_use" in st:
        return int(st["bytes_in_use"])
    return live_bytes(d)


def max_memory_allocated(device=None) -> int:
    """High-water mark (PJRT `peak_bytes_in_use`); 0 where the plugin
    doesn't track peaks (CPU)."""
    return int(_stats(_device(device)).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Arena size reserved from the system (`bytes_limit`/`bytes_reserved`)."""
    st = _stats(_device(device))
    return int(st.get("bytes_reserved", st.get("bytes_limit", 0)))


def stats(device=None) -> dict:
    """Raw PJRT allocator stats dict (may be empty on CPU)."""
    return dict(_stats(_device(device)))


def live_bytes(device=None) -> int:
    """Sum of live jax array bytes on the device (runtime accounting,
    backend-independent)."""
    import jax
    d = _device(device)
    total = 0
    for a in jax.live_arrays():
        try:
            if d in a.devices():
                total += a.nbytes // len(a.devices())
        except Exception:
            pass
    return int(total)


def live_tensor_count() -> int:
    import jax
    return len(jax.live_arrays())


def empty_cache():
    """Parity no-op: XLA's arena is not user-flushable; kept so reference
    scripts run unchanged (the reference's Release() equivalent)."""


def summary(device=None) -> str:
    """Human-readable report: allocator stats + live buffers by dtype."""
    import jax
    d = _device(device)
    st = _stats(d)
    lines = [f"memory summary for {d}"]
    if st:
        for k in sorted(st):
            lines.append(f"  {k:<28}{st[k]}")
    by_dtype = defaultdict(lambda: [0, 0])
    for a in jax.live_arrays():
        try:
            if d in a.devices():
                e = by_dtype[str(a.dtype)]
                e[0] += 1
                e[1] += a.nbytes // len(a.devices())
        except Exception:
            pass
    lines.append(f"  live arrays: {sum(v[0] for v in by_dtype.values())}"
                 f" ({sum(v[1] for v in by_dtype.values()) / 1e6:.2f} MB)")
    for dt, (n, nbytes) in sorted(by_dtype.items(),
                                  key=lambda kv: -kv[1][1]):
        lines.append(f"    {dt:<12}{n:>6} arrays {nbytes / 1e6:>10.2f} MB")
    return "\n".join(lines)
