"""paddle.tensor — the tensor-function namespace.

Analog of reference python/paddle/tensor/ (creation.py, manipulation.py,
math.py, linalg.py, logic.py, random.py, search.py, stat.py, attribute.py
— the functions also attach to the paddle root and as Tensor methods).
Here the implementations live in paddle_tpu.ops (one defop lowering per
family); this namespace re-exports them under the reference's module
layout so `from paddle.tensor.creation import full`-style imports port.
"""
from __future__ import annotations

import sys
import types

from ..ops import *  # noqa: F401,F403
from ..ops import (creation, linalg, logic, manipulation,  # noqa: F401
                   math, reduction)
from .. import ops as _ops


def _synth(name, symbols):
    import importlib
    import importlib.machinery
    m = types.ModuleType(f"{__name__}.{name}")
    m.__spec__ = importlib.machinery.ModuleSpec(m.__name__, None)
    root = importlib.import_module(__name__.rsplit(".", 1)[0])
    for s in symbols:
        fn = getattr(_ops, s, None)
        if fn is None:  # some families live on the paddle root only
            try:
                fn = getattr(root, s)
            except AttributeError:
                fn = None
        if fn is not None:
            setattr(m, s, fn)
    sys.modules[m.__name__] = m
    return m


# reference tensor/random.py
random = _synth("random", [
    "bernoulli", "multinomial", "normal", "rand", "randint", "randn",
    "randperm", "uniform", "poisson", "standard_gamma", "binomial",
    "log_normal", "truncated_normal", "exponential_",
])

# reference tensor/search.py
search = _synth("search", [
    "argmax", "argmin", "argsort", "searchsorted", "bucketize", "index_sample",
    "index_select", "masked_select", "nonzero", "sort", "topk", "where",
    "kthvalue", "mode",
])

# reference tensor/stat.py
stat = _synth("stat", [
    "mean", "median", "nanmedian", "quantile", "nanquantile", "std", "var",
    "numel",
])

# reference tensor/attribute.py
attribute = _synth("attribute", [
    "imag", "real", "is_complex", "is_floating_point", "is_integer",
    "rank", "shape",
])

# register the real ops modules under this package path too, so
# `import paddle_tpu.tensor.math` works like the reference's layout
for _name, _mod in (("creation", creation), ("linalg", linalg),
                    ("logic", logic), ("manipulation", manipulation),
                    ("math", math), ("reduction", reduction)):
    sys.modules[f"{__name__}.{_name}"] = _mod
