"""Top-level v1/compat names (reference python/paddle/__init__.py exports
the fluid-era tensor functions and config helpers at the root; this
module installs the ones with direct 2.0 equivalents). Imported at the
bottom of paddle_tpu/__init__.py."""
from __future__ import annotations

import numpy as _np

from . import ops as _ops
from .core.tensor import Tensor as _Tensor
from .core import dtype as _dtype_mod

__all__ = ["add_n", "mm", "numel", "rank", "shape", "is_tensor",
           "broadcast_shape", "has_inf", "has_nan", "fill_constant",
           "floor_mod", "elementwise_add", "elementwise_sub",
           "elementwise_mul", "elementwise_div", "elementwise_pow",
           "elementwise_mod", "elementwise_floordiv", "reduce_sum",
           "reduce_mean", "reduce_max", "reduce_min", "reduce_prod",
           "get_default_dtype", "set_default_dtype", "set_printoptions",
           "get_cudnn_version", "is_compiled_with_xpu",
           "create_parameter", "create_global_var",
           "get_tensor_from_selected_rows", "VarBase", "LoDTensor",
           "LoDTensorArray"]


def add_n(inputs):
    """reference sum_op.cc (paddle.add_n): elementwise sum of a list."""
    xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    out = xs[0]
    for x in xs[1:]:
        out = _ops.add(out, x)
    return out


def mm(input, mat2):  # noqa: A002
    return _ops.matmul(input, mat2)


def numel(x):
    from .core.tensor import to_tensor
    return to_tensor(_np.asarray(int(_np.prod(x.shape)), _np.int64))


def rank(input):  # noqa: A002
    from .core.tensor import to_tensor
    return to_tensor(_np.asarray(len(input.shape), _np.int32))


def shape(input):  # noqa: A002
    from .core.tensor import to_tensor
    return to_tensor(_np.asarray(input.shape, _np.int32))


def is_tensor(x):
    return isinstance(x, _Tensor)


def broadcast_shape(x_shape, y_shape):
    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def has_inf(x):
    return _ops.any(_ops.isinf(x))


def has_nan(x):
    return _ops.any(_ops.isnan(x))


def fill_constant(shape, dtype, value, name=None):  # noqa: A002
    return _ops.full(shape, value, dtype)


def floor_mod(x, y):
    return _ops.remainder(x, y)


elementwise_add = _ops.add
elementwise_sub = _ops.subtract
elementwise_mul = _ops.multiply
elementwise_div = _ops.divide
elementwise_pow = _ops.pow
elementwise_mod = _ops.remainder
elementwise_floordiv = _ops.floor_divide


def reduce_sum(x, dim=None, keep_dim=False):
    return _ops.sum(x, axis=dim, keepdim=keep_dim)


def reduce_mean(x, dim=None, keep_dim=False):
    return _ops.mean(x, axis=dim, keepdim=keep_dim)


def reduce_max(x, dim=None, keep_dim=False):
    return _ops.max(x, axis=dim, keepdim=keep_dim)


def reduce_min(x, dim=None, keep_dim=False):
    return _ops.min(x, axis=dim, keepdim=keep_dim)


def reduce_prod(x, dim=None, keep_dim=False):
    return _ops.prod(x, axis=dim, keepdim=keep_dim)


_default_dtype = ["float32"]


def get_default_dtype():
    return _default_dtype[0]


def set_default_dtype(d):
    """reference framework.set_default_dtype — consulted by to_tensor's
    float coercion."""
    _default_dtype[0] = str(_np.dtype(d)) if not isinstance(d, str) else d
    return _default_dtype[0]


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference tensor print options — maps onto numpy's (Tensors repr
    through numpy)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def get_cudnn_version():
    return None   # no CUDA in the loop — reference returns None likewise


def is_compiled_with_xpu():
    return False


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference layers.create_parameter: a standalone trainable Tensor
    (registered with the current static Program when tracing)."""
    from . import nn
    holder = nn.Layer()
    return holder.create_parameter(list(shape), attr=attr, is_bias=is_bias,
                                   default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False, name=None):
    from .core.tensor import Tensor
    t = Tensor(_np.full(tuple(shape), value,
                        _np.dtype(dtype if isinstance(dtype, str)
                                  else _np.dtype(dtype))))
    t.persistable = persistable
    return t


def get_tensor_from_selected_rows(x):
    """reference get_tensor_from_selected_rows_op.cc: densify."""
    from .core.selected_rows import SelectedRows
    if isinstance(x, SelectedRows):
        return x.to_dense()
    v = getattr(x, "_value", x)
    if isinstance(v, SelectedRows):
        from .core.tensor import Tensor
        return Tensor(v.to_dense(), _internal=True)
    return x


VarBase = _Tensor                       # dygraph-era name for Tensor

from .core.ragged import RaggedTensor as LoDTensor  # noqa: E402

LoDTensorArray = list                   # array of LoD tensors
