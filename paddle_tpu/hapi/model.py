"""High-level Model API.

Analog of reference python/paddle/hapi/model.py (Model :808, fit :1296,
prepare :1241, StaticGraphAdapter :223 / DynamicGraphAdapter :608).

Design delta (SURVEY.md §7.3): the two adapters collapse into ONE compiled
engine. The layer graph is traced functionally — parameters, buffers and
optimizer slots become pytree inputs/outputs of a pure step function that
jax.jit compiles to a single XLA program (forward + backward + optimizer
fused; buffers donated). That one program per (mode, shapes) replaces both
the static Executor program and the dygraph per-op path. Sharding hooks:
when paddle_tpu.distributed configured a mesh + sharding rules, the same
step is pjit-partitioned (engine consults distributed.sharding).
"""
from __future__ import annotations

import contextlib
import os
import warnings
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as _rng
from ..core import tape as _tape
from ..core.tensor import Tensor
from ..metric import Metric
from ..nn.layer.layers import Layer
from ..framework.io import load as _load, save as _save
from .callbacks import config_callbacks

__all__ = ["Model", "InputSpec"]


class _LazyLoss:
    """`logs["loss"]` placeholder in the async fit loop
    (docs/async_executor.md): materializes the EXACT loss of its own step
    on first read (float()/format()/np.asarray), draining the window in
    submission order so an in-flight failure names the first failing
    step. A callback that consumes the loss every batch (e.g. VisualDL's
    add_scalar) therefore sees exact per-batch values at per-batch sync
    cost; a loop where nothing reads it keeps the pipeline."""

    __slots__ = ("step", "_lval", "_drain", "_val")

    def __init__(self, step, lval, drain):
        self.step = step
        self._lval = lval
        self._drain = drain
        self._val = None

    def _materialize(self):
        """Called by the window drain, in submission order."""
        if self._val is None:
            try:
                self._val = float(np.asarray(self._lval))
            except Exception as e:
                raise RuntimeError(
                    f"hapi pipelined step {self.step} failed: "
                    f"{type(e).__name__}: {e}") from e
            self._lval = None
        return self._val

    def value(self):
        if self._val is None:
            self._drain(self.step)  # in-order: names the first failure
        return self._val if self._val is not None else self._materialize()

    def __float__(self):
        return self.value()

    def __format__(self, spec):
        return format(self.value(), spec)

    def __repr__(self):
        return repr(self.value())

    def __array__(self, dtype=None, copy=None):
        arr = np.asarray(self.value())
        return arr.astype(dtype) if dtype is not None else arr


class InputSpec:
    """Shape/dtype declaration (reference paddle/static/input.py InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_raw(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x)


class _CompiledEngine:
    """Traces net+loss+optimizer into pure jitted step functions."""

    def __init__(self, model):
        self.model = model
        self._train_fn = None
        self._eval_fn = None
        self._pred_fn = None
        self._grad_fn = None
        self._apply_fn = None
        self._accum_grads = None
        self._accum_count = 0
        self._param_names = None
        self._localsgd = None         # replicated-state LocalSGD mode

    # ---- functional pieces -------------------------------------------------
    def _amp_ctx(self):
        import contextlib
        cfg = self.model._amp_configs
        if not cfg:
            return contextlib.nullcontext()
        from .. import amp as amp_mod
        return amp_mod.auto_cast(
            level=cfg["level"], dtype=cfg["dtype"],
            custom_white_list=cfg.get("custom_white_list"),
            custom_black_list=cfg.get("custom_black_list"))

    def _forward_loss(self, params, buffers, inputs, labels, training):
        net = self.model.network
        net.load_functional_state(params, buffers)
        tin = [Tensor(v, stop_gradient=True, _internal=True) for v in inputs]
        with self._amp_ctx():
            outs = net(*tin)
            outs_list = _to_list(outs)
            loss = None
            if self.model._loss is not None and labels is not None:
                tlab = [Tensor(v, stop_gradient=True, _internal=True)
                        for v in labels]
                loss = self.model._compute_loss(outs_list, tlab)
        new_bufs = {n: b._value for n, b in net.named_buffers()}
        raw_outs = [o._value for o in outs_list]
        return loss, raw_outs, new_bufs

    def _sharding_plan(self):
        """When a mesh is active, build GSPMD shardings: batch on dp(+sp),
        params by TP/ZeRO name rules, slots following their params
        (the declarative replacement for fleet meta-optimizer program
        surgery — SURVEY.md §2.2)."""
        from ..distributed import mesh as mesh_mod
        mesh = mesh_mod.get_mesh()
        if mesh is None or int(np.prod(list(mesh.shape.values()))) == 1:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..distributed.sharding import build_param_shardings
        net = self.model.network
        opt = self.model._optimizer
        zero = bool(getattr(opt, "_zero_dp", False)) \
            or bool(getattr(net, "_zero_dp", False))
        params, buffers = net.functional_state()
        param_sh = build_param_shardings(params, mesh, zero_dp=zero)
        repl = NamedSharding(mesh, P())
        batch = NamedSharding(mesh, P("dp") if "dp" in mesh.axis_names
                              else P())
        return {"mesh": mesh, "param": param_sh, "repl": repl,
                "batch": batch}

    def _make_train_step(self):
        """The pure fwd+bwd+update step, shared by the jit/GSPMD path
        (_build_train_fn) and the LocalSGD shard_map path."""
        model = self.model
        opt = model._optimizer
        net = model.network
        params, _ = net.functional_state()
        named = {n: p for n, p in net.named_parameters()}
        trainable = {n for n, p in named.items() if not p.stop_gradient}
        meta = opt._param_meta(named)
        amp_cfg = model._amp_configs
        scaler = amp_cfg.get("scaler") if amp_cfg else None

        def step(params, buffers, slots, lr, t, key, inputs, labels,
                 scale_state):
            with _rng.rng_state(key), _tape.no_grad():
                train_p = {k: v for k, v in params.items() if k in trainable}
                frozen_p = {k: v for k, v in params.items()
                            if k not in trainable}

                def loss_of(tp):
                    full = dict(frozen_p)
                    full.update(tp)
                    loss, raw_outs, new_bufs = self._forward_loss(
                        full, buffers, inputs, labels, True)
                    lv = loss._value
                    if scaler is not None:
                        # loss scaling inside the differentiated region
                        # (reference amp/grad_scaler.py scale())
                        lv = lv * scale_state["scale"].astype(lv.dtype)
                    return lv, (raw_outs, new_bufs, loss._value)

                (_, (outs, new_bufs, lval)), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(train_p)
                if scaler is not None:
                    # check_finite_and_unscale + update_loss_scaling fused
                    # into the step; non-finite steps keep old params/slots
                    grads, found, scale_state = scaler.apply_pure(
                        grads, scale_state)
                    new_train, new_slots = opt.apply_gradients_pure(
                        train_p, grads, slots, lr, t, param_meta=meta)
                    keep = lambda old, new: jnp.where(found, old, new)  # noqa: E731
                    new_train = jax.tree_util.tree_map(keep, train_p,
                                                       new_train)
                    new_slots = jax.tree_util.tree_map(keep, dict(slots),
                                                       new_slots)
                else:
                    new_train, new_slots = opt.apply_gradients_pure(
                        train_p, grads, slots, lr, t, param_meta=meta)
                new_params = dict(frozen_p)
                new_params.update(new_train)
            return lval, outs, new_bufs, new_params, new_slots, scale_state

        return step

    def _build_train_fn(self, example_in=(), example_lab=()):
        step = self._make_train_step()
        amp_cfg = self.model._amp_configs
        scaler = amp_cfg.get("scaler") if amp_cfg else None
        plan = self._sharding_plan()
        if plan is None:
            return jax.jit(step, donate_argnums=(0, 1, 2))
        # distributed: partition the whole step via GSPMD
        opt_state = self.model._optimizer._slots
        slot_sh = {k: {s: plan["param"][k] for s in opt_state.get(k, {})}
                   for k in opt_state}
        buffers_sh = {n: plan["repl"] for n, _ in
                      self.model.network.named_buffers()}
        scale_sh = jax.tree_util.tree_map(lambda _: plan["repl"],
                                          {"scale": 0, "good": 0, "bad": 0}) \
            if scaler is not None else None

        def data_sh(example):  # scalar leaves (rank 0) cannot ride P('dp')
            def leaf_sh(a):
                if np.ndim(a) < 1:
                    return plan["repl"]
                dp = plan["mesh"].shape.get("dp", 1)
                if dp > 1 and np.shape(a)[0] % dp:
                    # a batch the dp axis cannot divide (e.g. a leaked
                    # wider-than-batch default mesh) degrades to
                    # replicated input, same contract as
                    # sharding._validate_divisible — loudly, not a
                    # pjit divisibility crash
                    from ..core import monitor as _monitor
                    _monitor.stat_add("sharding.nondivisible_fallback")
                    return plan["repl"]
                return plan["batch"]
            return jax.tree_util.tree_map(leaf_sh, tuple(example))

        return jax.jit(
            step,
            in_shardings=(plan["param"], buffers_sh, slot_sh, plan["repl"],
                          plan["repl"], plan["repl"], data_sh(example_in),
                          data_sh(example_lab), scale_sh),
            donate_argnums=(0, 1, 2))

    # ---- LocalSGD (strategy.localsgd / adaptive_localsgd) ------------------
    def _localsgd_cfg(self):
        """Live strategy.localsgd knob (reference
        meta_optimizers/localsgd_optimizer.py LocalSGDOptimizer /
        AdaptiveLocalSGDOptimizer): requires a mesh with dp>=2. Returns
        None when the plain path applies."""
        strat = getattr(self.model._optimizer, "_dist_strategy", None)
        if strat is None or not (getattr(strat, "localsgd", False)
                                 or getattr(strat, "adaptive_localsgd",
                                            False)):
            return None
        from ..distributed import mesh as mesh_mod
        mesh = mesh_mod.get_mesh()
        if mesh is None or "dp" not in mesh.axis_names \
                or mesh.shape["dp"] < 2:
            return None
        if self.model._amp_configs and \
                self.model._amp_configs.get("scaler"):
            raise ValueError(
                "strategy.localsgd does not compose with dynamic loss "
                "scaling (the reference's LocalSGDOptimizer is likewise "
                "incompatible with AMP program rewriting); use bf16 O2")
        cfg = dict(getattr(strat, "localsgd_configs", {}) or {})
        return {"mesh": mesh, "k": max(1, int(cfg.get("k_steps", 4) or 4)),
                "adaptive": bool(getattr(strat, "adaptive_localsgd", False)),
                "max_k": int(cfg.get("max_k_steps", 16) or 16),
                "rel_tol": float(cfg.get("rel_tol", 0.01) or 0.01)}

    def _build_localsgd_fn(self, k, mesh):
        """shard_map step over dp: each dp shard owns a PRIVATE copy of
        params/slots (leading replica dim), steps locally, and parameters
        are pmean-averaged only every k-th step — one lax.cond'ed ICI
        collective instead of a per-step gradient all-reduce
        (distributed/localsgd.py carries the standalone form)."""
        from jax.sharding import PartitionSpec as P
        step = self._make_train_step()

        def spmd(params, buffers, slots, lr, t, key, inputs, labels,
                 counter):
            one = lambda q: jax.tree_util.tree_map(lambda x: x[0], q)  # noqa: E731
            lift = lambda q: jax.tree_util.tree_map(lambda x: x[None], q)  # noqa: E731
            key = jax.random.fold_in(key, jax.lax.axis_index("dp"))
            lval, outs, new_bufs, new_p, new_s, _ = step(
                one(params), buffers, one(slots), lr, t, key,
                inputs, labels, {})
            c = counter[0] + 1

            def sync(q):
                return jax.tree_util.tree_map(
                    lambda x: jax.lax.pmean(x, "dp"), q)

            new_p = jax.lax.cond(c % k == 0, sync, lambda q: q, new_p)
            # buffers (e.g. BN running stats) stay replicated: average
            new_bufs = sync(new_bufs)
            lval = jax.lax.pmean(lval, "dp")
            return lval, outs, new_bufs, lift(new_p), lift(new_s), c[None]

        st = self._localsgd
        pspec = jax.tree_util.tree_map(lambda _: P("dp"), st["params"])
        sspec = jax.tree_util.tree_map(lambda _: P("dp"), st["slots"])
        bspec = jax.tree_util.tree_map(
            lambda _: P(), {n: 0 for n, _ in
                            self.model.network.named_buffers()})
        from ..distributed import mesh as _mesh_mod
        return jax.jit(_mesh_mod.shard_map(
            spmd, mesh=mesh,
            in_specs=(pspec, bspec, sspec, P(), P(), P(), P("dp"),
                      P("dp"), P("dp")),
            out_specs=(P(), P("dp"), bspec, pspec, sspec, P("dp"))))

    def _train_batch_localsgd(self, cfg, raw_in, raw_lab):
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        model = self.model
        net = model.network
        opt = model._optimizer
        mesh = cfg["mesh"]
        if self._localsgd is None:
            params, buffers = net.functional_state()
            named = dict(net.named_parameters())
            opt._ensure_slots({n: v for n, v in params.items()
                               if not named[n].stop_gradient})
            slots = {n: opt._slots[n] for n in opt._slots
                     if n in params and not named[n].stop_gradient}
            n = mesh.shape["dp"]
            sh = NamedSharding(mesh, P("dp"))
            rep = lambda q: jax.tree_util.tree_map(  # noqa: E731
                lambda x: jax.device_put(
                    jnp.broadcast_to(x[None], (n,) + x.shape), sh), q)
            self._localsgd = {
                "params": rep(params), "slots": rep(slots),
                "counter": jax.device_put(jnp.zeros((n,), jnp.int32), sh),
                "k": cfg["k"], "fns": {}, "last_sync_loss": None}
        st = self._localsgd
        k = st["k"]
        if k not in st["fns"]:
            st["fns"][k] = self._build_localsgd_fn(k, mesh)
        opt._step_count += 1
        params, buffers = net.functional_state()
        lval, outs, new_bufs, st["params"], st["slots"], st["counter"] = \
            st["fns"][k](st["params"], buffers, st["slots"],
                         jnp.asarray(opt.get_lr(), jnp.float32),
                         jnp.asarray(opt._step_count, jnp.int32),
                         _rng.next_key(), raw_in, raw_lab, st["counter"])
        self._write_back({}, new_bufs)
        c = int(np.asarray(st["counter"])[0])
        if cfg["adaptive"] and c % k == 0:
            loss = float(np.asarray(lval))
            last = st["last_sync_loss"]
            if last is not None and loss > last * (1 - cfg["rel_tol"]):
                st["k"] = min(k + 1, cfg["max_k"])
            st["last_sync_loss"] = loss
        if c % k == 0:
            # synced boundary: the replicas agree — surface the averaged
            # params to the net so eval/save/callbacks see fresh weights
            self._write_back(jax.tree_util.tree_map(
                lambda x: x[0], st["params"]), {})
        return lval, outs

    def finalize_localsgd(self):
        """Final cross-replica average written back into the network;
        called at fit() end and before eval/predict/save."""
        st = self._localsgd
        if st is None:
            return
        avg = jax.tree_util.tree_map(
            lambda x: jnp.mean(x.astype(jnp.float32), axis=0).astype(
                x.dtype), st["params"])
        self._write_back(avg, {})
        slot_avg = jax.tree_util.tree_map(
            lambda x: jnp.mean(x.astype(jnp.float32), axis=0).astype(
                x.dtype), st["slots"])
        self.model._optimizer._slots.update(slot_avg)
        self._localsgd = None

    def _build_grad_fn(self):
        """Forward+backward only — used for gradient accumulation
        (GradientMergeOptimizer analog, reference fluid/optimizer.py:5004).
        With a GradScaler the micro-batch loss is scaled, so accumulated
        grads stay scaled until the apply step unscales them once."""
        net = self.model.network
        named = {n: p for n, p in net.named_parameters()}
        trainable = {n for n, p in named.items() if not p.stop_gradient}
        amp_cfg = self.model._amp_configs
        scaler = amp_cfg.get("scaler") if amp_cfg else None

        def gstep(params, buffers, key, inputs, labels, scale):
            with _rng.rng_state(key), _tape.no_grad():
                train_p = {k: v for k, v in params.items() if k in trainable}
                frozen_p = {k: v for k, v in params.items()
                            if k not in trainable}

                def loss_of(tp):
                    full = dict(frozen_p)
                    full.update(tp)
                    loss, raw_outs, new_bufs = self._forward_loss(
                        full, buffers, inputs, labels, True)
                    lv = loss._value
                    if scaler is not None:
                        lv = lv * scale.astype(lv.dtype)
                    return lv, (raw_outs, new_bufs, loss._value)

                (_, (outs, new_bufs, lval)), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(train_p)
            return lval, outs, new_bufs, grads

        return jax.jit(gstep)

    def _build_apply_fn(self):
        opt = self.model._optimizer
        named = dict(self.model.network.named_parameters())
        meta = opt._param_meta(named)
        amp_cfg = self.model._amp_configs
        scaler = amp_cfg.get("scaler") if amp_cfg else None

        def apply(params, slots, grads, lr, t, inv_count, scale_state):
            if scaler is not None:
                # one unscale+finite-check over the merged grads, then the
                # same found_inf gating as the fused path
                grads, found, scale_state = scaler.apply_pure(
                    grads, scale_state)
            grads = {k: g * inv_count for k, g in grads.items()}
            train_p = {k: params[k] for k in grads}
            new_train, new_slots = opt.apply_gradients_pure(
                train_p, grads, slots, lr, t, param_meta=meta)
            if scaler is not None:
                keep = lambda old, new: jnp.where(found, old, new)  # noqa: E731
                new_train = jax.tree_util.tree_map(keep, train_p, new_train)
                new_slots = jax.tree_util.tree_map(keep, dict(slots),
                                                   new_slots)
            new_params = dict(params)
            new_params.update(new_train)
            return new_params, new_slots, scale_state

        return jax.jit(apply, donate_argnums=(0, 1))

    def _build_eval_fn(self):
        def step(params, buffers, key, inputs, labels):
            with _rng.rng_state(key), _tape.no_grad():
                loss, raw_outs, _ = self._forward_loss(
                    params, buffers, inputs, labels, False)
            lval = loss._value if loss is not None else jnp.zeros(())
            return lval, raw_outs

        return jax.jit(step)

    def _build_pred_fn(self):
        def step(params, buffers, key, inputs):
            with _rng.rng_state(key), _tape.no_grad():
                _, raw_outs, _ = self._forward_loss(params, buffers, inputs,
                                                    None, False)
            return raw_outs

        return jax.jit(step)

    # ---- public steps ------------------------------------------------------
    def train_batch(self, inputs, labels, update=True):
        with _eager_scope():
            return self._train_batch_impl(inputs, labels, update=update)

    def _train_batch_impl(self, inputs, labels, update=True):
        model = self.model
        net = model.network
        net.train()
        opt = model._optimizer
        params, buffers = net.functional_state()
        named = dict(net.named_parameters())
        opt._ensure_slots({k: v for k, v in params.items()
                           if not named[k].stop_gradient})
        slots = {k: opt._slots[k] for k in opt._slots
                 if k in params and not named[k].stop_gradient}
        raw_in = tuple(_to_raw(v) for v in inputs)
        raw_lab = tuple(_to_raw(v) for v in labels)
        accumulating = (not update) or self._accum_grads is not None

        lcfg = self._localsgd_cfg()
        if lcfg is not None and not accumulating:
            return self._train_batch_localsgd(lcfg, raw_in, raw_lab)

        if not accumulating:
            # fast path: forward+backward+update fused in one XLA program
            if self._train_fn is None:
                from .. import profiler as _prof
                with _prof.RecordEvent("hapi/build_train_fn"):
                    self._train_fn = self._build_train_fn(raw_in, raw_lab)
            amp_cfg = self.model._amp_configs
            scaler = amp_cfg.get("scaler") if amp_cfg else None
            scale_state = scaler.scale_state() if scaler is not None else {}
            opt._step_count += 1
            from .. import profiler as _prof
            from ..core import monitor as _monitor
            _monitor.stat_add("hapi/train_steps")
            with _prof.RecordEvent("hapi/train_step"):
                lval, outs, new_bufs, new_params, new_slots, scale_state = \
                    self._train_fn(
                        params, buffers, slots,
                        jnp.asarray(opt.get_lr(), jnp.float32),
                        jnp.asarray(opt._step_count, jnp.int32),
                        _rng.next_key(), raw_in, raw_lab, scale_state)
            if scaler is not None:
                scaler.load_scale_state(scale_state)
            from ..core import flags as _flags
            if _flags.flag("FLAGS_check_nan_inf"):
                from ..core.numeric_check import sweep
                sweep({"loss": lval, "params": new_params},
                      "train_batch step")
            self._write_back(new_params, new_bufs)
            opt._slots.update(new_slots)
            return lval, outs

        # accumulation path: grads summed across micro-batches, applied on
        # the update call (grads averaged by micro-batch count)
        amp_cfg = self.model._amp_configs
        scaler = amp_cfg.get("scaler") if amp_cfg else None
        if self._grad_fn is None:
            self._grad_fn = self._build_grad_fn()
        scale = scaler.scale_state()["scale"] if scaler is not None \
            else jnp.ones((), jnp.float32)
        lval, outs, new_bufs, grads = self._grad_fn(
            params, buffers, _rng.next_key(), raw_in, raw_lab, scale)
        self._write_back({}, new_bufs)
        self._restore(params, {})
        if self._accum_grads is None:
            self._accum_grads = grads
            self._accum_count = 1
        else:
            self._accum_grads = jax.tree_util.tree_map(
                jnp.add, self._accum_grads, grads)
            self._accum_count += 1
        if update:
            if self._apply_fn is None:
                self._apply_fn = self._build_apply_fn()
            opt._step_count += 1
            scale_state = scaler.scale_state() if scaler is not None else {}
            new_params, new_slots, scale_state = self._apply_fn(
                params, slots, self._accum_grads,
                jnp.asarray(opt.get_lr(), jnp.float32),
                jnp.asarray(opt._step_count, jnp.int32),
                jnp.asarray(1.0 / self._accum_count, jnp.float32),
                scale_state)
            if scaler is not None:
                scaler.load_scale_state(scale_state)
            self._write_back(new_params, {})
            opt._slots.update(new_slots)
            self._accum_grads = None
            self._accum_count = 0
        return lval, outs

    def eval_batch(self, inputs, labels):
        with _eager_scope():
            return self._eval_batch_impl(inputs, labels)

    def _eval_batch_impl(self, inputs, labels):
        self.finalize_localsgd()
        net = self.model.network
        net.eval()
        params, buffers = net.functional_state()
        if self._eval_fn is None:
            self._eval_fn = self._build_eval_fn()
        lval, outs = self._eval_fn(
            params, buffers, _rng.next_key(),
            tuple(_to_raw(v) for v in inputs),
            tuple(_to_raw(v) for v in labels) if labels else None)
        self._restore(params, buffers)
        return lval, outs

    def predict_batch(self, inputs):
        with _eager_scope():
            return self._predict_batch_impl(inputs)

    def _predict_batch_impl(self, inputs):
        self.finalize_localsgd()
        net = self.model.network
        net.eval()
        params, buffers = net.functional_state()
        if self._pred_fn is None:
            self._pred_fn = self._build_pred_fn()
        outs = self._pred_fn(params, buffers, _rng.next_key(),
                             tuple(_to_raw(v) for v in inputs))
        self._restore(params, buffers)
        return outs

    def _write_back(self, new_params, new_bufs):
        net = self.model.network
        for n, p in net.named_parameters():
            if n in new_params:
                p._value = new_params[n]
                p._node = None
                p.grad = None
        for n, b in net.named_buffers():
            if n in new_bufs:
                b._value = new_bufs[n]
                b._node = None

    def _restore(self, params, buffers):
        # forward inside jit seats tracers into the layer; put values back
        net = self.model.network
        net.load_functional_state(params, buffers)


@contextlib.contextmanager
def _eager_scope():
    """The hapi engine is mode-independent (one compiled step replaces
    BOTH reference adapters, StaticGraphAdapter :223 / DynamicGraphAdapter
    :608) — it always traces its own jitted program. Suspend static-graph
    recording for the duration so `paddle.enable_static()` elsewhere in
    the script doesn't make engine ops append to a Program."""
    from ..static.program import _state
    was = _state.enabled
    _state.enabled = False
    try:
        yield
    finally:
        _state.enabled = was


class Model:
    def __init__(self, network, inputs=None, labels=None):
        from ..static.program import Variable as _StaticVar
        for _n, p in network.named_parameters():
            if isinstance(p, _StaticVar) and p._value is None:
                raise TypeError(
                    "Model received a network built under "
                    "paddle.enable_static() (its parameters are static "
                    "Variables). The hapi engine compiles its own step and "
                    "serves both execution modes — construct the network "
                    "in dygraph (before enable_static), or use the "
                    "paddle.static Executor workflow for Program-based "
                    "training.")
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._engine = _CompiledEngine(self)
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        if loss is not None and not (isinstance(loss, Layer) or callable(loss)):
            raise TypeError("loss must be a Layer or callable")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be Metric instances, got {m}")
        self._amp_configs = self._parse_amp(amp_configs)
        self._apply_strategy_recompute()
        return self

    def _apply_strategy_recompute(self):
        """strategy.recompute -> Layer.enable_recompute on the designated
        blocks (reference RecomputeOptimizer applied via fleet strategy;
        fluid/optimizer.py:4526). recompute_configs:
          - "layers": fnmatch patterns over named_sublayers, or
          - default: every TransformerEncoderLayer/TransformerDecoderLayer.
        """
        strat = getattr(self._optimizer, "_dist_strategy", None)
        if strat is None or not getattr(strat, "recompute", False):
            return
        cfg = getattr(strat, "recompute_configs", {}) or {}
        policy = cfg.get("policy", "nothing")
        patterns = cfg.get("layers")
        net = self.network
        if patterns:
            import fnmatch
            hits = [sub for name, sub in net.named_sublayers()
                    if any(fnmatch.fnmatch(name, p) for p in patterns)]
        else:
            from ..nn.layer.transformer import (TransformerDecoderLayer,
                                                TransformerEncoderLayer)
            hits = [sub for _, sub in net.named_sublayers()
                    if isinstance(sub, (TransformerEncoderLayer,
                                        TransformerDecoderLayer))]
        for sub in hits:
            sub.enable_recompute(policy=policy)

    def _parse_amp(self, amp_configs):
        """amp_configs: None | 'O1'/'O2' | dict (reference hapi/model.py
        _check_amp_configs + amp/auto_cast.py). O2 casts parameters to the
        amp dtype and enables f32 master weights in the optimizer."""
        if amp_configs is None and self._optimizer is not None:
            # fleet strategy amp knob reaches the engine declaratively
            strat = getattr(self._optimizer, "_dist_strategy", None)
            if strat is not None and getattr(strat, "amp", False):
                amp_configs = dict(strat.amp_configs)
                if amp_configs.pop("use_pure_bf16", False):
                    amp_configs.setdefault("level", "O2")
        if amp_configs is None:
            return None
        from .. import amp as amp_mod
        if isinstance(amp_configs, str):
            amp_configs = {"level": amp_configs}
        cfg = dict(amp_configs)
        level = cfg.get("level", "O1")
        if level == "O0":
            return None
        if level not in ("O1", "O2"):
            raise ValueError(f"amp level must be O0/O1/O2, got {level!r}")
        dtype = cfg.get("dtype", "bfloat16")
        scaler = None
        # loss scaling matters for f16's narrow exponent range; bf16 matches
        # f32's range so the scaler is skipped unless explicitly forced
        want_scaler = (str(dtype) in ("float16", "fp16")
                       and (cfg.get("use_dynamic_loss_scaling", True)
                            or "init_loss_scaling" in cfg)) \
            or cfg.get("force_loss_scaling", False)
        if want_scaler:
            scaler = amp_mod.GradScaler(
                init_loss_scaling=cfg.get("init_loss_scaling", 2.0 ** 15),
                incr_ratio=cfg.get("incr_ratio", 2.0),
                decr_ratio=cfg.get("decr_ratio", 0.5),
                incr_every_n_steps=cfg.get("incr_every_n_steps", 1000),
                decr_every_n_nan_or_inf=cfg.get("decr_every_n_nan_or_inf", 2),
                use_dynamic_loss_scaling=cfg.get(
                    "use_dynamic_loss_scaling", True))
        if level == "O2" and self._optimizer is not None:
            amp_mod.decorate(self.network, self._optimizer, level="O2",
                             dtype=dtype)
        return {"level": level, "dtype": dtype, "scaler": scaler,
                "custom_white_list": cfg.get("custom_white_list"),
                "custom_black_list": cfg.get("custom_black_list")}

    def _compute_loss(self, outputs, labels):
        loss = self._loss
        if isinstance(loss, list):
            vals = [fn(o, l) for fn, o, l in zip(loss, outputs, labels)]
            total = vals[0]
            for v in vals[1:]:
                total = total + v
            return total
        return loss(*(outputs + labels))

    # -- batch-level API -----------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        lval, outs = self._engine.train_batch(_to_list(inputs),
                                              _to_list(labels),
                                              update=update)
        return self._wrap_loss(lval)

    def eval_batch(self, inputs, labels=None):
        lval, outs = self._engine.eval_batch(_to_list(inputs),
                                             _to_list(labels))
        return self._wrap_loss(lval)

    def predict_batch(self, inputs):
        outs = self._engine.predict_batch(_to_list(inputs))
        return [np.asarray(o) for o in outs]

    @staticmethod
    def _wrap_loss(lval):
        return [float(np.asarray(lval))]

    # -- loops ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None,
            auto_checkpoint_dir=None, auto_checkpoint_freq=50,
            keep_checkpoint_max=3):
        """... `auto_checkpoint_dir` enables preemption-safe training:
        async step-atomic checkpoints (params, optimizer, scaler, rng,
        counters) every `auto_checkpoint_freq` steps, keep-latest-
        `keep_checkpoint_max`, and resume-from-latest on the next fit()
        (reference fluid/incubate/checkpoint/auto_checkpoint.py:71)."""
        from ..io import DataLoader, Dataset

        assert self._optimizer is not None and self._loss is not None, \
            "call prepare(optimizer=..., loss=...) before fit()"
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        do_eval = eval_loader is not None
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                save_freq=save_freq, save_dir=save_dir,
                                verbose=verbose,
                                metrics=self._metrics_name())
        def _loader_state():
            if hasattr(train_loader, "state_dict"):
                try:
                    return train_loader.state_dict()
                except Exception:
                    return None
            return None

        acp, start_epoch, skip_steps, step_offset = None, 0, 0, 0
        if auto_checkpoint_dir is not None:
            from ..incubate.checkpoint import TrainingCheckpoint
            acp = TrainingCheckpoint(auto_checkpoint_dir,
                                     keep=keep_checkpoint_max,
                                     save_interval_steps=auto_checkpoint_freq)
            resumable = train_loader if hasattr(
                train_loader, "load_state_dict") else None
            counters = acp.restore_into(self, data_loader=resumable)
            if counters is not None:
                self._global_step = counters["global_step"]
                start_epoch = counters["epoch"]
                skip_steps = counters["step"] + 1
                if counters.get("data_resumed"):
                    # the loader fast-forwards itself (sampler-level
                    # skip, exact shuffle state) — fit only offsets the
                    # step numbering instead of replaying batches
                    step_offset, skip_steps = skip_steps, 0
                    # a cursor at the epoch boundary — the natural end
                    # OR fit's steps= cap (a boundary the loader can't
                    # see) — means that epoch is DONE: roll fit's epoch
                    # label in step with the loader's auto-roll, else
                    # the resumed loop trains one extra loader epoch
                    # under a stale label
                    bounds = [steps]
                    try:
                        bounds.append(len(train_loader))
                    except TypeError:
                        pass
                    epoch_len = min(b for b in bounds if b is not None) \
                        if any(b is not None for b in bounds) else None
                    if epoch_len is not None and step_offset >= epoch_len:
                        start_epoch, step_offset = start_epoch + 1, 0
                        # steps= truncation: advance the loader past the
                        # truncated epoch's permutation so the next
                        # iteration starts the new epoch fresh instead
                        # of replaying the truncated epoch's tail (a
                        # natural epoch end auto-rolls; this is a no-op
                        # there)
                        if hasattr(resumable, "roll_resumed_epoch"):
                            resumable.roll_resumed_epoch()
                elif steps is not None and skip_steps >= steps:
                    start_epoch, skip_steps = start_epoch + 1, 0
            else:
                self._global_step = 0
        self._acp = acp

        guard = contextlib.nullcontext()
        if acp is not None:
            from ..incubate.checkpoint import PreemptionGuard
            self._acp_pos = (start_epoch,
                             max(skip_steps + step_offset - 1, 0))
            # the guard capture uses the data state snapshotted at the
            # last COMPLETED batch (kept in step with _acp_pos by
            # _run_one_epoch), never the live loader cursor: a SIGTERM
            # mid-batch would otherwise save a cursor one batch ahead
            # of the applied optimizer state and the resume would skip
            # that batch
            self._acp_data_state = _loader_state()
            guard = PreemptionGuard(
                acp, lambda: (self._global_step,
                              acp.capture(self, *self._acp_pos,
                                          self._global_step,
                                          data_state=getattr(
                                              self, "_acp_data_state",
                                              None))))

        cbks.on_begin("train")
        logs = {}
        with guard:
            for epoch in range(start_epoch, epochs):
                cbks.on_epoch_begin(epoch)
                logs = self._run_one_epoch(train_loader, cbks, "train",
                                           num_iters=num_iters,
                                           accum=accumulate_grad_batches,
                                           epoch=epoch,
                                           skip_steps=skip_steps,
                                           step_offset=step_offset,
                                           log_freq=log_freq)
                skip_steps = 0
                step_offset = 0
                cbks.on_epoch_end(epoch, logs)
                if do_eval and epoch % eval_freq == 0:
                    eval_logs = self.evaluate(eval_loader, callbacks=cbks,
                                              _inside_fit=True)
                    logs.update({f"eval_{k}": v
                                 for k, v in eval_logs.items()})
                if self.stop_training:
                    break
        if acp is not None:
            acp.wait()
        self._engine.finalize_localsgd()
        cbks.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _inside_fit=False):
        from ..io import DataLoader, Dataset
        if isinstance(eval_data, Dataset):
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = eval_data
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            inputs, labels = self._split_batch(batch)
            lval, outs = self._engine.eval_batch(inputs, labels)
            losses.append(float(np.asarray(lval)))
            self._update_metrics(outs, labels)
        logs = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = res if isinstance(res, list) else [res]
            logs.update(dict(zip(names, vals)))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        from ..io import DataLoader, Dataset
        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = test_data
        outputs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch, allow_no_label=True)
            outs = self.predict_batch(inputs)
            outputs.append(outs)
        if not outputs:
            return []
        # transpose: list of per-batch lists -> per-output lists
        n_out = len(outputs[0])
        merged = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            merged = [np.concatenate(m) for m in merged]
        return merged

    def _run_one_epoch(self, loader, cbks, mode, num_iters=None, accum=1,
                       epoch=0, skip_steps=0, step_offset=0, log_freq=10):
        from collections import deque
        from ..core import flags as _flags
        for m in self._metrics:
            m.reset()
        logs = {}
        acp = getattr(self, "_acp", None)
        # async hot loop (docs/async_executor.md): the per-step
        # `float(np.asarray(loss))` host sync was the only thing forcing
        # the loop to wait for the device. With no metrics (metric.update
        # reads the outputs on host every batch) and no grad accumulation
        # bookkeeping, logs["loss"] becomes a _LazyLoss and the window
        # keeps up to FLAGS_executor_max_inflight steps un-materialized;
        # it drains at log_freq boundaries, at the window bound, and
        # whenever a consumer actually reads a loss. An in-flight failure
        # surfaces at the next drain, naming the step.
        inflight = int(_flags.flag("FLAGS_executor_max_inflight"))
        async_loop = (mode == "train" and inflight > 0
                      and not self._metrics and accum <= 1)
        window: deque = deque()

        def drain(through=None):
            # through=None retires only past the window bound; a boundary
            # passes `through` to materialize everything up to that step
            while window and ((through is not None
                               and window[0].step <= through)
                              or len(window) > inflight):
                window.popleft()._materialize()

        from ..distributed import elastic as _elastic
        for step, batch in enumerate(loader, start=step_offset):
            if step < skip_steps:
                continue  # resumed mid-epoch: fast-forward consumed batches
            cbks.on_batch_begin(mode, step, logs)
            inputs, labels = self._split_batch(batch)
            update = accum <= 1 or (step + 1) % accum == 0
            lval, outs = self._engine.train_batch(inputs, labels,
                                                  update=update)
            if self._lr_sched_step_on_batch():
                self._optimizer._learning_rate.step()
            if async_loop:
                lazy = _LazyLoss(step, lval, drain)
                window.append(lazy)
                if (step + 1) % max(log_freq, 1) == 0:
                    drain(through=step)  # boundary: window fully retired
                else:
                    drain()  # retire past the window bound only
                logs["loss"] = lazy  # exact for whoever reads it
            else:
                logs["loss"] = float(np.asarray(lval))
            logs["batch_size"] = np.asarray(inputs[0]).shape[0]
            metric_logs = self._update_metrics(outs, labels)
            logs.update(metric_logs)
            if mode == "train":
                _elastic.notify_step()  # StallMonitor/Heartbeat pulse
            if acp is not None and mode == "train":
                # account the completed batch BEFORE callbacks: a SIGTERM
                # raised from a callback must capture this step as done
                self._global_step = getattr(self, "_global_step", 0) + 1
                self._acp_pos = (epoch, step)
                data_state = None
                if hasattr(loader, "state_dict"):
                    try:
                        data_state = loader.state_dict()
                    except Exception:
                        data_state = None
                # batch-end snapshot for the PreemptionGuard capture:
                # consistent with _acp_pos/_global_step by construction
                self._acp_data_state = data_state
                acp.maybe_save(self, epoch, step, self._global_step,
                               data_state=data_state)
            cbks.on_batch_end(mode, step, logs)
            if num_iters is not None and step + 1 >= num_iters:
                break
        if window:  # epoch boundary: materialize the tail
            drain(through=window[-1].step)
        if async_loop and isinstance(logs.get("loss"), _LazyLoss):
            logs["loss"] = logs["loss"].value()  # plain float leaves fit
        if self._lr_sched_step_on_epoch():
            self._optimizer._learning_rate.step()
        return logs

    def _lr_sched_step_on_batch(self):
        from ..optimizer import lr as lr_mod
        sched = self._optimizer._lr_scheduler if self._optimizer else None
        return isinstance(sched, (lr_mod.NoamDecay, lr_mod.OneCycleLR,
                                  lr_mod.CyclicLR, lr_mod.LinearWarmup))

    def _lr_sched_step_on_epoch(self):
        sched = self._optimizer._lr_scheduler if self._optimizer else None
        return sched is not None and not self._lr_sched_step_on_batch()

    def _update_metrics(self, outs, labels):
        logs = {}
        for m in self._metrics:
            pre = m.compute(outs[0], *[np.asarray(_to_raw(l)) for l in labels])
            if isinstance(pre, tuple):
                m.update(*pre)
            else:
                m.update(pre)
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = res if isinstance(res, list) else [res]
            logs.update(dict(zip(names, vals)))
        return logs

    def _split_batch(self, batch, allow_no_label=False):
        n_in = max(len(self._inputs), 1)
        if isinstance(batch, (list, tuple)):
            batch = list(batch)
            if len(batch) == 1:
                return batch, []
            if allow_no_label and len(batch) <= n_in:
                return batch, []
            inputs = batch[:n_in]
            labels = batch[n_in:]
            return inputs, labels
        return [batch], []

    def _metrics_name(self):
        out = ["loss"]
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            out.extend(names)
        return out

    # -- persistence ---------------------------------------------------------
    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def state_dict(self):
        return self.network.state_dict()

    def save(self, path, training=True):
        """path prefix: writes {path}.pdparams (+ {path}.pdopt if training)."""
        self._engine.finalize_localsgd()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = _load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(_load(opt_path))
        # drop compiled steps: weights changed wholesale
        self._engine = _CompiledEngine(self)
        return self

    def summary(self, input_size=None, dtype=None):
        if input_size is not None:
            # full layer table with output shapes (hapi/summary.py — the
            # single implementation behind paddle.summary too)
            from .summary import summary as _summary
            return _summary(self.network, input_size,
                            dtypes=[dtype] if dtype else None)
        rows = []
        total = trainable = 0
        for name, p in self.network.named_parameters():
            rows.append((name, p.shape, p.size))
            total += p.size
            if not p.stop_gradient:
                trainable += p.size
        width = max((len(r[0]) for r in rows), default=10) + 2
        lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Params':<12}"]
        for name, shape, size in rows:
            lines.append(f"{name:<{width}}{str(list(shape)):<20}{size:<12}")
        lines.append(f"Total params: {total:,}")
        lines.append(f"Trainable params: {trainable:,}")
        text = "\n".join(lines)
        print(text)
        return {"total_params": total, "trainable_params": trainable}
