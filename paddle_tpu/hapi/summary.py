"""Model introspection: paddle.summary and paddle.flops.

Analog of reference python/paddle/hapi/model_summary.py (layer table via
forward hooks) and hapi/dynamic_flops.py (per-layer flop counting with a
hand-maintained formula registry). Design delta for flops: XLA's cost
analysis of the compiled forward is exact and covers every op, so the
formula registry disappears (profiler.cost_analysis).
"""
from __future__ import annotations

import numpy as np

__all__ = ["summary", "flops"]


def _example_inputs(input_size, dtypes):
    import jax.numpy as jnp
    from .model import InputSpec

    def norm(one):
        if isinstance(one, InputSpec):
            return list(one.shape), str(one.dtype)
        return list(one), None

    if isinstance(input_size, InputSpec):
        sizes = [norm(input_size)]
    elif isinstance(input_size, (tuple, list)) and input_size and \
            isinstance(input_size[0], (tuple, list, InputSpec)):
        sizes = [norm(s) for s in input_size]
    else:
        sizes = [norm(input_size)]
    dtypes = dtypes or [None] * len(sizes)
    from ..core.dtype import to_jax_dtype
    out = []
    for (shape, spec_dt), dt in zip(sizes, dtypes):
        shape = [1 if (d is None or d == -1) else int(d) for d in shape]
        jd = to_jax_dtype(dt or spec_dt or "float32")
        if jnp.issubdtype(jd, jnp.integer):
            out.append(jnp.zeros(shape, jd))
        else:
            out.append(jnp.ones(shape, jd))
    return out


def _snapshot_modes(net):
    return [(sub, sub.training) for _, sub in
            net.named_sublayers(include_self=True)]


def _restore_modes(snapshot):
    # reapply per-sublayer flags: a blanket net.train() would clobber
    # deliberately-frozen sublayers (e.g. eval-mode BN during fine-tuning)
    for sub, flag in snapshot:
        sub.training = flag


def summary(net, input_size, dtypes=None):
    """Layer-by-layer table: output shapes + parameter counts (reference
    hapi/model_summary.py summary). Returns {'total_params': ...,
    'trainable_params': ...}."""
    from ..core import tape as _tape
    from ..core.tensor import Tensor

    rows = []
    hooks = []

    def mk_hook(name, layer):
        def hook(lyr, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) \
                else outputs
            shape = list(out.shape) if hasattr(out, "shape") else []
            n_params = sum(int(np.prod(p.shape))
                           for p in lyr._parameters.values()
                           if p is not None)
            rows.append((f"{type(lyr).__name__}-{name}", shape, n_params))
        return hook

    for name, sub in net.named_sublayers():
        hooks.append(sub.register_forward_post_hook(mk_hook(name, sub)))

    modes = _snapshot_modes(net)
    net.eval()
    try:
        with _tape.no_grad():
            x = [Tensor(v, _internal=True)
                 for v in _example_inputs(input_size, dtypes)]
            net(*x)
    finally:
        for h in hooks:
            h.remove()
        _restore_modes(modes)

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    w = max([len(r[0]) for r in rows] + [14]) + 2
    lines = [f"{'Layer (type)':<{w}}{'Output Shape':<22}{'Param #':<12}",
             "-" * (w + 34)]
    for name, shape, n in rows:
        lines.append(f"{name:<{w}}{str(shape):<22}{n:<12,}")
    lines.append("-" * (w + 34))
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    lines.append(f"Non-trainable params: {total - trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, dtypes=None, print_detail=False):
    """FLOPs of one eval forward (reference hapi/dynamic_flops.py). Exact:
    XLA cost analysis of the compiled forward, no per-layer formulas."""
    import jax

    from .. import profiler
    from ..core import tape as _tape
    from ..core import rng as _rng
    from ..core.tensor import Tensor

    params, buffers = net.functional_state()
    modes = _snapshot_modes(net)
    net.eval()
    try:
        def fwd(p, *xs):
            with _tape.no_grad(), _rng.rng_state(jax.random.PRNGKey(0)):
                net.load_functional_state(p, buffers)
                out = net(*[Tensor(x, _internal=True) for x in xs])
            leaves = jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))
            return leaves

        example = _example_inputs(input_size, dtypes)
        ca = profiler.cost_analysis(jax.jit(fwd), params, *example)
        total = int(float(ca.get("flops", 0.0)))
    finally:
        # the trace seated tracers into the layer via load_functional_state;
        # put the concrete values back (same contract as the hapi engine's
        # _restore) or the next forward reads leaked tracers
        net.load_functional_state(params, buffers)
        _restore_modes(modes)
    if print_detail:
        print(f"Total FLOPs: {total:,}  (bytes accessed: "
              f"{int(float(ca.get('bytes accessed', 0))):,})")
    return total
