from . import callbacks  # noqa: F401
from .model import InputSpec, Model  # noqa: F401
