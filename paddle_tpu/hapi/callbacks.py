"""Training callbacks (reference python/paddle/hapi/callbacks.py: Callback,
CallbackList, ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping,
VisualDL)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRSchedulerCallback", "History",
           "ProfilerCallback", "VisualDL",
           "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        pass

    def on_end(self, mode, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            c.on_begin(mode, logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            c.on_end(mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_begin")(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_end")(step, logs)


class History(Callback):
    def __init__(self):
        super().__init__()
        self.history = {}

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_begin(self, mode, logs=None):
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._seen = 0
        self._epoch_t0 = time.time()
        if self.verbose and self.params.get("epochs"):
            print(f"Epoch {epoch + 1}/{self.params['epochs']}")

    def _format(self, logs):
        parts = []
        for k, v in logs.items():
            if k == "batch_size":
                continue
            # float-convertibles cover the async fit loop's _LazyLoss
            # (hapi/model.py), which materializes its exact loss on read
            if isinstance(v, numbers.Number) or hasattr(v, "__float__"):
                parts.append(f"{k}: {float(v):.4f}")
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        self._seen += 1
        if self.verbose == 2 and self._seen % self.log_freq == 0:
            total = f"/{self.steps}" if self.steps else ""
            dt = (time.time() - self._epoch_t0) / max(self._seen, 1)
            print(f"step {self._seen}{total} - {self._format(logs or {})}"
                  f" - {dt * 1000:.0f}ms/step")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._epoch_t0
            print(f"Epoch {epoch + 1} done ({dt:.1f}s) - "
                  f"{self._format(logs or {})}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_end(self, mode, logs=None):
        if mode == "train" and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0

    def _better(self, current):
        if self.best is None:
            return True
        if self.mode == "min":
            return current < self.best - self.min_delta
        return current > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            current = logs.get(f"eval_{self.monitor}")
        if current is None:
            return
        current = float(np.asarray(current).reshape(-1)[0])
        if self._better(current):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping at epoch {epoch + 1}: best "
                          f"{self.monitor}={self.best:.5f}")


class LRSchedulerCallback(Callback):
    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return opt._lr_scheduler if opt else None

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch and self._sched() is not None:
            self._sched().step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and self._sched() is not None:
            self._sched().step()


class VisualDL(Callback):
    """Stream per-step loss and per-epoch metrics to a LogWriter
    (reference hapi/callbacks.py VisualDL; zero-egress JSON-lines form,
    paddle_tpu.utils.LogWriter).

    `sample_freq`: write buffered per-batch losses every N batches
    instead of per batch. Under the async fit loop the per-batch
    `logs["loss"]` is a lazy window entry (hapi/model.py _LazyLoss) and
    reading it every batch forces a device sync that defeats the
    pipeline; the default N=10 matches fit's log_freq window boundary,
    where the loop has ALREADY drained the window — so the buffered
    reads cost no extra sync and per-batch values stay exact
    (tests/test_visualdl_async.py proves zero forced drains).
    sample_freq=1 restores write-every-batch (per-batch sync under the
    async loop). Pass the same value as fit(log_freq=...) if you change
    either."""

    def __init__(self, log_dir, sample_freq=10):
        from ..utils.log_writer import LogWriter
        self.writer = LogWriter(log_dir)
        self.sample_freq = max(1, int(sample_freq))
        self._step = 0
        self._pending = []   # [(global_step, loss-ish)] awaiting a write

    def _flush_pending(self):
        pending, self._pending = self._pending, []
        for s, v in pending:
            try:
                val = float(v)
            except Exception:
                # a buffered loss of a crashed in-flight step can refuse
                # to materialize; the earlier (good) entries still land
                continue
            # writer (I/O) errors propagate, as they always did
            self.writer.add_scalar("train/loss", val, s)

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if logs and "loss" in logs:
            self._pending.append((self._step, logs["loss"]))
        # cadence keyed on fit's PER-EPOCH step (the `step` argument), so
        # it stays phase-aligned with the loop's own log_freq drain even
        # when an epoch's length isn't a multiple of sample_freq
        if (step + 1) % self.sample_freq == 0:
            self._flush_pending()

    def on_epoch_end(self, epoch, logs=None):
        self._flush_pending()
        for k, v in (logs or {}).items():
            if isinstance(v, (int, float)):
                self.writer.add_scalar(f"epoch/{k}", v, epoch)
        self.writer.flush()

    def on_end(self, mode, logs=None):
        self._flush_pending()
        self.writer.close()

    def __del__(self):
        # fit() skips on_end when training raises; don't lose the
        # buffered tail — those are the losses closest to the crash
        try:
            self._flush_pending()
            self.writer.flush()
        except Exception:
            pass


class ProfilerCallback(Callback):
    """Capture host profiler events for a window of training steps and print
    the summary table (reference hapi callbacks + fluid/profiler.py usage;
    device-side capture via paddle_tpu.profiler.xplane_trace)."""

    def __init__(self, start_step=1, stop_step=10, sorted_key="total",
                 xplane_dir=None):
        self.start_step = start_step
        self.stop_step = stop_step
        self.sorted_key = sorted_key
        self.xplane_dir = xplane_dir
        self._step = 0

    def on_train_batch_begin(self, step, logs=None):
        from .. import profiler as prof
        self._step += 1
        if self._step == self.start_step:
            prof.reset_profiler()
            prof.start_profiler()
            if self.xplane_dir:
                prof.start_xplane(self.xplane_dir)

    def on_train_batch_end(self, step, logs=None):
        from .. import profiler as prof
        if self._step == self.stop_step and prof.is_profiler_enabled():
            if self.xplane_dir:
                prof.stop_xplane()
            prof.stop_profiler()
            print(prof.summary(sorted_key=self.sorted_key))


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=10, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, ModelCheckpoint) for c in cbks) and save_dir:
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                   "metrics": metrics or ["loss"]})
    return cl
