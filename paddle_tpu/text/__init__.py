"""paddle.text — NLP models and datasets (reference python/paddle/text/).

The reference ships dataset wrappers (Imdb, Conll05, WMT14...) and leaves
models to downstream repos; here the flagship pretraining models
(BERT-family) are first-class since they are the perf north star
(BASELINE.md config 3).
"""
from . import datasets  # noqa: F401
from .models import Bert, BertConfig, GPT, GPTConfig  # noqa: F401
from . import models  # noqa: F401
