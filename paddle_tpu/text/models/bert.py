"""BERT (encoder-only pretraining model) — the flagship benchmark model.

Parity target: the reference builds BERT from paddle.nn.Transformer pieces
(python/paddle/nn/layer/transformer.py:85 MultiHeadAttention,
:575 TransformerEncoder) — BASELINE.md config 3 ("BERT-base pretrain").
This module provides the assembled model the reference leaves to downstream
repos, with MLM + NSP heads, weight-tied decoder, and a `bert_base` config
matching the standard 110M-parameter recipe.
"""
from __future__ import annotations

from dataclasses import dataclass

from ... import nn, ops


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12

    @staticmethod
    def bert_base():
        return BertConfig()

    @staticmethod
    def bert_large():
        return BertConfig(hidden_size=1024, num_hidden_layers=24,
                          num_attention_heads=16, intermediate_size=4096)

    @staticmethod
    def tiny():
        return BertConfig(vocab_size=1024, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=128, max_position_embeddings=128)


def _bert_init(root: nn.Layer, std=0.02):
    """Standard BERT init: N(0, 0.02) matrices/embeddings, zero biases,
    unit LayerNorm — keeps tied-decoder logits O(1) at step 0."""
    from ...nn import initializer as I
    for name, p in root.named_parameters():
        if p.ndim >= 2:
            p.set_value(I.TruncatedNormal(0.0, std)(p.shape, "float32"))
        elif "weight" in name and p.ndim == 1:  # LayerNorm scale
            p.set_value(I.Constant(1.0)(p.shape, "float32"))
        else:
            p.set_value(I.Constant(0.0)(p.shape, "float32"))


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        seq_len = input_ids.shape[1]
        pos = ops.arange(seq_len, dtype="int64")
        emb = self.word_embeddings(input_ids)
        emb = emb + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertPooler(nn.Layer):
    def __init__(self, hidden_size):
        super().__init__()
        self.dense = nn.Linear(hidden_size, hidden_size)

    def forward(self, hidden_states):
        return ops.tanh(self.dense(hidden_states[:, 0]))


class Bert(nn.Layer):
    """Encoder + MLM head (tied to word embeddings) + NSP head."""

    def __init__(self, config: BertConfig = None, with_mlm=True,
                 with_nsp=False):
        super().__init__()
        cfg = config or BertConfig.bert_base()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            d_model=cfg.hidden_size, nhead=cfg.num_attention_heads,
            dim_feedforward=cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob)
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_hidden_layers)
        self.pooler = BertPooler(cfg.hidden_size)
        self.with_mlm = with_mlm
        self.with_nsp = with_nsp
        if with_mlm:
            self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
            self.mlm_norm = nn.LayerNorm(cfg.hidden_size,
                                         epsilon=cfg.layer_norm_eps)
            self.mlm_bias = self.create_parameter(
                [cfg.vocab_size], is_bias=True)
        if with_nsp:
            self.nsp_head = nn.Linear(cfg.hidden_size, 2)
        _bert_init(self, std=0.02)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None):
        x = self.embeddings(input_ids, token_type_ids)
        mask = None
        if attention_mask is not None:
            # [b, s] 1/0 -> additive [b, 1, 1, s]
            m = ops.unsqueeze(attention_mask.astype("float32"), [1, 2])
            mask = (1.0 - m) * -1e9
        h = self.encoder(x, src_mask=mask)
        outputs = []
        if self.with_mlm:
            t = ops.gelu(self.mlm_transform(h))
            t = self.mlm_norm(t)
            if masked_lm_labels is not None:
                if self.with_nsp:
                    raise ValueError(
                        "masked_lm_labels returns the fused MLM loss only; "
                        "with_nsp models must take the logits path and "
                        "combine losses via BertPretrainingCriterion")
                # fused head: tied-decoder projection + CE in one kernel,
                # no [b*s, vocab] logits in HBM (ops/pallas/fused_ce.py)
                from ...nn import functional as F
                return F.fused_linear_cross_entropy(
                    t, self.embeddings.word_embeddings.weight,
                    self.mlm_bias, masked_lm_labels, ignore_index=-100)
            # weight-tied decoder: [b,s,H] @ [V,H]^T
            logits = ops.matmul(t, self.embeddings.word_embeddings.weight,
                                transpose_y=True) + self.mlm_bias
            outputs.append(logits)
        if self.with_nsp:
            outputs.append(self.nsp_head(self.pooler(h)))
        if not outputs:
            return h
        return outputs[0] if len(outputs) == 1 else tuple(outputs)

    def num_params(self):
        return sum(p.size for p in self.parameters())


class BertPretrainingCriterion(nn.Layer):
    """MLM (+ optional NSP) loss with ignore_index=-100 masking."""

    def __init__(self, vocab_size):
        super().__init__()
        self.vocab_size = vocab_size
        self.ce = nn.CrossEntropyLoss(ignore_index=-100)

    def forward(self, prediction_scores, masked_lm_labels):
        b, s, v = prediction_scores.shape
        return self.ce(ops.reshape(prediction_scores, [b * s, v]),
                       ops.reshape(masked_lm_labels, [b * s]))
