"""GPT (decoder-only causal LM) — ERNIE/Transformer-XL-class model-parallel
workload (BASELINE.md config 5 territory). Built from the same encoder
blocks with causal masking via the fused attention core.
"""
from __future__ import annotations

import collections
import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ... import nn, ops
from ...nn import functional as F


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_seq_len: int = 1024
    dropout: float = 0.1

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                         num_heads=2, intermediate_size=128, max_seq_len=128)


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = nn.MultiHeadAttention(cfg.hidden_size, cfg.num_heads,
                                          dropout=cfg.dropout)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x, cache=None):
        h = self.ln1(x)
        if cache is not None:
            # StaticKVCache path: positions are tracked by the cache index,
            # masking happens against the cache — no is_causal needed
            a, cache = self.attn(h, cache=cache)
            x = x + a
        else:
            # is_causal (not a materialized [s,s] mask) keeps the Pallas
            # flash kernel's in-kernel triangular masking + block skipping
            # eligible
            x = x + self.attn(h, is_causal=True)
        h = self.ln2(x)
        x = x + self.drop(self.fc2(F.gelu(self.fc1(h))))
        return x if cache is None else (x, cache)


class GPT(nn.Layer):
    def __init__(self, config: GPTConfig = None):
        super().__init__()
        cfg = config or GPTConfig()
        self.config = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        from .bert import _bert_init
        _bert_init(self, std=0.02)

    def __getstate__(self):
        # the decode cache holds jitted executables and a lock — neither
        # pickles; they rebuild lazily on first generate() after load
        d = dict(self.__dict__)
        d.pop("_decode_cache", None)
        d.pop("_decode_lock", None)
        return d

    def forward(self, input_ids, labels=None):
        s = input_ids.shape[1]
        pos = ops.arange(s, dtype="int64")
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        for blk in self.blocks:
            x = blk(x)
        x = self.ln_f(x)
        if labels is not None:
            # fused tied-head LM loss: no [b*s, vocab] logits in HBM
            # (ops/pallas/fused_ce.py), ignore_index=-100
            return F.fused_linear_cross_entropy(
                x, self.wte.weight, None, labels, ignore_index=-100)
        # weight-tied LM head
        return ops.matmul(x, self.wte.weight, transpose_y=True)

    def _forward_cached(self, input_ids, caches, index):
        """One cached decode/prefill pass. input_ids [b, s_new] (Tensor or
        jnp), caches: list of StaticKVCache (one per block), index: i32
        tokens already in the cache. Returns (last-position logits [b, V]
        jnp, new caches)."""
        import jax.numpy as jnp

        from ...core.tensor import Tensor

        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(input_ids, _internal=True)
        s = ids.shape[1]
        pos = index + jnp.arange(s, dtype=jnp.int32)
        x = self.wte(ids) + self.wpe(Tensor(pos, _internal=True))
        x = self.drop(x)
        new_caches = []
        for blk, c in zip(self.blocks, caches):
            x, c = blk(x, cache=c)
            new_caches.append(c)
        x = self.ln_f(x)
        logits = ops.matmul(x[:, -1], self.wte.weight, transpose_y=True)
        return logits._value, new_caches

    def _forward_paged(self, input_ids, caches, last_index=None):
        """One paged decode/prefill pass over the serving tier's shared
        block arena (nn/kv_pool.py). input_ids [b, s] (Tensor or jnp);
        caches: list of PagedKVCache (one per block) whose `lengths`
        field carries each slot's fill count — per-slot positions, not
        the scalar index of `_forward_cached`. `last_index` [b] (or
        None = s-1) picks the position whose logits come back: a
        bucket-padded prefill reads the logits at the REAL last prompt
        token, not the pad tail. Returns (logits [b, V] jnp, new
        caches)."""
        import jax.numpy as jnp

        from ...core.tensor import Tensor

        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(input_ids, _internal=True)
        s = ids.shape[1]
        lens = jnp.asarray(caches[0].lengths, jnp.int32)
        pos = lens[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
        # pad rows of a bucketed prefill can run past the cap; their
        # k/v writes already land in the trash block, so the position
        # embedding only needs to stay in range
        pos = jnp.clip(pos, 0, self.config.max_seq_len - 1)
        x = self.wte(ids) + self.wpe(Tensor(pos, _internal=True))
        x = self.drop(x)
        new_caches = []
        for blk, c in zip(self.blocks, caches):
            x, c = blk(x, cache=c)
            new_caches.append(c)
        x = self.ln_f(x)
        h = x._value
        if last_index is not None:
            idx = jnp.asarray(last_index, jnp.int32).reshape(-1)
            h = jnp.take_along_axis(
                h, idx[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
        else:
            h = h[:, -1]
        logits = ops.matmul(Tensor(h, _internal=True), self.wte.weight,
                            transpose_y=True)
        return logits._value, new_caches

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=None, eos_token_id=None, use_cache=True, seed=0):
        """Autoregressive sampling (reference generation utils; greedy at
        temperature=0). Returns [b, s + new] ids.

        use_cache=True (default): static-shape KV-cache decode — the whole
        generation (prefill + lax.scan over steps) is ONE jitted dispatch,
        O(1) work per token and no per-token retrace; re-traced only per
        (prompt_len, max_new_tokens, sampling-config). The reference's
        incremental decoding lives in its C++ predictor
        (inference/api/analysis_predictor.cc:306); here it is a compiled
        scan over a preallocated cache (nn/layer/transformer.py
        StaticKVCache). use_cache=False keeps the simple host loop that
        re-forwards the growing prefix (the equality oracle in tests)."""
        import numpy as np

        from ...core import tape as _tape

        if use_cache:
            with _tape.no_grad():
                return self._generate_cached(
                    input_ids, int(max_new_tokens), float(temperature),
                    None if top_k is None else int(top_k),
                    eos_token_id, int(seed))

        with _tape.no_grad():
            ids = input_ids
            finished = np.zeros(int(ids.shape[0]), bool)
            for _ in range(max_new_tokens):
                logits = self(ids)[:, -1]                 # [b, V]
                if temperature == 0:
                    nxt = ops.argmax(logits, axis=-1)
                else:
                    logits = logits / float(temperature)
                    if top_k is not None:
                        kth = ops.topk(logits, top_k, axis=-1)[0][:, -1:]
                        logits = ops.where(
                            logits < kth,
                            ops.full_like(logits, -1e9), logits)
                    from ...distribution import Categorical
                    nxt = Categorical(logits=logits._value).sample()
                nxt = ops.reshape(nxt, [-1, 1]).astype("int64")
                if eos_token_id is not None:
                    keep = np.asarray(~finished)[:, None]
                    from ... import to_tensor
                    nxt = ops.where(
                        to_tensor(keep), nxt,
                        ops.full_like(nxt, eos_token_id))
                    finished |= (
                        np.asarray(nxt._value)[:, 0] == eos_token_id)
                ids = ops.concat([ids, nxt], axis=1)
                if eos_token_id is not None and finished.all():
                    break
            return ids

    def _generate_cached(self, input_ids, max_new, temperature, top_k,
                         eos_id, seed):
        import numpy as np

        from ... import to_tensor
        from ...core.tensor import Tensor

        ids = input_ids if isinstance(input_ids, Tensor) \
            else to_tensor(input_ids)
        b, s = int(ids.shape[0]), int(ids.shape[1])
        total = s + max_new
        if total > self.config.max_seq_len:
            raise ValueError(
                f"generate: prompt {s} + max_new_tokens {max_new} exceeds "
                f"max_seq_len {self.config.max_seq_len}")

        params, buffers = self.functional_state()
        cache_dtype = jnp.bfloat16 if any(
            v.dtype == jnp.bfloat16 for v in params.values()) else jnp.float32

        fn = _decode_fn(self, max_new, temperature, top_k,
                        None if eos_id is None else int(eos_id),
                        total, jnp.dtype(cache_dtype).name, b, s)
        try:
            toks = fn(params, buffers, ids._value,
                      jax.random.PRNGKey(seed))
        finally:
            # tracing mutated the layers' parameters to tracers; restore
            # the real arrays so eager use of the net keeps working
            self.load_functional_state(params, buffers)
        out = np.concatenate([np.asarray(ids._value, np.int64),
                              np.asarray(toks, np.int64)], axis=1)
        return to_tensor(out)


_DECODE_CACHE_CAP = 64


def _decode_fn(net, max_new, temperature, top_k, eos_id, total, cache_dtype,
               b, s):
    """Build + jit the whole-generation program (prefill + lax.scan decode):
    ONE compiled dispatch per generate() call, O(1) work per token. The
    LRU-capped cache lives on the instance (net -> cache -> jitted fn ->
    net is a cycle the GC collects once the model is dropped — a global
    registry would pin the model forever, since the jitted fn closes over
    it; GPT.__getstate__ excludes the cache so pickling/deepcopy still
    work). The per-instance lock is held across lookup and build: tracing
    temporarily rebinds this layer's parameters to tracers, so concurrent
    builds on one model are unsafe, while unrelated models stay parallel;
    holding it for the lookup also keeps LRU eviction race-free."""
    key = (max_new, temperature, top_k, eos_id, total, cache_dtype, b, s)
    lock = net.__dict__.setdefault("_decode_lock", threading.Lock())
    with lock:
        cache = net.__dict__.setdefault("_decode_cache",
                                        collections.OrderedDict())
        if key in cache:
            cache.move_to_end(key)
            return cache[key]
        fn = _build_decode_fn(net, max_new, temperature, top_k, eos_id,
                              total, cache_dtype, b, s)
        cache[key] = fn
        while len(cache) > _DECODE_CACHE_CAP:
            cache.popitem(last=False)
        return fn


def _build_decode_fn(net, max_new, temperature, top_k, eos_id, total,
                     cache_dtype, b, s):
    import jax
    import jax.numpy as jnp

    from ...core import tape as _tape

    dt = jnp.dtype(cache_dtype)

    def run(params, buffers, ids_j, key):
        with _tape.no_grad():
            net.load_functional_state(params, buffers)
            caches = [blk.attn.gen_static_cache(b, total, dt)
                      for blk in net.blocks]
            logits, caches = net._forward_cached(ids_j, caches, jnp.int32(0))

            def sample(logits, k):
                if temperature == 0:
                    return jnp.argmax(logits, axis=-1).astype(jnp.int32)
                lg = (logits / temperature).astype(jnp.float32)
                if top_k is not None:
                    kth = jax.lax.top_k(lg, top_k)[0][:, -1:]
                    lg = jnp.where(lg < kth, -1e9, lg)
                return jax.random.categorical(k, lg, axis=-1).astype(
                    jnp.int32)

            def body(carry, step_key):
                caches, logits, finished, index = carry
                nxt = sample(logits, step_key)
                if eos_id is not None:
                    # finished rows are frozen to eos (their sample is
                    # discarded), and once EVERY row is finished the
                    # whole forward is skipped: the scan still runs to
                    # max_new for shape stability, but the tail steps
                    # cost one all-reduce of `finished`, not a model
                    # pass — per-request EOS at batched-decode cost
                    nxt = jnp.where(finished, jnp.int32(eos_id), nxt)
                    finished = finished | (nxt == eos_id)

                    def _run(op):
                        c, _lg, nx, ix = op
                        return net._forward_cached(nx[:, None], c, ix)

                    def _skip(op):
                        c, lg, _nx, _ix = op
                        return lg, c

                    logits, caches = jax.lax.cond(
                        jnp.all(finished), _skip, _run,
                        (caches, logits, nxt, index))
                else:
                    logits, caches = net._forward_cached(nxt[:, None],
                                                         caches, index)
                return (caches, logits, finished, index + 1), nxt

            init = (caches, logits, jnp.zeros((b,), bool), jnp.int32(s))
            keys = jax.random.split(key, max_new)
            _, toks = jax.lax.scan(body, init, keys)       # [max_new, b]
        return toks.swapaxes(0, 1)                         # [b, max_new]

    return jax.jit(run)


def export_decode(net, path, batch_size, prompt_len, max_new_tokens,
                  temperature=0.0, top_k=None, eos_token_id=None):
    """Export the WHOLE generation program (prefill + scan decode over the
    StaticKVCache) as a StableHLO artifact the inference Predictor can
    run — the deployment form of incremental decoding (reference ships
    this inside the C++ AnalysisPredictor; here it is one exported XLA
    program). Inputs: input_ids [batch, prompt_len] int32, seed []
    int32. Output: generated tokens [batch, max_new_tokens] int32.

    Parameters are baked into the artifact as constants (same convention
    as jit.save). Writes {path}.stablehlo + {path}.pdinfer.json.
    """
    import json
    import os

    import jax.export as jexport

    params, buffers = net.functional_state()
    total = prompt_len + int(max_new_tokens)
    if total > net.config.max_seq_len:
        raise ValueError("prompt_len + max_new_tokens exceeds max_seq_len")
    cache_dtype = "bfloat16" if any(
        v.dtype == jnp.bfloat16 for v in params.values()) else "float32"
    fn = _decode_fn(net, int(max_new_tokens), float(temperature),
                    None if top_k is None else int(top_k),
                    None if eos_token_id is None else int(eos_token_id),
                    total, cache_dtype, int(batch_size), int(prompt_len))

    def run(ids, seed):
        key = jax.random.PRNGKey(seed.astype(jnp.int32))
        return fn(params, buffers, ids.astype(jnp.int64), key)

    ids_spec = jax.ShapeDtypeStruct((int(batch_size), int(prompt_len)),
                                    jnp.int32)
    seed_spec = jax.ShapeDtypeStruct((), jnp.int32)
    exported = jexport.export(jax.jit(run),
                              platforms=("cpu", "tpu"))(ids_spec, seed_spec)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".stablehlo", "wb") as f:
        f.write(bytes(exported.serialize()))
    with open(path + ".pdinfer.json", "w") as f:
        json.dump({"input_names": ["input_ids", "seed"],
                   "output_names": ["tokens"],
                   "input_dtypes": ["int32", "int32"],
                   "decode": {"batch_size": int(batch_size),
                              "prompt_len": int(prompt_len),
                              "max_new_tokens": int(max_new_tokens),
                              "temperature": float(temperature),
                              "top_k": top_k,
                              "eos_token_id": eos_token_id}}, f)
    return path
