"""GPT (decoder-only causal LM) — ERNIE/Transformer-XL-class model-parallel
workload (BASELINE.md config 5 territory). Built from the same encoder
blocks with causal masking via the fused attention core.
"""
from __future__ import annotations

from dataclasses import dataclass

from ... import nn, ops
from ...nn import functional as F


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_seq_len: int = 1024
    dropout: float = 0.1

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                         num_heads=2, intermediate_size=128, max_seq_len=128)


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = nn.MultiHeadAttention(cfg.hidden_size, cfg.num_heads,
                                          dropout=cfg.dropout)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        h = self.ln1(x)
        # is_causal (not a materialized [s,s] mask) keeps the Pallas flash
        # kernel's in-kernel triangular masking + block skipping eligible
        x = x + self.attn(h, is_causal=True)
        h = self.ln2(x)
        x = x + self.drop(self.fc2(F.gelu(self.fc1(h))))
        return x


class GPT(nn.Layer):
    def __init__(self, config: GPTConfig = None):
        super().__init__()
        cfg = config or GPTConfig()
        self.config = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        from .bert import _bert_init
        _bert_init(self, std=0.02)

    def forward(self, input_ids, labels=None):
        s = input_ids.shape[1]
        pos = ops.arange(s, dtype="int64")
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        for blk in self.blocks:
            x = blk(x)
        x = self.ln_f(x)
        if labels is not None:
            # fused tied-head LM loss: no [b*s, vocab] logits in HBM
            # (ops/pallas/fused_ce.py), ignore_index=-100
            return F.fused_linear_cross_entropy(
                x, self.wte.weight, None, labels, ignore_index=-100)
        # weight-tied LM head
        return ops.matmul(x, self.wte.weight, transpose_y=True)

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=None, eos_token_id=None):
        """Autoregressive sampling (reference generation utils; greedy at
        temperature=0). Eager host loop re-forwarding the growing prefix —
        the simple inference form; the flash kernel keeps each forward
        O(s) in memory. Returns [b, s + new] ids."""
        import numpy as np

        from ...core import tape as _tape

        with _tape.no_grad():
            ids = input_ids
            finished = np.zeros(int(ids.shape[0]), bool)
            for _ in range(max_new_tokens):
                logits = self(ids)[:, -1]                 # [b, V]
                if temperature == 0:
                    nxt = ops.argmax(logits, axis=-1)
                else:
                    logits = logits / float(temperature)
                    if top_k is not None:
                        kth = ops.topk(logits, top_k, axis=-1)[0][:, -1:]
                        logits = ops.where(
                            logits < kth,
                            ops.full_like(logits, -1e9), logits)
                    from ...distribution import Categorical
                    nxt = Categorical(logits=logits._value).sample()
                nxt = ops.reshape(nxt, [-1, 1]).astype("int64")
                if eos_token_id is not None:
                    keep = np.asarray(~finished)[:, None]
                    from ... import to_tensor
                    nxt = ops.where(
                        to_tensor(keep), nxt,
                        ops.full_like(nxt, eos_token_id))
                    finished |= (
                        np.asarray(nxt._value)[:, 0] == eos_token_id)
                ids = ops.concat([ids, nxt], axis=1)
                if eos_token_id is not None and finished.all():
                    break
            return ids
