from .bert import Bert, BertConfig  # noqa: F401
from .gpt import GPT, GPTConfig  # noqa: F401
