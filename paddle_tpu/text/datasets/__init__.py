"""Text datasets (reference python/paddle/text/datasets/: Imdb, Conll05,
Movielens, UCIHousing, WMT14/16...). Zero-egress fallback: synthetic token
streams with Zipfian statistics for LM pretraining benches.
"""
from __future__ import annotations

import numpy as np

from ...io import Dataset

__all__ = ["LMDataset", "UCIHousing", "Imdb"]


class LMDataset(Dataset):
    """Synthetic masked/causal LM pretraining data (deterministic)."""

    def __init__(self, vocab_size=30522, seq_len=128, n=4096, mode="mlm",
                 mask_prob=0.15, seed=0):
        rng = np.random.RandomState(seed)
        # Zipfian token distribution, like natural text
        ranks = np.arange(1, vocab_size - 4)
        probs = 1.0 / ranks
        probs /= probs.sum()
        self.tokens = (rng.choice(ranks, size=(n, seq_len), p=probs) + 4) \
            .astype("int64")
        self.mode = mode
        self.vocab_size = vocab_size
        if mode == "mlm":
            mask = rng.rand(n, seq_len) < mask_prob
            self.labels = np.where(mask, self.tokens, -100).astype("int64")
            self.inputs = np.where(mask, 3, self.tokens).astype("int64")  # [MASK]=3
        else:  # causal
            self.inputs = self.tokens[:, :-1]
            self.labels = self.tokens[:, 1:]

    def __getitem__(self, idx):
        return self.inputs[idx], self.labels[idx]

    def __len__(self):
        return len(self.inputs)


class UCIHousing(Dataset):
    def __init__(self, mode="train"):
        rng = np.random.RandomState(42)
        n = 404 if mode == "train" else 102
        self.x = rng.randn(n, 13).astype("float32")
        w = rng.randn(13, 1).astype("float32")
        self.y = (self.x @ w + 0.1 * rng.randn(n, 1)).astype("float32")

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Imdb(Dataset):
    def __init__(self, mode="train", cutoff=150):
        rng = np.random.RandomState(9 if mode == "train" else 10)
        n = 2048 if mode == "train" else 512
        self.docs = rng.randint(2, 5000, size=(n, 128)).astype("int64")
        self.labels = rng.randint(0, 2, n).astype("int64")
        # plant signal: positive docs use low token ids more often
        self.docs[self.labels == 1] //= 2

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)
