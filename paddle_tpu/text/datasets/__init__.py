"""Text datasets (reference python/paddle/text/datasets/: Imdb, Conll05,
Movielens, UCIHousing, WMT14/16...). Zero-egress fallback: synthetic token
streams with Zipfian statistics for LM pretraining benches.
"""
from __future__ import annotations

import numpy as np

from ...io import Dataset

__all__ = ["LMDataset", "UCIHousing", "Imdb"]


class LMDataset(Dataset):
    """Synthetic masked/causal LM pretraining data (deterministic)."""

    def __init__(self, vocab_size=30522, seq_len=128, n=4096, mode="mlm",
                 mask_prob=0.15, seed=0):
        rng = np.random.RandomState(seed)
        # Zipfian token distribution, like natural text
        ranks = np.arange(1, vocab_size - 4)
        probs = 1.0 / ranks
        probs /= probs.sum()
        self.tokens = (rng.choice(ranks, size=(n, seq_len), p=probs) + 4) \
            .astype("int64")
        self.mode = mode
        self.vocab_size = vocab_size
        if mode == "mlm":
            mask = rng.rand(n, seq_len) < mask_prob
            self.labels = np.where(mask, self.tokens, -100).astype("int64")
            self.inputs = np.where(mask, 3, self.tokens).astype("int64")  # [MASK]=3
        else:  # causal
            self.inputs = self.tokens[:, :-1]
            self.labels = self.tokens[:, 1:]

    def __getitem__(self, idx):
        return self.inputs[idx], self.labels[idx]

    def __len__(self):
        return len(self.inputs)


class UCIHousing(Dataset):
    def __init__(self, mode="train"):
        rng = np.random.RandomState(42)
        n = 404 if mode == "train" else 102
        self.x = rng.randn(n, 13).astype("float32")
        w = rng.randn(13, 1).astype("float32")
        self.y = (self.x @ w + 0.1 * rng.randn(n, 1)).astype("float32")

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Imdb(Dataset):
    def __init__(self, mode="train", cutoff=150):
        rng = np.random.RandomState(9 if mode == "train" else 10)
        n = 2048 if mode == "train" else 512
        self.docs = rng.randint(2, 5000, size=(n, 128)).astype("int64")
        self.labels = rng.randint(0, 2, n).astype("int64")
        # plant signal: positive docs use low token ids more often
        self.docs[self.labels == 1] //= 2

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


# -- round-4 breadth: the remaining reference text datasets in zero-egress
#    local-archive form (reference python/paddle/text/datasets/
#    imikolov.py, movielens.py, conll05.py, wmt14.py, wmt16.py) -----------

__all__ += ["Imikolov", "Movielens", "Conll05st", "WMT14", "WMT16"]


def _build_word_dict(lines, min_word_freq=1, extra=("<s>", "<e>", "<unk>")):
    from collections import Counter
    c = Counter()
    for ln in lines:
        c.update(ln.split())
    vocab = [w for w, n in sorted(c.items(), key=lambda kv: (-kv[1], kv[0]))
             if n >= min_word_freq]
    word_idx = {w: i for i, w in enumerate(vocab)}
    for t in extra:
        word_idx.setdefault(t, len(word_idx))
    return word_idx


class Imikolov(Dataset):
    """PTB language-model dataset (reference imikolov.py): n-gram or
    seq mode over ptb.{train,valid}.txt inside the local simple-examples
    tar (pass data_file; download is zero-egress-disabled)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        import tarfile
        if download:
            raise RuntimeError("zero-egress: pass the local PTB tar via "
                               "data_file")
        if data_file is None:
            raise ValueError("data_file is required")
        name = {"train": "ptb.train.txt", "test": "ptb.valid.txt"}[mode]
        with tarfile.open(data_file) as tf:
            members = {m.name.rsplit("/", 1)[-1]: m for m in tf}
            train_lines = tf.extractfile(
                members["ptb.train.txt"]).read().decode().splitlines()
            lines = tf.extractfile(
                members[name]).read().decode().splitlines()
        self.word_idx = _build_word_dict(train_lines, min_word_freq)
        unk = self.word_idx["<unk>"]
        s, e = self.word_idx["<s>"], self.word_idx["<e>"]
        self.data = []
        dt = data_type.upper()
        for ln in lines:
            ids = [s] + [self.word_idx.get(w, unk)
                         for w in ln.split()] + [e]
            if dt == "NGRAM":
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        self.data.append(
                            np.asarray(ids[i - window_size:i], np.int64))
            elif dt == "SEQ":
                src, trg = ids[:-1], ids[1:]
                if len(src) and len(src) < window_size - 2:
                    self.data.append((np.asarray(src, np.int64),
                                      np.asarray(trg, np.int64)))
            else:
                raise ValueError("data_type must be NGRAM or SEQ")

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """MovieLens-1M ratings (reference movielens.py): yields
    (user_id, gender, age, job, movie_id, categories, title, rating)
    feature tuples parsed from the local ml-1m zip (users.dat /
    movies.dat / ratings.dat, '::'-separated)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        import zipfile
        if download:
            raise RuntimeError("zero-egress: pass the local ml-1m zip via "
                               "data_file")
        if data_file is None:
            raise ValueError("data_file is required")
        with zipfile.ZipFile(data_file) as zf:
            names = {n.rsplit("/", 1)[-1]: n for n in zf.namelist()}

            def read(fname):
                return zf.read(names[fname]).decode(
                    "latin1").strip().splitlines()

            users, movies, ratings = (read(f) for f in
                                      ("users.dat", "movies.dat",
                                       "ratings.dat"))
        self.user_info = {}
        for ln in users:
            uid, gender, age, job, _zip = ln.split("::")
            self.user_info[int(uid)] = (0 if gender == "M" else 1,
                                        int(age), int(job))
        self.movie_info = {}
        self.categories = {}
        self.movie_title_dict = {}
        for ln in movies:
            mid, title, cats = ln.split("::")
            cat_ids = []
            for c in cats.split("|"):
                cat_ids.append(self.categories.setdefault(
                    c, len(self.categories)))
            words = []
            for wrd in title.split():
                words.append(self.movie_title_dict.setdefault(
                    wrd, len(self.movie_title_dict)))
            self.movie_info[int(mid)] = (cat_ids, words)
        rng = np.random.RandomState(rand_seed)
        self.data = []
        for ln in ratings:
            uid, mid, rating, _ts = ln.split("::")
            uid, mid = int(uid), int(mid)
            is_test = rng.rand() < test_ratio
            if (mode == "test") != is_test or mid not in self.movie_info:
                continue
            g, age, job = self.user_info[uid]
            cats, title = self.movie_info[mid]
            self.data.append((uid, g, age, job, mid,
                              np.asarray(cats, np.int64),
                              np.asarray(title, np.int64),
                              float(rating)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference conll05.py): (word, predicate, label)
    sequences from local words/props files (plain or .gz), with
    word/label dicts built from the data."""

    def __init__(self, words_file=None, props_file=None, mode="test",
                 download=False):
        import gzip
        if download:
            raise RuntimeError("zero-egress: pass words_file/props_file")
        if not (words_file and props_file):
            raise ValueError("words_file and props_file are required")

        def read(path):
            op = gzip.open if str(path).endswith(".gz") else open
            with op(path, "rt") as f:
                return f.read().splitlines()

        sentences, labels = [], []
        cur_w, cur_l = [], []
        for wln, pln in zip(read(words_file), read(props_file)):
            if not wln.strip():
                if cur_w:
                    sentences.append(cur_w)
                    labels.append(cur_l)
                cur_w, cur_l = [], []
                continue
            cur_w.append(wln.strip())
            cur_l.append(pln.split()[-1])
        if cur_w:
            sentences.append(cur_w)
            labels.append(cur_l)
        self.word_dict = {}
        self.label_dict = {}
        self.data = []
        for ws, ls in zip(sentences, labels):
            wi = [self.word_dict.setdefault(w, len(self.word_dict))
                  for w in ws]
            li = [self.label_dict.setdefault(lb, len(self.label_dict))
                  for lb in ls]
            self.data.append((np.asarray(wi, np.int64),
                              np.asarray(li, np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class _WMTBase(Dataset):
    """Shared parallel-corpus reader: tar containing src/trg token files
    line-aligned; builds dicts with <s>/<e>/<unk> like the reference."""

    def __init__(self, data_file, src_name, trg_name, dict_size=-1,
                 mode="train"):
        import tarfile
        with tarfile.open(data_file) as tf:
            members = {m.name.rsplit("/", 1)[-1]: m for m in tf}
            src_lines = tf.extractfile(
                members[src_name]).read().decode().splitlines()
            trg_lines = tf.extractfile(
                members[trg_name]).read().decode().splitlines()
        self.src_dict = _build_word_dict(src_lines)
        self.trg_dict = _build_word_dict(trg_lines)
        if dict_size > 0:
            self.src_dict = {w: i for w, i in self.src_dict.items()
                             if i < dict_size}
            self.trg_dict = {w: i for w, i in self.trg_dict.items()
                             if i < dict_size}
        s, e = self.trg_dict["<s>"], self.trg_dict["<e>"]
        sunk = self.src_dict["<unk>"]
        tunk = self.trg_dict["<unk>"]
        self.data = []
        for sl, tl in zip(src_lines, trg_lines):
            src = [self.src_dict.get(w, sunk) for w in sl.split()]
            trg = [self.trg_dict.get(w, tunk) for w in tl.split()]
            if not src or not trg:
                continue
            self.data.append((np.asarray(src, np.int64),
                              np.asarray([s] + trg, np.int64),
                              np.asarray(trg + [e], np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class WMT14(_WMTBase):
    """reference wmt14.py: (src_ids, trg_in [<s>+trg], trg_out [trg+<e>])."""

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=False):
        if download:
            raise RuntimeError("zero-egress: pass the local tar via "
                               "data_file")
        if data_file is None:
            raise ValueError("data_file is required")
        name = {"train": "train", "test": "test", "gen": "gen"}[mode]
        super().__init__(data_file, f"{name}.src", f"{name}.trg",
                         dict_size, mode)


class WMT16(_WMTBase):
    """reference wmt16.py (multi30k layout: {mode}.en / {mode}.de)."""

    def __init__(self, data_file=None, mode="train", src_lang="en",
                 trg_lang="de", dict_size=-1, download=False):
        if download:
            raise RuntimeError("zero-egress: pass the local tar via "
                               "data_file")
        if data_file is None:
            raise ValueError("data_file is required")
        m = {"train": "train", "test": "test", "val": "val"}[mode]
        super().__init__(data_file, f"{m}.{src_lang}", f"{m}.{trg_lang}",
                         dict_size, mode)
