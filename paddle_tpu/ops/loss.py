"""Loss functional ops.

Parity targets: reference operators/softmax_with_cross_entropy_op.cc,
cross_entropy_op.cc, mean/squared_l2_distance, bce_loss_op.cc,
sigmoid_cross_entropy_with_logits_op.cc, huber_loss_op.cc, kldiv_loss_op.cc,
smooth_l1_loss_op.cc, margin_rank_loss, hinge, nll via gather, mse, ctc
(warpctc — deferred), label_smooth_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._dispatch import defop


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@defop
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        valid = lbl != ignore_index
        safe_lbl = jnp.where(valid, lbl, 0)  # avoid OOB gather on sentinel
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe_lbl, axis).astype(jnp.int32), axis=axis)
        loss = jnp.where(jnp.expand_dims(valid, axis), -picked, 0.0)
    if return_softmax:
        return loss, jax.nn.softmax(logits, axis=axis)
    return loss


@defop
def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    if use_softmax:
        logp = jax.nn.log_softmax(input, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(input, 1e-30))
    n_classes = input.shape[axis]
    if soft_label:
        soft = label
    else:
        lbl = label
        if lbl.ndim == input.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        soft = jax.nn.one_hot(lbl, n_classes, axis=axis, dtype=logp.dtype)
    if label_smoothing > 0.0:
        soft = soft * (1.0 - label_smoothing) + label_smoothing / n_classes
    loss = -jnp.sum(soft * logp, axis=axis)
    lbl1 = (jnp.squeeze(label, axis) if not soft_label and label.ndim == input.ndim
            else label)
    if not soft_label:
        valid = (lbl1 != ignore_index)
        # mean normalizes by the sum of selected weights over valid samples
        # (reference softmax_with_cross_entropy + weighted NLL semantics)
        w = (jnp.take(weight, jnp.where(valid, lbl1, 0))
             if weight is not None else jnp.ones_like(loss))
        w = jnp.where(valid, w, 0.0)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    return _reduce(loss, reduction)


@defop
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):  # noqa: A002
    valid = label != ignore_index
    safe = jnp.where(valid, label, 0)
    picked = -jnp.take_along_axis(input, safe[..., None].astype(jnp.int32),
                                  axis=-1)[..., 0]
    w = jnp.take(weight, safe) if weight is not None else jnp.ones_like(picked)
    w = jnp.where(valid, w, 0.0)
    picked = picked * w
    if reduction == "mean":
        return jnp.sum(picked) / jnp.maximum(jnp.sum(w), 1e-12)
    return _reduce(picked, reduction)


@defop
def mse_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.square(input - label), reduction)


@defop
def l1_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.abs(input - label), reduction)


@defop
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):  # noqa: A002
    d = input - label
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
    return _reduce(loss, reduction)


@defop
def huber_loss(input, label, delta=1.0):  # noqa: A002
    d = input - label
    ad = jnp.abs(d)
    return jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))


@defop
def binary_cross_entropy(input, label, weight=None, reduction="mean"):  # noqa: A002
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps))
             + (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    neg_abs = -jnp.abs(logit)
    loss = jnp.maximum(logit, 0.0) - logit * label + jnp.log1p(jnp.exp(neg_abs))
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = loss * log_w
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


sigmoid_cross_entropy_with_logits = binary_cross_entropy_with_logits


@defop
def kl_div(input, label, reduction="mean"):  # noqa: A002
    # input is log-prob, label is prob (paddle semantics)
    loss = label * (jnp.log(jnp.maximum(label, 1e-30)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@defop
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):  # noqa: A002
    loss = jnp.maximum(-label * (input - other) + margin, 0.0)
    return _reduce(loss, reduction)


@defop
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):  # noqa: A002
    loss = jnp.where(label == 1.0, input, jnp.maximum(0.0, margin - input))
    return _reduce(loss, reduction)


@defop
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


@defop
def label_smooth(label, prior_dist=None, epsilon=0.1):
    n = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / n


@defop
def square_error_cost(input, label):  # noqa: A002
    return jnp.square(input - label)


@defop
def log_loss(input, label, epsilon=1e-4):  # noqa: A002
    return -(label * jnp.log(input + epsilon)
             + (1 - label) * jnp.log(1 - input + epsilon))


@defop
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, reduction="mean"):
    dp = jnp.power(jnp.sum(jnp.power(jnp.abs(input - positive) + epsilon, p),
                           axis=-1), 1.0 / p)
    dn = jnp.power(jnp.sum(jnp.power(jnp.abs(input - negative) + epsilon, p),
                           axis=-1), 1.0 / p)
    return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)


@defop
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference operators/warpctc_op.cc wrapping Baidu warpctc).

    TPU-native design: warpctc's hand-written CPU/GPU alpha-beta kernels
    become a log-space alpha recursion under lax.scan over the extended
    (blank-interleaved) label sequence — fully differentiable by jax AD,
    so no hand-written beta/grad kernel is needed, and the whole loss
    jits into the training step.

    log_probs: [T, B, C] logits (softmax applied internally, matching
    warpctc); labels: [B, S] int; input_lengths/label_lengths: [B].
    """
    from jax.scipy.special import logsumexp

    lp = jax.nn.log_softmax(log_probs.astype(jnp.float32), axis=-1)
    T, B, C = lp.shape
    S = labels.shape[1]
    L = 2 * S + 1
    NEG = -1e30
    labels = labels.astype(jnp.int32)
    input_lengths = input_lengths.astype(jnp.int32)
    label_lengths = label_lengths.astype(jnp.int32)

    ext = jnp.full((B, L), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)                       # [B, L]
    # skip transition allowed into odd (label) positions whose label
    # differs from the one two slots back
    skip_ok = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] != ext[:, :-2]], axis=1)

    emit0 = jnp.take_along_axis(lp[0], ext, axis=1)         # [B, L]
    alpha0 = jnp.full((B, L), NEG)
    alpha0 = alpha0.at[:, 0].set(emit0[:, 0])
    if S > 0:
        has_label = (label_lengths > 0)
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(has_label, emit0[:, 1], NEG))

    def step(alpha, inp):
        lp_t, t = inp
        prev1 = jnp.concatenate(
            [jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate(
            [jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(skip_ok, prev2, NEG)
        merged = logsumexp(jnp.stack([alpha, prev1, prev2]), axis=0)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        new = merged + emit
        active = (t < input_lengths)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0,
                            (lp[1:], jnp.arange(1, T, dtype=jnp.int32)))

    idx_last = (2 * label_lengths)[:, None]                 # final blank
    idx_prev = jnp.maximum(idx_last - 1, 0)                 # final label
    a_last = jnp.take_along_axis(alpha, idx_last, axis=1)[:, 0]
    a_prev = jnp.where(
        label_lengths > 0,
        jnp.take_along_axis(alpha, idx_prev, axis=1)[:, 0], NEG)
    ll = logsumexp(jnp.stack([a_last, a_prev]), axis=0)     # [B]
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(input_lengths.astype(jnp.float32), 1.0)
    if reduction == "mean":
        # warpctc 'mean': per-sample loss over its label length, then
        # batch average (paddle.nn.CTCLoss and torch agree)
        return jnp.mean(
            loss / jnp.maximum(label_lengths.astype(jnp.float32), 1.0))
    return _reduce(loss, reduction)


@defop
def linear_chain_crf(emission, transition, label, length=None):
    """Linear-chain CRF negative log-likelihood (reference
    operators/linear_chain_crf_op.cc — alpha recursion over the log
    partition; transition[0]=start scores, transition[1]=stop scores,
    transition[2:]=pairwise [num_tags, num_tags], matching the reference's
    parameter layout).

    emission: [B, T, N]; transition: [N+2, N]; label: [B, T] int;
    length: [B] or None (= full T). Returns per-sequence NLL [B].
    """
    em = emission.astype(jnp.float32)
    B, T, N = em.shape
    start = transition[0].astype(jnp.float32)            # [N]
    stop = transition[1].astype(jnp.float32)             # [N]
    trans = transition[2:].astype(jnp.float32)           # [N, N] from->to
    label = label.astype(jnp.int32)
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    length = length.astype(jnp.int32)

    # ---- log partition via alpha recursion -------------------------------
    alpha0 = start[None, :] + em[:, 0]                   # [B, N]

    def step(alpha, inp):
        e_t, t = inp                                     # [B, N], scalar
        scores = alpha[:, :, None] + trans[None]         # [B, from, to]
        new = jax.scipy.special.logsumexp(scores, axis=1) + e_t
        active = (t < length)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = jax.lax.scan(
        step, alpha0,
        (jnp.moveaxis(em, 1, 0)[1:], jnp.arange(1, T, dtype=jnp.int32)))
    logZ = jax.scipy.special.logsumexp(alpha + stop[None, :], axis=1)

    # ---- gold path score -------------------------------------------------
    brange = jnp.arange(B)
    gold = start[label[:, 0]] + em[brange, 0, label[:, 0]]

    def gold_step(acc, inp):
        prev_y, y, e_t, t = inp
        add = trans[prev_y, y] + e_t[brange, y]
        return jnp.where(t < length, acc + add, acc), None

    gold, _ = jax.lax.scan(
        gold_step, gold,
        (jnp.moveaxis(label, 1, 0)[:-1], jnp.moveaxis(label, 1, 0)[1:],
         jnp.moveaxis(em, 1, 0)[1:], jnp.arange(1, T, dtype=jnp.int32)))
    last = jnp.clip(length - 1, 0, T - 1)
    gold = gold + stop[label[brange, last]]
    return logZ - gold


@defop
def viterbi_decode(emission, transition, length=None):
    """CRF argmax decoding (reference operators/crf_decoding_op.cc /
    paddle.text.viterbi_decode): returns (scores [B], paths [B, T])."""
    em = emission.astype(jnp.float32)
    B, T, N = em.shape
    start = transition[0].astype(jnp.float32)
    stop = transition[1].astype(jnp.float32)
    trans = transition[2:].astype(jnp.float32)
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    length = length.astype(jnp.int32)

    v0 = start[None, :] + em[:, 0]

    def step(v, inp):
        e_t, t = inp
        scores = v[:, :, None] + trans[None]             # [B, from, to]
        best_prev = jnp.argmax(scores, axis=1)           # [B, to]
        new = jnp.max(scores, axis=1) + e_t
        active = (t < length)[:, None]
        v_next = jnp.where(active, new, v)
        bp = jnp.where(active, best_prev,
                       jnp.arange(N)[None, :].repeat(B, 0))
        return v_next, bp

    v, bps = jax.lax.scan(
        step, v0,
        (jnp.moveaxis(em, 1, 0)[1:], jnp.arange(1, T, dtype=jnp.int32)))
    final = v + stop[None, :]
    scores = jnp.max(final, axis=1)
    last_tag = jnp.argmax(final, axis=1)                 # [B]

    def backtrack(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    _, path_rev = jax.lax.scan(backtrack, last_tag, bps, reverse=True)
    paths = jnp.concatenate(
        [jnp.moveaxis(path_rev, 0, 1),
         last_tag[:, None]], axis=1)                     # [B, T]
    return scores, paths.astype(jnp.int64)
