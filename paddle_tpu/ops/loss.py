"""Loss functional ops.

Parity targets: reference operators/softmax_with_cross_entropy_op.cc,
cross_entropy_op.cc, mean/squared_l2_distance, bce_loss_op.cc,
sigmoid_cross_entropy_with_logits_op.cc, huber_loss_op.cc, kldiv_loss_op.cc,
smooth_l1_loss_op.cc, margin_rank_loss, hinge, nll via gather, mse, ctc
(warpctc — deferred), label_smooth_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._dispatch import defop


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@defop
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        valid = lbl != ignore_index
        safe_lbl = jnp.where(valid, lbl, 0)  # avoid OOB gather on sentinel
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe_lbl, axis).astype(jnp.int32), axis=axis)
        loss = jnp.where(jnp.expand_dims(valid, axis), -picked, 0.0)
    if return_softmax:
        return loss, jax.nn.softmax(logits, axis=axis)
    return loss


@defop
def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    if use_softmax:
        logp = jax.nn.log_softmax(input, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(input, 1e-30))
    n_classes = input.shape[axis]
    if soft_label:
        soft = label
    else:
        lbl = label
        if lbl.ndim == input.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        soft = jax.nn.one_hot(lbl, n_classes, axis=axis, dtype=logp.dtype)
    if label_smoothing > 0.0:
        soft = soft * (1.0 - label_smoothing) + label_smoothing / n_classes
    loss = -jnp.sum(soft * logp, axis=axis)
    lbl1 = (jnp.squeeze(label, axis) if not soft_label and label.ndim == input.ndim
            else label)
    if not soft_label:
        valid = (lbl1 != ignore_index)
        # mean normalizes by the sum of selected weights over valid samples
        # (reference softmax_with_cross_entropy + weighted NLL semantics)
        w = (jnp.take(weight, jnp.where(valid, lbl1, 0))
             if weight is not None else jnp.ones_like(loss))
        w = jnp.where(valid, w, 0.0)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    return _reduce(loss, reduction)


@defop
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):  # noqa: A002
    valid = label != ignore_index
    safe = jnp.where(valid, label, 0)
    picked = -jnp.take_along_axis(input, safe[..., None].astype(jnp.int32),
                                  axis=-1)[..., 0]
    w = jnp.take(weight, safe) if weight is not None else jnp.ones_like(picked)
    w = jnp.where(valid, w, 0.0)
    picked = picked * w
    if reduction == "mean":
        return jnp.sum(picked) / jnp.maximum(jnp.sum(w), 1e-12)
    return _reduce(picked, reduction)


@defop
def mse_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.square(input - label), reduction)


@defop
def l1_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.abs(input - label), reduction)


@defop
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):  # noqa: A002
    d = input - label
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
    return _reduce(loss, reduction)


@defop
def huber_loss(input, label, delta=1.0):  # noqa: A002
    d = input - label
    ad = jnp.abs(d)
    return jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))


@defop
def binary_cross_entropy(input, label, weight=None, reduction="mean"):  # noqa: A002
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps))
             + (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    neg_abs = -jnp.abs(logit)
    loss = jnp.maximum(logit, 0.0) - logit * label + jnp.log1p(jnp.exp(neg_abs))
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = loss * log_w
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


sigmoid_cross_entropy_with_logits = binary_cross_entropy_with_logits


@defop
def kl_div(input, label, reduction="mean"):  # noqa: A002
    # input is log-prob, label is prob (paddle semantics)
    loss = label * (jnp.log(jnp.maximum(label, 1e-30)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@defop
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):  # noqa: A002
    loss = jnp.maximum(-label * (input - other) + margin, 0.0)
    return _reduce(loss, reduction)


@defop
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):  # noqa: A002
    loss = jnp.where(label == 1.0, input, jnp.maximum(0.0, margin - input))
    return _reduce(loss, reduction)


@defop
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


@defop
def label_smooth(label, prior_dist=None, epsilon=0.1):
    n = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / n


@defop
def square_error_cost(input, label):  # noqa: A002
    return jnp.square(input - label)


@defop
def log_loss(input, label, epsilon=1e-4):  # noqa: A002
    return -(label * jnp.log(input + epsilon)
             + (1 - label) * jnp.log(1 - input + epsilon))


@defop
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, reduction="mean"):
    dp = jnp.power(jnp.sum(jnp.power(jnp.abs(input - positive) + epsilon, p),
                           axis=-1), 1.0 / p)
    dn = jnp.power(jnp.sum(jnp.power(jnp.abs(input - negative) + epsilon, p),
                           axis=-1), 1.0 / p)
    return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)


@defop
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference operators/warpctc_op.cc wrapping Baidu warpctc).

    TPU-native design: warpctc's hand-written CPU/GPU alpha-beta kernels
    become a log-space alpha recursion under lax.scan over the extended
    (blank-interleaved) label sequence — fully differentiable by jax AD,
    so no hand-written beta/grad kernel is needed, and the whole loss
    jits into the training step.

    log_probs: [T, B, C] logits (softmax applied internally, matching
    warpctc); labels: [B, S] int; input_lengths/label_lengths: [B].
    """
    from jax.scipy.special import logsumexp

    lp = jax.nn.log_softmax(log_probs.astype(jnp.float32), axis=-1)
    T, B, C = lp.shape
    S = labels.shape[1]
    L = 2 * S + 1
    NEG = -1e30
    labels = labels.astype(jnp.int32)
    input_lengths = input_lengths.astype(jnp.int32)
    label_lengths = label_lengths.astype(jnp.int32)

    ext = jnp.full((B, L), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)                       # [B, L]
    # skip transition allowed into odd (label) positions whose label
    # differs from the one two slots back
    skip_ok = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] != ext[:, :-2]], axis=1)

    emit0 = jnp.take_along_axis(lp[0], ext, axis=1)         # [B, L]
    alpha0 = jnp.full((B, L), NEG)
    alpha0 = alpha0.at[:, 0].set(emit0[:, 0])
    if S > 0:
        has_label = (label_lengths > 0)
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(has_label, emit0[:, 1], NEG))

    def step(alpha, inp):
        lp_t, t = inp
        prev1 = jnp.concatenate(
            [jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate(
            [jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(skip_ok, prev2, NEG)
        merged = logsumexp(jnp.stack([alpha, prev1, prev2]), axis=0)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        new = merged + emit
        active = (t < input_lengths)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0,
                            (lp[1:], jnp.arange(1, T, dtype=jnp.int32)))

    idx_last = (2 * label_lengths)[:, None]                 # final blank
    idx_prev = jnp.maximum(idx_last - 1, 0)                 # final label
    a_last = jnp.take_along_axis(alpha, idx_last, axis=1)[:, 0]
    a_prev = jnp.where(
        label_lengths > 0,
        jnp.take_along_axis(alpha, idx_prev, axis=1)[:, 0], NEG)
    ll = logsumexp(jnp.stack([a_last, a_prev]), axis=0)     # [B]
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(input_lengths.astype(jnp.float32), 1.0)
    if reduction == "mean":
        # warpctc 'mean': per-sample loss over its label length, then
        # batch average (paddle.nn.CTCLoss and torch agree)
        return jnp.mean(
            loss / jnp.maximum(label_lengths.astype(jnp.float32), 1.0))
    return _reduce(loss, reduction)


@defop
def linear_chain_crf(emission, transition, label, length=None):
    """Linear-chain CRF negative log-likelihood (reference
    operators/linear_chain_crf_op.cc — alpha recursion over the log
    partition; transition[0]=start scores, transition[1]=stop scores,
    transition[2:]=pairwise [num_tags, num_tags], matching the reference's
    parameter layout).

    emission: [B, T, N]; transition: [N+2, N]; label: [B, T] int;
    length: [B] or None (= full T). Returns per-sequence NLL [B].
    """
    em = emission.astype(jnp.float32)
    B, T, N = em.shape
    start = transition[0].astype(jnp.float32)            # [N]
    stop = transition[1].astype(jnp.float32)             # [N]
    trans = transition[2:].astype(jnp.float32)           # [N, N] from->to
    label = label.astype(jnp.int32)
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    length = length.astype(jnp.int32)

    # ---- log partition via alpha recursion -------------------------------
    alpha0 = start[None, :] + em[:, 0]                   # [B, N]

    def step(alpha, inp):
        e_t, t = inp                                     # [B, N], scalar
        scores = alpha[:, :, None] + trans[None]         # [B, from, to]
        new = jax.scipy.special.logsumexp(scores, axis=1) + e_t
        active = (t < length)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = jax.lax.scan(
        step, alpha0,
        (jnp.moveaxis(em, 1, 0)[1:], jnp.arange(1, T, dtype=jnp.int32)))
    logZ = jax.scipy.special.logsumexp(alpha + stop[None, :], axis=1)

    # ---- gold path score -------------------------------------------------
    brange = jnp.arange(B)
    gold = start[label[:, 0]] + em[brange, 0, label[:, 0]]

    def gold_step(acc, inp):
        prev_y, y, e_t, t = inp
        add = trans[prev_y, y] + e_t[brange, y]
        return jnp.where(t < length, acc + add, acc), None

    gold, _ = jax.lax.scan(
        gold_step, gold,
        (jnp.moveaxis(label, 1, 0)[:-1], jnp.moveaxis(label, 1, 0)[1:],
         jnp.moveaxis(em, 1, 0)[1:], jnp.arange(1, T, dtype=jnp.int32)))
    last = jnp.clip(length - 1, 0, T - 1)
    gold = gold + stop[label[brange, last]]
    return logZ - gold


@defop
def viterbi_decode(emission, transition, length=None):
    """CRF argmax decoding (reference operators/crf_decoding_op.cc /
    paddle.text.viterbi_decode): returns (scores [B], paths [B, T])."""
    em = emission.astype(jnp.float32)
    B, T, N = em.shape
    start = transition[0].astype(jnp.float32)
    stop = transition[1].astype(jnp.float32)
    trans = transition[2:].astype(jnp.float32)
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    length = length.astype(jnp.int32)

    v0 = start[None, :] + em[:, 0]

    def step(v, inp):
        e_t, t = inp
        scores = v[:, :, None] + trans[None]             # [B, from, to]
        best_prev = jnp.argmax(scores, axis=1)           # [B, to]
        new = jnp.max(scores, axis=1) + e_t
        active = (t < length)[:, None]
        v_next = jnp.where(active, new, v)
        bp = jnp.where(active, best_prev,
                       jnp.arange(N)[None, :].repeat(B, 0))
        return v_next, bp

    v, bps = jax.lax.scan(
        step, v0,
        (jnp.moveaxis(em, 1, 0)[1:], jnp.arange(1, T, dtype=jnp.int32)))
    final = v + stop[None, :]
    scores = jnp.max(final, axis=1)
    last_tag = jnp.argmax(final, axis=1)                 # [B]

    def backtrack(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        # emit the PREDECESSOR: bps[k] maps tag@k+1 -> tag@k, so the
        # reverse scan's slot k must receive tag@k (emitting the carry
        # dropped tag@0 and duplicated the final tag)
        return prev, prev

    _, path_rev = jax.lax.scan(backtrack, last_tag, bps, reverse=True)
    paths = jnp.concatenate(
        [jnp.moveaxis(path_rev, 0, 1),
         last_tag[:, None]], axis=1)                     # [B, T]
    return scores, paths.astype(jnp.int64)


# -- round-4 widening (reference operators/: bpr_loss_op.cc,
#    center_loss_op.cc, hinge_loss_op.cc, rank_loss_op.cc,
#    modified_huber_loss_op.cc, teacher_student_sigmoid_loss_op.cc,
#    npair_loss [python/paddle/fluid/layers/loss.py], nce_op.cc,
#    hierarchical_sigmoid_op.cc, sigmoid_focal_loss) ----------------------


@defop
def bpr_loss(logits, label):
    """Bayesian personalized ranking: -mean log sigmoid(s_pos - s_neg)
    over the negatives (reference bpr_loss_op.cc)."""
    pos = jnp.take_along_axis(logits, label.reshape(-1, 1).astype(jnp.int32),
                              axis=1)
    diff = pos - logits                              # [n, classes]
    loss = -jax.nn.log_sigmoid(diff)
    n_cls = logits.shape[1]
    mask = jnp.ones_like(loss).at[
        jnp.arange(loss.shape[0]), label.reshape(-1).astype(jnp.int32)].set(0)
    return jnp.sum(loss * mask, axis=1, keepdims=True) / (n_cls - 1)


@defop
def hinge_loss(logits, label):
    """reference hinge_loss_op.cc: max(0, 1 - (2*label-1)*logits)."""
    pm = 2.0 * label - 1.0
    return jnp.maximum(0.0, 1.0 - pm * logits)


@defop
def rank_loss(label, left, right):
    """reference rank_loss_op.cc: sigmoid CE on pairwise score diff."""
    d = left - right
    return jnp.maximum(d, 0) - d * label + jnp.log1p(jnp.exp(-jnp.abs(d)))


@defop
def modified_huber_loss(x, y):
    """reference modified_huber_loss_op.cc: y in {0,1}; z = (2y-1)*x."""
    z = (2.0 * y - 1.0) * x
    return jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, jnp.square(1.0 - z), 0.0))


@defop
def teacher_student_sigmoid_loss(x, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """reference teacher_student_sigmoid_loss_op.cc (CTR distillation):
    teacher part is plain sigmoid CE on the click signal, student part is
    sigmoid CE against the teacher score carried in label's fraction."""
    x = jnp.clip(x, soft_max_lower_bound, soft_max_up_bound)
    teacher = jnp.where(label > -1.0, 1.0, 0.0)
    ce = jnp.maximum(x, 0) - x * teacher + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return ce


@defop
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """fluid/layers/loss.py npair_loss: softmax CE over anchor·positiveᵀ
    similarity with same-label targets + L2 on embeddings."""
    sim = anchor @ positive.T                         # [n, n]
    lab = labels.reshape(-1)
    same = (lab[:, None] == lab[None, :]).astype(sim.dtype)
    tgt = same / jnp.sum(same, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce_r = -jnp.mean(jnp.sum(tgt * logp, axis=1))
    ce_c = -jnp.mean(jnp.sum(tgt * jax.nn.log_softmax(sim.T, axis=1),
                             axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), axis=1))
                    + jnp.mean(jnp.sum(jnp.square(positive), axis=1))) / 2
    return (ce_r + ce_c) / 2 + reg


@defop
def center_loss(features, label, centers, alpha=0.1, update_center=True):
    """reference center_loss_op.cc: 0.5||f - c_y||²; returns (loss,
    new_centers) — centers move toward their class means at rate alpha."""
    lab = label.reshape(-1).astype(jnp.int32)
    c = centers[lab]                                  # [n, d]
    diff = features - c
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if update_center:
        num = jax.ops.segment_sum(diff, lab, num_segments=centers.shape[0])
        cnt = jax.ops.segment_sum(jnp.ones_like(lab, centers.dtype), lab,
                                  num_segments=centers.shape[0])
        centers = centers + alpha * num / (1.0 + cnt)[:, None]
    return loss, centers


@defop
def nce(input, label, weight, bias=None, sample_ids=None,  # noqa: A002
        num_neg_samples=5, num_total_classes=None):
    """Noise-contrastive estimation loss (reference nce_op.cc). The
    sampled negatives arrive as `sample_ids` [num_neg] (callers sample on
    host or via paddle.randint — sampling is not part of the compiled
    graph, matching the reference's CPU sampler)."""
    lab = label.reshape(-1).astype(jnp.int32)
    if sample_ids is None:
        raise ValueError("nce: pass sample_ids (host-sampled negatives)")
    sid = sample_ids.reshape(-1).astype(jnp.int32)
    def score(ids_vec, x):
        w = weight[ids_vec]                           # [k, d]
        s = x @ w.T                                   # [n, k]
        if bias is not None:
            s = s + bias[ids_vec]
        return s
    pos = jnp.sum(input * weight[lab], axis=1, keepdims=True)
    if bias is not None:
        pos = pos + bias[lab][:, None]
    neg = score(sid, input)                           # [n, num_neg]
    pos_loss = -jax.nn.log_sigmoid(pos)
    neg_loss = -jnp.sum(jax.nn.log_sigmoid(-neg), axis=1, keepdims=True)
    return pos_loss + neg_loss


@defop
def hsigmoid_loss(input, label, weight, bias=None,  # noqa: A002
                  num_classes=None):
    """Hierarchical sigmoid over the default complete binary tree
    (reference hierarchical_sigmoid_op.cc default path codes): class c's
    path is the binary expansion of c + num_classes in a heap layout."""
    n_cls = int(num_classes)
    code_len = max(1, (n_cls - 1).bit_length())
    lab = label.reshape(-1).astype(jnp.int32)
    node = lab + n_cls                                # heap leaf index
    losses = []
    for _ in range(code_len):
        parent = node // 2
        bit = (node % 2).astype(input.dtype)          # 1 = right child
        live = (parent >= 1) & (parent - 1 < weight.shape[0])
        w_idx = jnp.clip(parent - 1, 0, weight.shape[0] - 1)
        s = jnp.sum(input * weight[w_idx], axis=1)
        if bias is not None:
            s = s + bias.reshape(-1)[w_idx]
        ce = jnp.maximum(s, 0) - s * bit + jnp.log1p(jnp.exp(-jnp.abs(s)))
        losses.append(jnp.where(live, ce, 0.0))
        node = parent
    return sum(losses)[:, None]


@defop
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0):
    """reference operators/detection/sigmoid_focal_loss_op.cc."""
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label \
        + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return loss


# reference v1 op names for the same kernels (op_registry.h registers
# these exact strings; keep them resolvable in the inventory)
def kldiv_loss(x, target, reduction="mean"):
    return kl_div(x, target, reduction=reduction)


def bce_loss(input, label):  # noqa: A002
    return binary_cross_entropy(input, label, reduction="none")


def warpctc(logits, label, logits_length=None, labels_length=None,
            blank=0, norm_by_times=False):
    return ctc_loss(logits, label, logits_length, labels_length,
                    blank=blank)
