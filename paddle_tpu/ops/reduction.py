"""Reduction ops.

Parity targets: reference operators/reduce_ops/* (reduce_sum, reduce_mean,
reduce_max, reduce_min, reduce_prod, reduce_all, reduce_any, logsumexp),
arg_max/arg_min_op.cc, mean_op.cc, sum_op.cc and
python/paddle/tensor/math.py / stat.py reductions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._dispatch import defop


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@defop(name="sum")
def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    out = jnp.sum(x, axis=_norm_axis(axis), keepdims=keepdim)
    return out.astype(dtype) if dtype is not None else out


@defop
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop(name="max")
def max(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.max(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop(name="min")
def min(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.min(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop
def amax(x, axis=None, keepdim=False):
    return jnp.amax(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop
def amin(x, axis=None, keepdim=False):
    return jnp.amin(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop
def prod(x, axis=None, keepdim=False, dtype=None):
    out = jnp.prod(x, axis=_norm_axis(axis), keepdims=keepdim)
    return out.astype(dtype) if dtype is not None else out


@defop
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=_norm_axis(axis), keepdims=keepdim)
    return out.astype(jnp.int64 if dtype is None else dtype)


@defop
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=_norm_axis(axis), keepdims=keepdim)
    return out.astype(jnp.int64 if dtype is None else dtype)


@defop(name="all")
def all(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.all(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop(name="any")
def any(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.any(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@defop
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@defop
def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop
def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, q, axis=_norm_axis(axis), keepdims=keepdim)


@defop
def nansum(x, axis=None, keepdim=False):
    return jnp.nansum(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_norm_axis(axis), keepdims=keepdim)


@defop
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.sum((x != 0).astype(jnp.int64), axis=_norm_axis(axis),
                   keepdims=keepdim)


@defop
def mode(x, axis=-1, keepdim=False):
    """Most frequent value along `axis` (reference operators/mode_op —
    unreleased in ~2.0-rc but part of the 2.x surface; torch-compatible
    semantics: ties resolve to the smallest value). Returns (values,
    indices) with indices pointing into the input along `axis`.

    Fully vectorized for XLA: sort, mark run starts, recover each
    position's run length as index - cummax(start_index), take the run
    with the largest length. No data-dependent control flow."""
    ax = axis % x.ndim
    xm = jnp.moveaxis(x, ax, -1)
    n = xm.shape[-1]
    sort_idx = jnp.argsort(xm, axis=-1, stable=True)
    xs = jnp.take_along_axis(xm, sort_idx, axis=-1)
    idxs = jnp.arange(n, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones(xs.shape[:-1] + (1,), bool), xs[..., 1:] != xs[..., :-1]],
        axis=-1)
    start = jax.lax.cummax(jnp.where(is_start, idxs, jnp.int32(0)), axis=xs.ndim - 1)
    runlen = idxs - start + 1
    best = jnp.argmax(runlen, axis=-1)          # run end of earliest max run
    values = jnp.take_along_axis(xs, best[..., None], axis=-1)
    indices = jnp.take_along_axis(sort_idx, best[..., None], axis=-1)
    if keepdim:
        values = jnp.moveaxis(values, -1, ax)
        indices = jnp.moveaxis(indices, -1, ax)
    else:
        values = values[..., 0]
        indices = indices[..., 0]
    return values, indices


@defop
def kthvalue(x, k, axis=-1, keepdim=False):
    xs = jnp.sort(x, axis=axis)
    idx = jnp.argsort(x, axis=axis)
    val = jnp.take(xs, k - 1, axis=axis)
    ind = jnp.take(idx, k - 1, axis=axis)
    if keepdim:
        val = jnp.expand_dims(val, axis)
        ind = jnp.expand_dims(ind, axis)
    return val, ind.astype(jnp.int64)
