"""Detection op family.

Analog of reference paddle/fluid/operators/detection/ (~4k LoC of SSD/YOLO
box machinery: iou_similarity_op.cc, box_coder_op.cc, prior_box_op.cc,
yolo_box_op.cc, multiclass_nms_op.cc, bipartite_match_op.cc,
roi_align_op.cc, roi_pool_op.cc, box_clip_op.cc).

TPU design split: dense geometry (iou, coders, priors, yolo decode,
roi_align/pool) lowers to jnp — static shapes, fully jittable, roi_align
differentiable. Selection ops with data-dependent output sizes (nms
families, bipartite match) run as eager host kernels exactly like the
reference's CPU-only kernels for the same ops (multiclass_nms_op.cc has
no CUDA kernel either) — they sit at the postprocessing boundary where
the device step has already ended.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._dispatch import defop

__all__ = ["iou_similarity", "box_coder", "prior_box", "yolo_box",
           "box_clip", "roi_align", "roi_pool", "nms", "multiclass_nms",
           "bipartite_match"]


@defop
def iou_similarity(x, y, box_normalized=True):
    """[N,4] x [M,4] -> [N,M] IoU (reference iou_similarity_op.cc)."""
    off = 0.0 if box_normalized else 1.0
    ax1, ay1, ax2, ay2 = jnp.split(x, 4, axis=-1)        # [N,1]
    bx1, by1, bx2, by2 = [v.T for v in jnp.split(y, 4, axis=-1)]  # [1,M]
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1) + off, 0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1) + off, 0)
    inter = iw * ih
    area_a = (ax2 - ax1 + off) * (ay2 - ay1 + off)
    area_b = (bx2 - bx1 + off) * (by2 - by1 + off)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-10)


@defop
def box_coder(prior_box_, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    """reference box_coder_op.cc: encode/decode against priors."""
    off = 0.0 if box_normalized else 1.0
    pw = prior_box_[:, 2] - prior_box_[:, 0] + off
    ph = prior_box_[:, 3] - prior_box_[:, 1] + off
    pcx = prior_box_[:, 0] + pw * 0.5
    pcy = prior_box_[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((1, 4), target_box.dtype)
    else:
        var = prior_box_var.reshape(-1, 4)
    if code_type.startswith("encode"):
        tw = target_box[:, 2] - target_box[:, 0] + off
        th = target_box[:, 3] - target_box[:, 1] + off
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1)      # [N,M,4]
        return out / var[None, :, :] if var.shape[0] > 1 else out / var
    # decode: target_box [N, M, 4] deltas (or [N,4] with broadcast priors)
    t = target_box if target_box.ndim == 3 else target_box[:, None, :]
    v = var if var.shape[0] > 1 else jnp.broadcast_to(var, (pw.shape[0], 4))
    if axis == 0:
        pcx_, pcy_, pw_, ph_ = (a[None, :] for a in (pcx, pcy, pw, ph))
        v = v[None, :, :]
    else:
        pcx_, pcy_, pw_, ph_ = (a[:, None] for a in (pcx, pcy, pw, ph))
        v = v[:, None, :]
    cx = v[..., 0] * t[..., 0] * pw_ + pcx_
    cy = v[..., 1] * t[..., 1] * ph_ + pcy_
    w = jnp.exp(v[..., 2] * t[..., 2]) * pw_
    h = jnp.exp(v[..., 3] * t[..., 3]) * ph_
    out = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                     cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1)
    return out.reshape(target_box.shape)


@defop
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),  # noqa: A002
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5):
    """reference prior_box_op.cc (SSD anchor generation)."""
    fh, fw = input.shape[-2], input.shape[-1]
    ih, iw = image.shape[-2], image.shape[-1]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    whs = []
    for ms in min_sizes:
        for ar in ars:
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            for mx in max_sizes:
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    whs = jnp.asarray(whs)                                 # [P, 2]
    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                        # [fh, fw]
    cxy = jnp.stack([cxg, cyg], -1)[:, :, None, :]         # [fh,fw,1,2]
    half = whs[None, None, :, :] * 0.5
    mins = (cxy - half) / jnp.asarray([iw, ih])
    maxs = (cxy + half) / jnp.asarray([iw, ih])
    boxes = jnp.concatenate([mins, maxs], -1)              # [fh,fw,P,4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), boxes.shape)
    return boxes, var


@defop
def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """reference yolo_box_op.cc: decode YOLOv3 head output [N, A*(5+C), H, W]."""
    n, _, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, x.dtype).reshape(na, 2)
    x = x.reshape(n, na, 5 + class_num, h, w)
    gx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) / 2 + jnp.arange(w)[None, None, None, :]) / w
    gy = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) / 2 + jnp.arange(h)[None, None, :, None]) / h
    input_w = downsample_ratio * w
    input_h = downsample_ratio * h
    gw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    gh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imh = img_size[:, 0].astype(x.dtype)[:, None]
    imw = img_size[:, 1].astype(x.dtype)[:, None]
    flat = lambda v: v.reshape(n, -1)  # noqa: E731
    x1 = flat(gx - gw * 0.5) * imw
    y1 = flat(gy - gh * 0.5) * imh
    x2 = flat(gx + gw * 0.5) * imw
    y2 = flat(gy + gh * 0.5) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], -1)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    mask = flat(conf) > conf_thresh
    boxes = boxes * mask[..., None]
    scores = scores * mask[..., None]
    return boxes, scores


@defop
def box_clip(input, im_info):  # noqa: A002
    """reference box_clip_op.cc: clip boxes to image."""
    h = im_info[..., 0:1] - 1
    w = im_info[..., 1:2] - 1
    x1 = jnp.clip(input[..., 0::4], 0, w)
    y1 = jnp.clip(input[..., 1::4], 0, h)
    x2 = jnp.clip(input[..., 2::4], 0, w)
    y2 = jnp.clip(input[..., 3::4], 0, h)
    out = jnp.stack([x1, y1, x2, y2], -1)
    return out.reshape(input.shape)


@defop
def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """reference roi_align_op.cc: bilinear ROI pooling, differentiable.
    x: [N,C,H,W]; boxes: [R,4] (x1,y1,x2,y2) on image scale; boxes_num:
    rois per batch image (None => all on image 0)."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    n, c, H, W = x.shape
    r = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((r,), jnp.int32)
    else:
        batch_idx = jnp.repeat(jnp.arange(len(boxes_num)),
                               jnp.asarray(boxes_num),
                               total_repeat_length=r).astype(jnp.int32)
    off = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - off
    y1 = boxes[:, 1] * spatial_scale - off
    x2 = boxes[:, 2] * spatial_scale - off
    y2 = boxes[:, 3] * spatial_scale - off
    rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
    sr = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: [R, oh*sr, ow*sr]
    ys = y1[:, None] + rh[:, None] * (jnp.arange(oh * sr) + 0.5) / (oh * sr)
    xs = x1[:, None] + rw[:, None] * (jnp.arange(ow * sr) + 0.5) / (ow * sr)

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(yy, 0, H - 1) - y0
        wx = jnp.clip(xx, 0, W - 1) - x0
        y0i, x0i, y1i, x1i = (v.astype(jnp.int32) for v in (y0, x0, y1_, x1_))
        g = lambda yi, xi: img[:, yi, xi]  # noqa: E731  [C, ...]
        return (g(y0i, x0i) * (1 - wy) * (1 - wx) + g(y0i, x1i) * (1 - wy) * wx
                + g(y1i, x0i) * wy * (1 - wx) + g(y1i, x1i) * wy * wx)

    def per_roi(bi, yy, xx):
        img = x[bi]                                   # [C,H,W]
        grid_y = jnp.repeat(yy, ow * sr)              # [(oh*sr)*(ow*sr)]
        grid_x = jnp.tile(xx, oh * sr)
        vals = bilinear(img, grid_y, grid_x)          # [C, ohsr*owsr]
        vals = vals.reshape(c, oh, sr, ow, sr)
        return vals.mean(axis=(2, 4))                 # [C, oh, ow]

    return jax.vmap(per_roi)(batch_idx, ys, xs)


@defop
def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0):
    """reference roi_pool_op.cc: max pooling over quantized ROI bins."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    n, c, H, W = x.shape
    r = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((r,), jnp.int32)
    else:
        batch_idx = jnp.repeat(jnp.arange(len(boxes_num)),
                               jnp.asarray(boxes_num),
                               total_repeat_length=r).astype(jnp.int32)
    x1 = jnp.round(boxes[:, 0] * spatial_scale).astype(jnp.int32)
    y1 = jnp.round(boxes[:, 1] * spatial_scale).astype(jnp.int32)
    x2 = jnp.round(boxes[:, 2] * spatial_scale).astype(jnp.int32)
    y2 = jnp.round(boxes[:, 3] * spatial_scale).astype(jnp.int32)

    SR = 8  # fixed sample lattice per bin (static shapes; max over samples)

    def per_roi(bi, xx1, yy1, xx2, yy2):
        img = x[bi]
        rh = jnp.maximum(yy2 - yy1 + 1, 1)
        rw = jnp.maximum(xx2 - xx1 + 1, 1)
        ys = yy1 + (jnp.arange(oh * SR) * rh) // (oh * SR)
        xs = xx1 + (jnp.arange(ow * SR) * rw) // (ow * SR)
        ys = jnp.clip(ys, 0, H - 1)
        xs = jnp.clip(xs, 0, W - 1)
        grid_y = jnp.repeat(ys, ow * SR)
        grid_x = jnp.tile(xs, oh * SR)
        vals = img[:, grid_y, grid_x].reshape(c, oh, SR, ow, SR)
        return vals.max(axis=(2, 4))

    return jax.vmap(per_roi)(batch_idx, x1, y1, x2, y2)


# -- host-side selection kernels (eager; reference ships CPU-only too) ------

def _nms_np(boxes, scores, threshold, top_k=-1, eta=1.0):
    """Greedy NMS; eta < 1 is the reference's adaptive mode (threshold
    decays by eta after each kept box while it stays above 0.5)."""
    order = np.argsort(-scores)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    areas = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        if top_k > 0 and len(keep) >= top_k:
            break
        xx1 = np.maximum(boxes[i, 0], boxes[:, 0])
        yy1 = np.maximum(boxes[i, 1], boxes[:, 1])
        xx2 = np.minimum(boxes[i, 2], boxes[:, 2])
        yy2 = np.minimum(boxes[i, 3], boxes[:, 3])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= iou > threshold
        if eta < 1.0 and threshold > 0.5:
            threshold *= eta
    return np.asarray(keep, np.int64)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """reference nms_op / multiclass path. Eager host kernel (dynamic
    output size, like the reference's CPU-only kernel)."""
    from ..core.tensor import Tensor
    b = np.asarray(boxes._value if isinstance(boxes, Tensor) else boxes)
    s = np.asarray(scores._value if isinstance(scores, Tensor) else scores) \
        if scores is not None else np.arange(len(b), 0, -1, dtype=np.float32)
    if category_idxs is not None:
        cats = np.asarray(category_idxs._value
                          if isinstance(category_idxs, Tensor)
                          else category_idxs)
        keep_all = []
        for cval in (categories if categories is not None
                     else np.unique(cats)):
            idx = np.nonzero(cats == cval)[0]
            kept = _nms_np(b[idx], s[idx], iou_threshold)
            keep_all.append(idx[kept])
        keep = np.concatenate(keep_all) if keep_all else np.zeros(0, np.int64)
        keep = keep[np.argsort(-s[keep])]
    else:
        keep = _nms_np(b, s, iou_threshold)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep), _internal=True)


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   background_label=0):
    """reference multiclass_nms_op.cc: per-class NMS then global keep_top_k.
    bboxes [N, M, 4]; scores [N, C, M]. Returns (out [K, 6], rois_num)."""
    from ..core.tensor import Tensor
    b = np.asarray(bboxes._value if isinstance(bboxes, Tensor) else bboxes)
    s = np.asarray(scores._value if isinstance(scores, Tensor) else scores)
    outs, nums = [], []
    for n in range(b.shape[0]):
        dets = []
        for c in range(s.shape[1]):
            if c == background_label:
                continue
            sc = s[n, c]
            m = sc > score_threshold
            if not m.any():
                continue
            idx = np.nonzero(m)[0]
            order = idx[np.argsort(-sc[idx])][:nms_top_k]
            kept = _nms_np(b[n][order], sc[order], nms_threshold)
            for i in order[kept]:
                dets.append([c, sc[i], *b[n, i]])
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        outs.extend(dets)
        nums.append(len(dets))
    out = np.asarray(outs, np.float32).reshape(-1, 6)
    return (Tensor(jnp.asarray(out), _internal=True),
            Tensor(jnp.asarray(np.asarray(nums, np.int32)), _internal=True))


def bipartite_match(dist_mat):
    """reference bipartite_match_op.cc greedy bipartite matching:
    repeatedly take the global max entry, match that (row, col) pair.
    Returns (match_indices [M], match_dist [M]) over columns."""
    from ..core.tensor import Tensor
    d = np.array(np.asarray(dist_mat._value
                            if isinstance(dist_mat, Tensor) else dist_mat),
                 copy=True)
    n, m = d.shape
    match_idx = np.full(m, -1, np.int64)
    match_dist = np.zeros(m, np.float32)
    used_rows = np.zeros(n, bool)
    used_cols = np.zeros(m, bool)
    for _ in range(min(n, m)):
        i, j = np.unravel_index(np.argmax(d), d.shape)
        if d[i, j] <= 0:
            break
        match_idx[j] = i
        match_dist[j] = d[i, j]
        used_rows[i] = True
        used_cols[j] = True
        d[i, :] = -1
        d[:, j] = -1
    return (Tensor(jnp.asarray(match_idx), _internal=True),
            Tensor(jnp.asarray(match_dist), _internal=True))


# -- round-4 widening: the rest of the frequently-used detection zoo
#    (reference operators/detection/: anchor_generator_op.cc,
#    density_prior_box_op.cc, matrix_nms_op.cc, target_assign_op.cc,
#    polygon_box_transform_op.cc, distribute_fpn_proposals_op.cc,
#    collect_fpn_proposals_op.cc, yolov3_loss_op.cc,
#    box_decoder_and_assign_op.cc, mine_hard_examples_op.cc) --------------

__all__ += ["anchor_generator", "density_prior_box", "matrix_nms",
            "target_assign", "polygon_box_transform",
            "distribute_fpn_proposals", "collect_fpn_proposals",
            "box_decoder_and_assign", "mine_hard_examples", "yolov3_loss"]


@defop
def anchor_generator(input, anchor_sizes, aspect_ratios,  # noqa: A002
                     variances=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5):
    """reference anchor_generator_op.cc (Faster-RCNN RPN anchors):
    [fh, fw, A, 4] xyxy anchors in INPUT-image pixels + variances."""
    fh, fw = input.shape[-2], input.shape[-1]
    whs = []
    for s in anchor_sizes:
        for ar in aspect_ratios:
            area = float(s) * float(s)
            w = np.sqrt(area / ar)
            whs.append((w, w * ar))
    whs = jnp.asarray(whs)                                 # [A, 2]
    cx = (jnp.arange(fw) + offset) * stride[0]
    cy = (jnp.arange(fh) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    cxy = jnp.stack([cxg, cyg], -1)[:, :, None, :]
    half = whs[None, None] * 0.5
    anchors = jnp.concatenate([cxy - half, cxy + half], -1)
    var = jnp.broadcast_to(jnp.asarray(variances), anchors.shape)
    return anchors, var


@defop
def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,  # noqa: A002
                      variances=(0.1, 0.1, 0.2, 0.2), steps=(0.0, 0.0),
                      offset=0.5, clip=False):
    """reference density_prior_box_op.cc (SSD-variant dense anchors):
    each (density, fixed_size) pair tiles density^2 shifted centers."""
    fh, fw = input.shape[-2], input.shape[-1]
    ih, iw = image.shape[-2], image.shape[-1]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    boxes_per_cell = []
    for density, size in zip(densities, fixed_sizes):
        for ratio in fixed_ratios:
            w = size * np.sqrt(ratio)
            h = size / np.sqrt(ratio)
            shift_w = step_w / density
            shift_h = step_h / density
            for di in range(density):
                for dj in range(density):
                    boxes_per_cell.append(
                        (dj * shift_w + shift_w / 2 - step_w / 2,
                         di * shift_h + shift_h / 2 - step_h / 2, w, h))
    spec = jnp.asarray(boxes_per_cell)                     # [P, 4]
    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    centers = jnp.stack([cxg, cyg], -1)[:, :, None, :]     # [fh,fw,1,2]
    ctr = centers + spec[None, None, :, :2]
    half = spec[None, None, :, 2:] * 0.5
    mins = (ctr - half) / jnp.asarray([iw, ih])
    maxs = (ctr + half) / jnp.asarray([iw, ih])
    out = jnp.concatenate([mins, maxs], -1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return out, var


def matrix_nms(bboxes, scores, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0):
    """reference matrix_nms_op.cc (SOLOv2 parallel soft-NMS): score decay
    from pairwise IoUs, no sequential suppression loop. Eager host op
    (data-dependent output), like the reference's CPU-only kernel.
    bboxes [N,4], scores [C,N] -> (out [n,6] label/score/xyxy, indices)."""
    bboxes = np.asarray(getattr(bboxes, "numpy", lambda: bboxes)())
    scores = np.asarray(getattr(scores, "numpy", lambda: scores)())
    outs = []
    idxs = []
    for c in range(scores.shape[0]):
        s = scores[c]
        keep = np.where(s > score_threshold)[0]
        if keep.size == 0:
            continue
        order = keep[np.argsort(-s[keep])][:nms_top_k]
        b = bboxes[order]
        sv = s[order]
        n = len(order)
        x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
        area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
        iw = np.maximum(np.minimum(x2[:, None], x2[None]) -
                        np.maximum(x1[:, None], x1[None]), 0)
        ih = np.maximum(np.minimum(y2[:, None], y2[None]) -
                        np.maximum(y1[:, None], y1[None]), 0)
        inter = iw * ih
        iou = inter / np.maximum(area[:, None] + area[None] - inter, 1e-10)
        iou = np.triu(iou, 1)                    # higher-scored pairs only
        iou_max = iou.max(axis=0)                # per-box max overlap
        comp = iou.max(axis=1, initial=0)
        if use_gaussian:
            decay = np.exp(-(iou_max ** 2 - comp ** 2) / gaussian_sigma)
        else:
            decay = (1 - iou_max) / np.maximum(1 - comp, 1e-10)
        decayed = sv * np.minimum(decay, 1.0)
        sel = decayed > post_threshold
        for i in np.where(sel)[0]:
            outs.append([c, decayed[i], *b[i]])
            idxs.append(order[i])
    if not outs:
        from ._dispatch import wrap
        return wrap(jnp.zeros((0, 6), jnp.float32)), \
            wrap(jnp.zeros((0,), jnp.int64))
    outs = np.asarray(outs, np.float32)
    idxs = np.asarray(idxs, np.int64)
    order = np.argsort(-outs[:, 1])[:keep_top_k]
    from ._dispatch import wrap
    return wrap(jnp.asarray(outs[order])), wrap(jnp.asarray(idxs[order]))


@defop
def target_assign(x, match_indices, mismatch_value=0):
    """reference target_assign_op.cc: per-prior gather of matched gt rows;
    match_indices [N, M] (-1 = unmatched -> mismatch_value, weight 0).
    x [N, G, K] -> (out [N, M, K], weights [N, M, 1])."""
    mi = match_indices.astype(jnp.int32)
    safe = jnp.maximum(mi, 0)
    out = jnp.take_along_axis(x, safe[:, :, None], axis=1)
    matched = (mi >= 0)[:, :, None]
    out = jnp.where(matched, out, mismatch_value)
    return out, matched.astype(x.dtype)


@defop
def polygon_box_transform(input):  # noqa: A002
    """reference polygon_box_transform_op.cc (EAST text detection):
    channels are (dx, dy) offset pairs; convert offsets at each grid cell
    into absolute vertex coordinates: out = 4*grid_coord - offset."""
    n, c, h, w = input.shape
    xs = jnp.arange(w, dtype=input.dtype)[None, None, None, :]
    ys = jnp.arange(h, dtype=input.dtype)[None, None, :, None]
    idx = jnp.arange(c)[None, :, None, None]
    grid = jnp.where(idx % 2 == 0, xs * jnp.ones((h, w), input.dtype),
                     ys * jnp.ones((h, w), input.dtype))
    return 4.0 * grid - input


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale):
    """reference distribute_fpn_proposals_op.cc: route each RoI to its
    pyramid level by sqrt-area heuristic. Eager (data-dependent splits).
    Returns (rois_per_level list, restore_index)."""
    rois = np.asarray(getattr(fpn_rois, "numpy", lambda: fpn_rois)())
    w = np.maximum(rois[:, 2] - rois[:, 0], 0)
    h = np.maximum(rois[:, 3] - rois[:, 1], 0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    from ._dispatch import wrap
    outs = []
    order = []
    for level in range(min_level, max_level + 1):
        idx = np.where(lvl == level)[0]
        order.append(idx)
        outs.append(wrap(jnp.asarray(rois[idx])))
    order = np.concatenate(order) if order else np.zeros((0,), int)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    return outs, wrap(jnp.asarray(restore.astype(np.int64)))


def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n):
    """reference collect_fpn_proposals_op.cc: concat per-level RoIs, keep
    the global top-n by score. Eager."""
    rois = np.concatenate([np.asarray(getattr(r, "numpy", lambda r=r: r)())
                           for r in multi_rois], axis=0)
    scores = np.concatenate(
        [np.asarray(getattr(s, "numpy", lambda s=s: s)()).reshape(-1)
         for s in multi_scores], axis=0)
    order = np.argsort(-scores)[:post_nms_top_n]
    from ._dispatch import wrap
    return wrap(jnp.asarray(rois[order]))


@defop
def box_decoder_and_assign(prior_box_, prior_box_var, target_box,
                           box_score, box_clip_value=4.135):
    """reference box_decoder_and_assign_op.cc (Cascade R-CNN): decode
    per-class deltas against priors, then assign each prior its best
    class's box. target_box [N, C*4], box_score [N, C]."""
    n = prior_box_.shape[0]
    c = box_score.shape[1]
    pw = prior_box_[:, 2] - prior_box_[:, 0]
    ph = prior_box_[:, 3] - prior_box_[:, 1]
    pcx = prior_box_[:, 0] + pw * 0.5
    pcy = prior_box_[:, 1] + ph * 0.5
    t = jnp.reshape(target_box, (n, c, 4))
    var = jnp.reshape(prior_box_var, (-1, 4))
    dx = t[:, :, 0] * var[:, 0:1]
    dy = t[:, :, 1] * var[:, 1:2]
    dw = jnp.clip(t[:, :, 2] * var[:, 2:3], -box_clip_value, box_clip_value)
    dh = jnp.clip(t[:, :, 3] * var[:, 3:4], -box_clip_value, box_clip_value)
    cx = pcx[:, None] + dx * pw[:, None]
    cy = pcy[:, None] + dy * ph[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    decoded = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                        axis=-1)                            # [N, C, 4]
    best = jnp.argmax(box_score, axis=1)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    return jnp.reshape(decoded, (n, c * 4)), assigned


@defop
def mine_hard_examples(cls_loss, match_indices, neg_pos_ratio=3.0,
                       mining_type="max_negative"):
    """reference mine_hard_examples_op.cc (SSD OHEM): pick the highest-
    loss negatives up to ratio * n_positives per sample. Returns a 0/1
    mask over [N, M] priors selecting mined negatives."""
    neg = match_indices < 0                                  # [N, M]
    n_pos = jnp.sum(~neg, axis=1, keepdims=True)
    quota = jnp.ceil(neg_pos_ratio * n_pos).astype(jnp.int32)
    masked_loss = jnp.where(neg, cls_loss, -jnp.inf)
    order = jnp.argsort(-masked_loss, axis=1)
    rank = jnp.argsort(order, axis=1)                        # rank per slot
    return (neg & (rank < quota)).astype(jnp.int32)


@defop
def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh=0.7, downsample_ratio=32,
                use_label_smooth=False):
    """reference yolov3_loss_op.cc — simplified faithful form: decode the
    head like yolo_box, build targets from gt boxes whose best-matching
    anchor is in anchor_mask, sum coordinate + objectness + class BCE
    losses. x [N, A*(5+C), H, W]; gt_box [N, B, 4] (cx, cy, w, h relative);
    gt_label [N, B]."""
    n, _, h, w = x.shape
    a = len(anchor_mask)
    c = int(class_num)
    xr = jnp.reshape(x, (n, a, 5 + c, h, w))
    pred_xy = jax.nn.sigmoid(xr[:, :, 0:2])
    pred_wh = xr[:, :, 2:4]
    pred_obj = xr[:, :, 4]
    pred_cls = xr[:, :, 5:]

    masked = [(anchors[2 * i], anchors[2 * i + 1]) for i in anchor_mask]
    all_anchors = [(anchors[2 * i], anchors[2 * i + 1])
                   for i in range(len(anchors) // 2)]
    stride = float(downsample_ratio)
    in_w, in_h = w * stride, h * stride

    total = jnp.zeros((n,), jnp.float32)
    gt_box = gt_box.astype(jnp.float32)
    B = gt_box.shape[1]
    for bi in range(B):
        gx, gy, gw, gh = (gt_box[:, bi, k] for k in range(4))
        valid = (gw > 0) & (gh > 0)
        # best anchor by wh IoU at origin
        ious = []
        for aw, ah in all_anchors:
            iw = jnp.minimum(gw * in_w, aw)
            ih = jnp.minimum(gh * in_h, ah)
            inter = iw * ih
            union = gw * in_w * gh * in_h + aw * ah - inter
            ious.append(inter / jnp.maximum(union, 1e-10))
        best = jnp.argmax(jnp.stack(ious), axis=0)           # [N]
        for mi, src in enumerate(anchor_mask):
            sel = valid & (best == src)
            gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)
            gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)
            tx = gx * w - gi
            ty = gy * h - gj
            aw, ah = masked[mi]
            tw = jnp.log(jnp.maximum(gw * in_w / aw, 1e-9))
            th = jnp.log(jnp.maximum(gh * in_h / ah, 1e-9))
            bidx = jnp.arange(n)
            pxy = pred_xy[bidx, mi, :, gj, gi]
            pwh = pred_wh[bidx, mi, :, gj, gi]
            pob = pred_obj[bidx, mi, gj, gi]
            pcl = pred_cls[bidx, mi, :, gj, gi]
            scale = 2.0 - gw * gh
            coord = (jnp.square(pxy[:, 0] - tx) + jnp.square(pxy[:, 1] - ty)
                     + jnp.square(pwh[:, 0] - tw)
                     + jnp.square(pwh[:, 1] - th)) * scale
            obj = -jax.nn.log_sigmoid(pob)
            lbl = gt_label[:, bi].astype(jnp.int32)
            onehot = jax.nn.one_hot(lbl, c)
            if use_label_smooth:
                onehot = onehot * (1 - 1.0 / c) + 1.0 / c * (1 - onehot)
            cls = jnp.sum(jnp.maximum(pcl, 0) - pcl * onehot
                          + jnp.log1p(jnp.exp(-jnp.abs(pcl))), axis=1)
            total = total + jnp.where(sel, coord + obj + cls, 0.0)
    # negative objectness for all cells (ignoring high-IoU handled by
    # callers' ignore mask in the full pipeline; simplified here)
    noobj = -jax.nn.log_sigmoid(-pred_obj)
    total = total + jnp.sum(noobj, axis=(1, 2, 3)) / (a * h * w)
    return total


__all__ += ["generate_proposals", "retinanet_detection_output"]


def generate_proposals(scores, bbox_deltas, im_shape, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=True, return_rois_num=False):
    """reference detection/generate_proposals_op.cc (+ the v2 variant's
    pixel_offset flag): RPN box decoding -> clip to image -> min-size
    filter -> top-K by score -> NMS -> top post_nms. Eager host kernel
    (dynamic output size, like the reference's CPU kernel); per-image loop
    over the batch.

    scores [N, A, H, W]; bbox_deltas [N, 4A, H, W]; anchors [H, W, A, 4]
    (or [H*W*A, 4]); variances like anchors; im_shape [N, 2] (h, w).
    Returns (rois [R, 4], roi_probs [R, 1]) (+ rois_num [N] if asked).
    """
    from ..core.tensor import Tensor

    def _np(v):
        return np.asarray(v._value if isinstance(v, Tensor) else v)

    sc, bd = _np(scores), _np(bbox_deltas)
    anc = _np(anchors).reshape(-1, 4).astype(np.float64)
    var = _np(variances).reshape(-1, 4).astype(np.float64)
    ims = _np(im_shape)
    n, a, h, w = sc.shape
    offset = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, nums = [], [], []
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)          # H,W,A
        d = bd[i].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, anc_i, var_i = s[order], d[order], anc[order], var[order]
        aw = anc_i[:, 2] - anc_i[:, 0] + offset
        ah = anc_i[:, 3] - anc_i[:, 1] + offset
        acx, acy = anc_i[:, 0] + aw * 0.5, anc_i[:, 1] + ah * 0.5
        dx, dy, dw, dh = (d * var_i).T
        cx, cy = dx * aw + acx, dy * ah + acy
        bw = np.exp(np.minimum(dw, np.log(1000.0 / 16))) * aw
        bh = np.exp(np.minimum(dh, np.log(1000.0 / 16))) * ah
        boxes = np.stack([cx - bw * 0.5, cy - bh * 0.5,
                          cx + bw * 0.5 - offset,
                          cy + bh * 0.5 - offset], axis=1)
        imh, imw = float(ims[i][0]), float(ims[i][1])
        boxes[:, 0::2] = boxes[:, 0::2].clip(0, imw - offset)
        boxes[:, 1::2] = boxes[:, 1::2].clip(0, imh - offset)
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + offset >= min_size)
                   & (boxes[:, 3] - boxes[:, 1] + offset >= min_size))
        boxes, s = boxes[keep_sz], s[keep_sz]
        keep = _nms_np(boxes, s, nms_thresh, top_k=post_nms_top_n, eta=eta)
        all_rois.append(boxes[keep])
        all_probs.append(s[keep, None])
        nums.append(len(keep))
    rois = np.concatenate(all_rois) if all_rois else np.zeros((0, 4))
    probs = np.concatenate(all_probs) if all_probs else np.zeros((0, 1))
    out = (Tensor(jnp.asarray(rois.astype(np.float32)), _internal=True),
           Tensor(jnp.asarray(probs.astype(np.float32)), _internal=True))
    if return_rois_num:
        out += (Tensor(jnp.asarray(np.asarray(nums, np.int32)),
                       _internal=True),)
    return out


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.45,
                               nms_eta=1.0):
    """reference detection/retinanet_detection_output_op.cc: decode
    per-FPN-level predictions and run class-wise NMS. Composed from
    box_coder + multiclass_nms (eager host kernel)."""
    from ..core.tensor import Tensor

    def _np(v):
        return np.asarray(v._value if isinstance(v, Tensor) else v)

    box_l = [_np(b) for b in (bboxes if isinstance(bboxes, (list, tuple))
                              else [bboxes])]
    sc_l = [_np(s) for s in (scores if isinstance(scores, (list, tuple))
                             else [scores])]
    anc_l = [_np(a).reshape(-1, 4) for a in
             (anchors if isinstance(anchors, (list, tuple)) else [anchors])]
    n = box_l[0].shape[0]
    outs = []
    for i in range(n):
        dets_boxes, dets_scores = [], []
        for bx, scl, anc in zip(box_l, sc_l, anc_l):
            d = bx[i].reshape(-1, 4)
            s = scl[i].reshape(d.shape[0], -1)
            aw = anc[:, 2] - anc[:, 0] + 1
            ah = anc[:, 3] - anc[:, 1] + 1
            acx, acy = anc[:, 0] + aw * 0.5, anc[:, 1] + ah * 0.5
            cx, cy = d[:, 0] * aw + acx, d[:, 1] * ah + acy
            bw, bh = np.exp(d[:, 2]) * aw, np.exp(d[:, 3]) * ah
            box = np.stack([cx - bw * 0.5, cy - bh * 0.5,
                            cx + bw * 0.5 - 1, cy + bh * 0.5 - 1], 1)
            dets_boxes.append(box)
            dets_scores.append(s)
        boxes = np.concatenate(dets_boxes)            # [M, 4]
        scs = np.concatenate(dets_scores)             # [M, C]
        results = []
        for c in range(scs.shape[1]):
            mask = scs[:, c] > score_threshold
            if not mask.any():
                continue
            bsel, ssel = boxes[mask], scs[mask, c]
            order = np.argsort(-ssel)[:nms_top_k]
            keep = _nms_np(bsel[order], ssel[order], nms_threshold,
                           eta=nms_eta)
            for j in keep:
                results.append([c, ssel[order][j], *bsel[order][j]])
        res = np.asarray(sorted(results, key=lambda r: -r[1])[:keep_top_k],
                         np.float32).reshape(-1, 6)
        outs.append(res)
    out = np.concatenate(outs) if outs else np.zeros((0, 6), np.float32)
    return Tensor(jnp.asarray(out), _internal=True)
