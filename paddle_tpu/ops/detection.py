"""Detection op family.

Analog of reference paddle/fluid/operators/detection/ (~4k LoC of SSD/YOLO
box machinery: iou_similarity_op.cc, box_coder_op.cc, prior_box_op.cc,
yolo_box_op.cc, multiclass_nms_op.cc, bipartite_match_op.cc,
roi_align_op.cc, roi_pool_op.cc, box_clip_op.cc).

TPU design split: dense geometry (iou, coders, priors, yolo decode,
roi_align/pool) lowers to jnp — static shapes, fully jittable, roi_align
differentiable. Selection ops with data-dependent output sizes (nms
families, bipartite match) run as eager host kernels exactly like the
reference's CPU-only kernels for the same ops (multiclass_nms_op.cc has
no CUDA kernel either) — they sit at the postprocessing boundary where
the device step has already ended.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._dispatch import defop

__all__ = ["iou_similarity", "box_coder", "prior_box", "yolo_box",
           "box_clip", "roi_align", "roi_pool", "nms", "multiclass_nms",
           "bipartite_match"]


@defop
def iou_similarity(x, y, box_normalized=True):
    """[N,4] x [M,4] -> [N,M] IoU (reference iou_similarity_op.cc)."""
    off = 0.0 if box_normalized else 1.0
    ax1, ay1, ax2, ay2 = jnp.split(x, 4, axis=-1)        # [N,1]
    bx1, by1, bx2, by2 = [v.T for v in jnp.split(y, 4, axis=-1)]  # [1,M]
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1) + off, 0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1) + off, 0)
    inter = iw * ih
    area_a = (ax2 - ax1 + off) * (ay2 - ay1 + off)
    area_b = (bx2 - bx1 + off) * (by2 - by1 + off)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-10)


@defop
def box_coder(prior_box_, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    """reference box_coder_op.cc: encode/decode against priors."""
    off = 0.0 if box_normalized else 1.0
    pw = prior_box_[:, 2] - prior_box_[:, 0] + off
    ph = prior_box_[:, 3] - prior_box_[:, 1] + off
    pcx = prior_box_[:, 0] + pw * 0.5
    pcy = prior_box_[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((1, 4), target_box.dtype)
    else:
        var = prior_box_var.reshape(-1, 4)
    if code_type.startswith("encode"):
        tw = target_box[:, 2] - target_box[:, 0] + off
        th = target_box[:, 3] - target_box[:, 1] + off
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1)      # [N,M,4]
        return out / var[None, :, :] if var.shape[0] > 1 else out / var
    # decode: target_box [N, M, 4] deltas (or [N,4] with broadcast priors)
    t = target_box if target_box.ndim == 3 else target_box[:, None, :]
    v = var if var.shape[0] > 1 else jnp.broadcast_to(var, (pw.shape[0], 4))
    if axis == 0:
        pcx_, pcy_, pw_, ph_ = (a[None, :] for a in (pcx, pcy, pw, ph))
        v = v[None, :, :]
    else:
        pcx_, pcy_, pw_, ph_ = (a[:, None] for a in (pcx, pcy, pw, ph))
        v = v[:, None, :]
    cx = v[..., 0] * t[..., 0] * pw_ + pcx_
    cy = v[..., 1] * t[..., 1] * ph_ + pcy_
    w = jnp.exp(v[..., 2] * t[..., 2]) * pw_
    h = jnp.exp(v[..., 3] * t[..., 3]) * ph_
    out = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                     cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1)
    return out.reshape(target_box.shape)


@defop
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),  # noqa: A002
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5):
    """reference prior_box_op.cc (SSD anchor generation)."""
    fh, fw = input.shape[-2], input.shape[-1]
    ih, iw = image.shape[-2], image.shape[-1]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    whs = []
    for ms in min_sizes:
        for ar in ars:
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            for mx in max_sizes:
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    whs = jnp.asarray(whs)                                 # [P, 2]
    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                        # [fh, fw]
    cxy = jnp.stack([cxg, cyg], -1)[:, :, None, :]         # [fh,fw,1,2]
    half = whs[None, None, :, :] * 0.5
    mins = (cxy - half) / jnp.asarray([iw, ih])
    maxs = (cxy + half) / jnp.asarray([iw, ih])
    boxes = jnp.concatenate([mins, maxs], -1)              # [fh,fw,P,4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), boxes.shape)
    return boxes, var


@defop
def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """reference yolo_box_op.cc: decode YOLOv3 head output [N, A*(5+C), H, W]."""
    n, _, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, x.dtype).reshape(na, 2)
    x = x.reshape(n, na, 5 + class_num, h, w)
    gx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) / 2 + jnp.arange(w)[None, None, None, :]) / w
    gy = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) / 2 + jnp.arange(h)[None, None, :, None]) / h
    input_w = downsample_ratio * w
    input_h = downsample_ratio * h
    gw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    gh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imh = img_size[:, 0].astype(x.dtype)[:, None]
    imw = img_size[:, 1].astype(x.dtype)[:, None]
    flat = lambda v: v.reshape(n, -1)  # noqa: E731
    x1 = flat(gx - gw * 0.5) * imw
    y1 = flat(gy - gh * 0.5) * imh
    x2 = flat(gx + gw * 0.5) * imw
    y2 = flat(gy + gh * 0.5) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], -1)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    mask = flat(conf) > conf_thresh
    boxes = boxes * mask[..., None]
    scores = scores * mask[..., None]
    return boxes, scores


@defop
def box_clip(input, im_info):  # noqa: A002
    """reference box_clip_op.cc: clip boxes to image."""
    h = im_info[..., 0:1] - 1
    w = im_info[..., 1:2] - 1
    x1 = jnp.clip(input[..., 0::4], 0, w)
    y1 = jnp.clip(input[..., 1::4], 0, h)
    x2 = jnp.clip(input[..., 2::4], 0, w)
    y2 = jnp.clip(input[..., 3::4], 0, h)
    out = jnp.stack([x1, y1, x2, y2], -1)
    return out.reshape(input.shape)


@defop
def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """reference roi_align_op.cc: bilinear ROI pooling, differentiable.
    x: [N,C,H,W]; boxes: [R,4] (x1,y1,x2,y2) on image scale; boxes_num:
    rois per batch image (None => all on image 0)."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    n, c, H, W = x.shape
    r = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((r,), jnp.int32)
    else:
        batch_idx = jnp.repeat(jnp.arange(len(boxes_num)),
                               jnp.asarray(boxes_num),
                               total_repeat_length=r).astype(jnp.int32)
    off = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - off
    y1 = boxes[:, 1] * spatial_scale - off
    x2 = boxes[:, 2] * spatial_scale - off
    y2 = boxes[:, 3] * spatial_scale - off
    rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
    sr = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: [R, oh*sr, ow*sr]
    ys = y1[:, None] + rh[:, None] * (jnp.arange(oh * sr) + 0.5) / (oh * sr)
    xs = x1[:, None] + rw[:, None] * (jnp.arange(ow * sr) + 0.5) / (ow * sr)

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(yy, 0, H - 1) - y0
        wx = jnp.clip(xx, 0, W - 1) - x0
        y0i, x0i, y1i, x1i = (v.astype(jnp.int32) for v in (y0, x0, y1_, x1_))
        g = lambda yi, xi: img[:, yi, xi]  # noqa: E731  [C, ...]
        return (g(y0i, x0i) * (1 - wy) * (1 - wx) + g(y0i, x1i) * (1 - wy) * wx
                + g(y1i, x0i) * wy * (1 - wx) + g(y1i, x1i) * wy * wx)

    def per_roi(bi, yy, xx):
        img = x[bi]                                   # [C,H,W]
        grid_y = jnp.repeat(yy, ow * sr)              # [(oh*sr)*(ow*sr)]
        grid_x = jnp.tile(xx, oh * sr)
        vals = bilinear(img, grid_y, grid_x)          # [C, ohsr*owsr]
        vals = vals.reshape(c, oh, sr, ow, sr)
        return vals.mean(axis=(2, 4))                 # [C, oh, ow]

    return jax.vmap(per_roi)(batch_idx, ys, xs)


@defop
def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0):
    """reference roi_pool_op.cc: max pooling over quantized ROI bins."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    n, c, H, W = x.shape
    r = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((r,), jnp.int32)
    else:
        batch_idx = jnp.repeat(jnp.arange(len(boxes_num)),
                               jnp.asarray(boxes_num),
                               total_repeat_length=r).astype(jnp.int32)
    x1 = jnp.round(boxes[:, 0] * spatial_scale).astype(jnp.int32)
    y1 = jnp.round(boxes[:, 1] * spatial_scale).astype(jnp.int32)
    x2 = jnp.round(boxes[:, 2] * spatial_scale).astype(jnp.int32)
    y2 = jnp.round(boxes[:, 3] * spatial_scale).astype(jnp.int32)

    SR = 8  # fixed sample lattice per bin (static shapes; max over samples)

    def per_roi(bi, xx1, yy1, xx2, yy2):
        img = x[bi]
        rh = jnp.maximum(yy2 - yy1 + 1, 1)
        rw = jnp.maximum(xx2 - xx1 + 1, 1)
        ys = yy1 + (jnp.arange(oh * SR) * rh) // (oh * SR)
        xs = xx1 + (jnp.arange(ow * SR) * rw) // (ow * SR)
        ys = jnp.clip(ys, 0, H - 1)
        xs = jnp.clip(xs, 0, W - 1)
        grid_y = jnp.repeat(ys, ow * SR)
        grid_x = jnp.tile(xs, oh * SR)
        vals = img[:, grid_y, grid_x].reshape(c, oh, SR, ow, SR)
        return vals.max(axis=(2, 4))

    return jax.vmap(per_roi)(batch_idx, x1, y1, x2, y2)


# -- host-side selection kernels (eager; reference ships CPU-only too) ------

def _nms_np(boxes, scores, threshold, top_k=-1):
    order = np.argsort(-scores)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    areas = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        if top_k > 0 and len(keep) >= top_k:
            break
        xx1 = np.maximum(boxes[i, 0], boxes[:, 0])
        yy1 = np.maximum(boxes[i, 1], boxes[:, 1])
        xx2 = np.minimum(boxes[i, 2], boxes[:, 2])
        yy2 = np.minimum(boxes[i, 3], boxes[:, 3])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= iou > threshold
    return np.asarray(keep, np.int64)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """reference nms_op / multiclass path. Eager host kernel (dynamic
    output size, like the reference's CPU-only kernel)."""
    from ..core.tensor import Tensor
    b = np.asarray(boxes._value if isinstance(boxes, Tensor) else boxes)
    s = np.asarray(scores._value if isinstance(scores, Tensor) else scores) \
        if scores is not None else np.arange(len(b), 0, -1, dtype=np.float32)
    if category_idxs is not None:
        cats = np.asarray(category_idxs._value
                          if isinstance(category_idxs, Tensor)
                          else category_idxs)
        keep_all = []
        for cval in (categories if categories is not None
                     else np.unique(cats)):
            idx = np.nonzero(cats == cval)[0]
            kept = _nms_np(b[idx], s[idx], iou_threshold)
            keep_all.append(idx[kept])
        keep = np.concatenate(keep_all) if keep_all else np.zeros(0, np.int64)
        keep = keep[np.argsort(-s[keep])]
    else:
        keep = _nms_np(b, s, iou_threshold)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep), _internal=True)


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   background_label=0):
    """reference multiclass_nms_op.cc: per-class NMS then global keep_top_k.
    bboxes [N, M, 4]; scores [N, C, M]. Returns (out [K, 6], rois_num)."""
    from ..core.tensor import Tensor
    b = np.asarray(bboxes._value if isinstance(bboxes, Tensor) else bboxes)
    s = np.asarray(scores._value if isinstance(scores, Tensor) else scores)
    outs, nums = [], []
    for n in range(b.shape[0]):
        dets = []
        for c in range(s.shape[1]):
            if c == background_label:
                continue
            sc = s[n, c]
            m = sc > score_threshold
            if not m.any():
                continue
            idx = np.nonzero(m)[0]
            order = idx[np.argsort(-sc[idx])][:nms_top_k]
            kept = _nms_np(b[n][order], sc[order], nms_threshold)
            for i in order[kept]:
                dets.append([c, sc[i], *b[n, i]])
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        outs.extend(dets)
        nums.append(len(dets))
    out = np.asarray(outs, np.float32).reshape(-1, 6)
    return (Tensor(jnp.asarray(out), _internal=True),
            Tensor(jnp.asarray(np.asarray(nums, np.int32)), _internal=True))


def bipartite_match(dist_mat):
    """reference bipartite_match_op.cc greedy bipartite matching:
    repeatedly take the global max entry, match that (row, col) pair.
    Returns (match_indices [M], match_dist [M]) over columns."""
    from ..core.tensor import Tensor
    d = np.array(np.asarray(dist_mat._value
                            if isinstance(dist_mat, Tensor) else dist_mat),
                 copy=True)
    n, m = d.shape
    match_idx = np.full(m, -1, np.int64)
    match_dist = np.zeros(m, np.float32)
    used_rows = np.zeros(n, bool)
    used_cols = np.zeros(m, bool)
    for _ in range(min(n, m)):
        i, j = np.unravel_index(np.argmax(d), d.shape)
        if d[i, j] <= 0:
            break
        match_idx[j] = i
        match_dist[j] = d[i, j]
        used_rows[i] = True
        used_cols[j] = True
        d[i, :] = -1
        d[:, j] = -1
    return (Tensor(jnp.asarray(match_idx), _internal=True),
            Tensor(jnp.asarray(match_dist), _internal=True))
