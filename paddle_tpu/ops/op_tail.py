"""Long-tail op families closing the registry audit residue
(tools/op_coverage.py; VERDICT r04 item 3).

Each op cites its reference registration. TPU-first design notes: the
beam-search pair is batched-dense (fixed [batch, beam] lanes lowered onto
top_k/one_hot — no LoD, XLA-friendly) instead of the reference's
LoD-walking CPU kernel (beam_search_op.cc); segment reductions lower to
jax.ops.segment_*; the rest are direct jnp lowering rules.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as _rng
from ..core.dtype import to_jax_dtype
from ._dispatch import defop, unwrap, wrap

__all__ = [
    "spectral_norm", "beam_search", "beam_search_decode",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "truncated_normal", "spp", "sampling_id", "dequantize_log",
    "positive_negative_pair", "print_op", "assert_op",
]


def print_op(x, message="", summarize=20, first_n=-1):
    """reference print_op.cc: print tensor values as a pass-through.
    Eager prints immediately (honoring first_n); under a trace it lowers
    to jax.debug.print, which fires at run time — summarize/first_n are
    trace-time unknowable there and are ignored (noted divergence)."""
    v = unwrap(x)
    if isinstance(v, jax.core.Tracer):
        # message goes through as data, never as a format string
        jax.debug.print("{m} {x}", m=message, x=v)
        return x
    if first_n and first_n > 0:
        seen = getattr(print_op, "_counts", None)
        if seen is None:
            seen = print_op._counts = {}
        seen[message] = seen.get(message, 0) + 1
        if seen[message] > first_n:
            return x
    flat = np.asarray(v).reshape(-1)
    head = flat[:summarize] if summarize and summarize > 0 else flat
    print(f"{message} shape={tuple(np.shape(v))} "
          f"dtype={np.asarray(v).dtype} values={head.tolist()}")
    return x


def assert_op(cond, data=None, summarize=20):
    """reference assert_op.cc: abort when cond is false. Eager raises;
    under a trace it lowers to jax.debug.check-style callback (XLA has no
    abort: the check fires when the value lands on the host)."""
    c = unwrap(cond)
    if isinstance(c, jax.core.Tracer):
        def _check(val):
            if not bool(np.asarray(val).all()):
                raise AssertionError(
                    f"Assert failed (traced): {data if data is not None else ''}")
        jax.debug.callback(_check, c)
        return cond
    if not bool(np.asarray(c).all()):
        extra = ""
        if data is not None:
            items = data if isinstance(data, (list, tuple)) else [data]
            extra = "; data=" + ", ".join(
                str(np.asarray(unwrap(d)).reshape(-1)[:summarize].tolist())
                for d in items)
        raise AssertionError("Assert failed" + extra)
    return cond


@defop
def spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12):
    """reference spectral_norm_op.cc (fluid/layers/nn.py spectral_norm):
    sigma-normalized weight via power iteration on the given u/v seed
    vectors. Returns the normalized weight (the reference op's Out)."""
    w = jnp.moveaxis(weight, dim, 0)
    h = w.shape[0]
    mat = w.reshape(h, -1)
    for _ in range(max(int(power_iters), 0)):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ mat @ v
    out = mat / (sigma + eps)
    return jnp.moveaxis(out.reshape(w.shape), 0, dim)


@defop(version=2)
def beam_search(pre_ids, pre_scores, scores, beam_size, end_id,
                is_accumulated=True):
    """reference beam_search_op.cc, batched-dense: one step of beam
    expansion. pre_ids/pre_scores [B, K]; scores [B, K, V] — accumulated
    log-probs when is_accumulated, else NORMALIZED probabilities of the
    candidate step (the reference contract: beam_search_op.cc applies
    std::log to them before adding pre_scores). Returns
    (selected_ids [B, K], selected_scores [B, K], parent_idx [B, K]).
    Finished lanes (pre_id == end_id) emit end_id with their score frozen,
    matching the reference's finished-branch handling.

    version 2: is_accumulated=False now applies jnp.log per that
    contract (v1 wrongly re-normalized via log_softmax, treating the
    probabilities as logits); the bump makes program_serde refuse
    replaying v2 artifacts on v1 builds."""
    b, k, vsz = scores.shape
    if not is_accumulated:
        scores = pre_scores[:, :, None] + jnp.log(scores)
    finished = (pre_ids == end_id)
    # a finished lane contributes exactly one candidate: end_id at its
    # frozen score; mask the rest of its row to -inf
    is_end = (jnp.arange(vsz) == end_id)
    frozen = jnp.where(is_end, pre_scores[:, :, None],
                       jnp.full_like(scores, -jnp.inf))
    total = jnp.where(finished[:, :, None], frozen, scores)
    flat = total.reshape(b, k * vsz)
    top_scores, top_idx = jax.lax.top_k(flat, k)
    parent = (top_idx // vsz).astype(jnp.int32)
    ids = (top_idx % vsz).astype(pre_ids.dtype)
    return ids, top_scores, parent


@defop
def beam_search_decode(step_ids, step_parents, end_id):
    """reference beam_search_decode_op.cc, batched-dense: backtrack the
    per-step (ids, parents) trellis [T, B, K] into full sequences
    [B, K, T] plus the final-beam scores ordering (identity here — lanes
    are already sorted per step by beam_search)."""
    ids = jnp.asarray(step_ids)
    parents = jnp.asarray(step_parents)
    t = ids.shape[0]

    def back(carry, xs):
        lane = carry                     # [B, K] lane index at step s+1
        step_id, step_par = xs
        tok = jnp.take_along_axis(step_id, lane, axis=1)
        lane = jnp.take_along_axis(step_par, lane, axis=1).astype(jnp.int32)
        return lane, tok

    b, k = ids.shape[1], ids.shape[2]
    init = jnp.tile(jnp.arange(k, dtype=jnp.int32)[None, :], (b, 1))
    _, toks = jax.lax.scan(back, init, (ids[::-1], parents[::-1]))
    seqs = jnp.transpose(toks[::-1], (1, 2, 0))      # [B, K, T]
    return seqs


def _segment(op_name, data, segment_ids, num_segments=None):
    data = unwrap(data)
    seg = unwrap(segment_ids).astype(jnp.int32)
    if num_segments is None:
        if isinstance(seg, jax.core.Tracer):
            raise ValueError(
                f"segment_{op_name}: num_segments must be passed "
                "explicitly under jit/to_static (the output shape cannot "
                "depend on traced ids)")
        num_segments = int(jnp.max(seg)) + 1 if seg.size else 0
    fns = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}
    if op_name == "mean":
        s = jax.ops.segment_sum(data, seg, num_segments)
        cnt = jax.ops.segment_sum(jnp.ones_like(seg, data.dtype), seg,
                                  num_segments)
        shape = (num_segments,) + (1,) * (data.ndim - 1)
        return wrap(s / jnp.maximum(cnt, 1).reshape(shape))
    return wrap(fns[op_name](data, seg, num_segments))


def segment_sum(data, segment_ids, num_segments=None):
    """reference segment_pool_op.cc SUM (paddle.incubate.segment_sum)."""
    return _segment("sum", data, segment_ids, num_segments)


def segment_mean(data, segment_ids, num_segments=None):
    return _segment("mean", data, segment_ids, num_segments)


def segment_max(data, segment_ids, num_segments=None):
    return _segment("max", data, segment_ids, num_segments)


def segment_min(data, segment_ids, num_segments=None):
    return _segment("min", data, segment_ids, num_segments)


def truncated_normal(shape, mean=0.0, std=1.0, dtype="float32"):
    """reference truncated_gaussian_random_op.cc: N(mean, std) clipped to
    two standard deviations by resampling (here: jax's inverse-CDF
    truncated sampler — same distribution, no rejection loop)."""
    key = _rng.next_key()
    x = jax.random.truncated_normal(key, -2.0, 2.0, tuple(shape),
                                    to_jax_dtype(dtype))
    return wrap(x * std + mean)


def spp(x, pyramid_height=3, pool_type="max"):
    """reference spp_op.cc (spatial pyramid pooling, He et al.): concat of
    adaptive poolings at 1x1, 2x2, ... 2^(h-1) bins, flattened per image."""
    from ..nn import functional as F
    pool = (F.adaptive_max_pool2d if pool_type == "max"
            else F.adaptive_avg_pool2d)
    outs = []
    n = x.shape[0]
    for level in range(int(pyramid_height)):
        bins = 2 ** level
        p = pool(x, output_size=(bins, bins))
        outs.append(p.reshape([n, -1]))
    from . import concat
    return concat(outs, axis=1)


def sampling_id(x, min=0.0, max=1.0, seed=0):  # noqa: A002
    """reference sampling_id_op.cc: draw r ~ U[min, max) per row and pick
    the first index where cumsum(p) crosses r — the reference's inverse-
    CDF walk, vectorized (keeps its behavior for unnormalized rows and
    non-default ranges, unlike a categorical() resample)."""
    key = jax.random.PRNGKey(seed) if seed else _rng.next_key()
    p = unwrap(x)
    r = jax.random.uniform(key, p.shape[:-1], minval=min, maxval=max,
                           dtype=p.dtype)
    c = jnp.cumsum(p, axis=-1)
    idx = jnp.sum(c < r[..., None], axis=-1)
    return wrap(jnp.clip(idx, 0, p.shape[-1] - 1).astype(jnp.int64))


@defop
def dequantize_log(x, dict_table):
    """reference dequantize_log_op.cc: int8 -> float through a 128-entry
    log-scale lookup table; negative codes mirror with sign."""
    xi = x.astype(jnp.int32)
    code = jnp.where(xi < 0, xi + 128, xi)
    val = jnp.take(dict_table, code)
    return jnp.where(xi < 0, -val, val)


@defop
def positive_negative_pair(score, label, query_ids):
    """reference positive_negative_pair_op.cc: within each query, count
    pairs ranked concordantly (positive), discordantly (negative), and
    ties (neutral) between predicted scores and labels."""
    s = score.reshape(-1)
    y = label.reshape(-1).astype(jnp.float32)
    q = query_ids.reshape(-1)
    same_q = (q[:, None] == q[None, :])
    upper = jnp.triu(jnp.ones_like(same_q, dtype=bool), k=1)
    valid = same_q & upper & (y[:, None] != y[None, :])
    ds = s[:, None] - s[None, :]
    dy = y[:, None] - y[None, :]
    pos = jnp.sum((valid & (ds * dy > 0)).astype(jnp.float32))
    neg = jnp.sum((valid & (ds * dy < 0)).astype(jnp.float32))
    neu = jnp.sum((valid & (ds == 0)).astype(jnp.float32))
    return pos, neg, neu
