"""Normalization + dropout + embedding functional ops.

Parity targets: reference operators/batch_norm_op.cc (+ sync_batch_norm_op.cu),
layer_norm_op.cc, instance_norm_op.cc, group_norm_op.cc, dropout_op.cc,
lookup_table_v2_op.cc.

batch_norm is functional: running stats go in and come out as values; the
nn.BatchNorm layer threads them through its buffers so the same op works in
eager mode and inside a jitted/partitioned train step. sync_batch_norm's
cross-device moment reduction (reference sync_batch_norm_op.cu) maps to a
`psum` over the data-parallel mesh axis when inside shard_map.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._dispatch import defop
from ..core import rng as _rng


@defop
def layer_norm(x, weight=None, bias=None, epsilon=1e-05, begin_norm_axis=-1):
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim)) \
        if begin_norm_axis != -1 else (x.ndim - 1,)
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@defop
def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", sync_axis=None):
    """Returns (out, new_running_mean, new_running_var)."""
    c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = -1

    if training:
        mean = jnp.mean(x, axis=reduce_axes)
        mean_sq = jnp.mean(jnp.square(x), axis=reduce_axes)
        if sync_axis is not None:
            # sync_batch_norm: average moments over the DP mesh axis
            mean = jax.lax.pmean(mean, sync_axis)
            mean_sq = jax.lax.pmean(mean_sq, sync_axis)
        var = mean_sq - jnp.square(mean)
        new_rm = momentum * running_mean + (1 - momentum) * mean
        new_rv = momentum * running_var + (1 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var

    out = (x - jnp.reshape(mean, bshape)) * jax.lax.rsqrt(
        jnp.reshape(var, bshape) + epsilon)
    if weight is not None:
        out = out * jnp.reshape(weight, bshape)
    if bias is not None:
        out = out + jnp.reshape(bias, bshape)
    return out, new_rm, new_rv


@defop
def instance_norm(x, weight=None, bias=None, epsilon=1e-05):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = out * jnp.reshape(weight, shape)
    if bias is not None:
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = out + jnp.reshape(bias, shape)
    return out


@defop
def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-05,
               data_format="NCHW"):
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xg = jnp.reshape(x, (n, num_groups, c // num_groups) + spatial)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = jnp.reshape((xg - mean) * jax.lax.rsqrt(var + epsilon), x.shape)
    if weight is not None:
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = out * jnp.reshape(weight, shape)
    if bias is not None:
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = out + jnp.reshape(bias, shape)
    return out


@defop
def rms_norm(x, weight=None, epsilon=1e-06):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    out = x * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    return out


def _keep_mask(key, keep, shape):
    """Bernoulli(keep) mask via the TPU hardware bit generator.

    The per-call key still comes from the threefry chain (statistically
    independent across calls); only the BULK bit generation is re-seated on
    an unsafe_rbg key so XLA lowers it to RngBitGenerator — a hardware
    instruction — instead of a threefry hash per element, and the comparison
    is uint32-vs-uint32 so no (x64-widened) float uniforms are materialized.
    ~4x faster than jax.random.bernoulli on v5e at BERT-base mask volumes."""
    kd = jax.random.key_data(key).astype(jnp.uint32).ravel()
    words = jnp.concatenate([kd, kd ^ jnp.uint32(0x9E3779B9)])[:4]
    rbg_key = jax.random.wrap_key_data(words, impl="unsafe_rbg")
    thresh = jnp.uint32(int(keep * 0xFFFFFFFF))
    return jax.random.bits(rbg_key, shape, jnp.uint32) < thresh


@defop(name="dropout_op")
def _dropout(x, p, mode):
    # the key is drawn INSIDE the kernel so that recorded static Programs
    # and jitted steps split it from the per-run chain (core/rng.py) rather
    # than baking one mask at record time
    keep = 1.0 - p
    if keep <= 0.0:  # p=1: drop everything (valid per reference dropout_op)
        return jnp.zeros_like(x)
    key = _rng.next_key()
    mask = _keep_mask(key, keep, x.shape)
    if mode == "upscale_in_train":
        scale = jnp.asarray(1.0 / keep, x.dtype)
        return jnp.where(mask, x * scale, jnp.zeros((), x.dtype))
    return jnp.where(mask, x, jnp.zeros((), x.dtype))


def dropout(x, p=0.5, training=True, mode="upscale_in_train", axis=None):
    if not training or p == 0.0:
        return x
    return _dropout(x, p=float(p), mode=mode)


@defop
def embedding(weight, ids, padding_idx=None, sparse=False):
    # reference: operators/lookup_table_v2_op.cc. In jitted steps the dense
    # gather is the right form (XLA fuses the scatter-add transpose); in
    # EAGER mode sparse=True emits SelectedRows grads so huge-vocab tables
    # never materialize dense gradients (core/selected_rows.py).
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        if padding_idx < 0:  # paddle normalizes negative indices
            padding_idx = weight.shape[0] + padding_idx
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return out


def _sparse_embedding(weight_t, ids_t, padding_idx):
    """Eager-only sparse-grad embedding: custom tape Node whose backward
    emits SelectedRows for the table (the lookup_table_v2 grad kernel's
    SelectedRows output, made a tape citizen)."""
    from ..core.selected_rows import SelectedRows
    from ..core.tape import Node, _wrap_outputs
    from ..core.tensor import Tensor

    weight = weight_t._value
    ids = ids_t._value if isinstance(ids_t, Tensor) else jnp.asarray(ids_t)
    pidx = padding_idx
    if pidx is not None and pidx < 0:
        pidx = weight.shape[0] + pidx
    out = jnp.take(weight, ids, axis=0)
    if pidx is not None:
        out = out * (ids != pidx)[..., None].astype(out.dtype)

    def vjp_fn(g):
        rows = ids.reshape(-1)
        vals = g.reshape(-1, weight.shape[-1]).astype(weight.dtype)
        if pidx is not None:
            keep = (rows != pidx)[:, None].astype(vals.dtype)
            vals = vals * keep
        return (SelectedRows(rows, vals, weight.shape),)

    node = Node(vjp_fn, [weight_t], [(tuple(out.shape), out.dtype)],
                "embedding_sparse_grad", False)
    return _wrap_outputs(out, node=node, stop_gradient=False)


@defop
def local_response_norm(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
    sq = jnp.square(x)
    c = x.shape[1]
    half = size // 2
    padded = jnp.pad(sq, [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (x.ndim - 2))
    acc = jnp.zeros_like(x)
    for i in range(size):
        acc = acc + padded[:, i:i + c]
    return x / jnp.power(k + alpha * acc, beta)


# -- round-4 widening ------------------------------------------------------

@defop
def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4):
    """reference data_norm_op.cc (CTR models): normalize by accumulated
    batch statistics; means = batch_sum/batch_size, scales =
    sqrt(batch_size / batch_square_sum_centered)."""
    means = batch_sum / batch_size
    var = batch_square_sum / batch_size - jnp.square(means)
    scales = 1.0 / jnp.sqrt(var + epsilon)
    return (x - means) * scales


@defop
def l2_normalize(x, axis=-1, epsilon=1e-12):
    """reference norm_op.cc (l2 normalize along axis)."""
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    return x / jnp.maximum(n, epsilon)


def lrn(x, n=5, k=1.0, alpha=1e-4, beta=0.75, data_format="NCHW"):
    """reference lrn_op.cc — v1 name for local_response_norm (NCHW)."""
    return local_response_norm(x, size=n, alpha=alpha, beta=beta, k=k)
