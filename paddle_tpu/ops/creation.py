"""Tensor creation ops.

Parity targets: reference operators/fill_constant_op.cc, uniform_random_op.cc,
gaussian_random_op.cc, range_op.cc, linspace_op.cc, eye_op.cc,
fill_any_like_op.cc, randint / randperm / bernoulli / multinomial ops and
python/paddle/tensor/creation.py. Random ops draw from the global functional
PRNG chain (core/rng.py) instead of per-device curand states.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._dispatch import defop, unwrap, wrap
from ..core import rng as _rng
from ..core.dtype import to_jax_dtype
from ..core.tensor import Tensor


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(unwrap(s)) if not isinstance(s, int) else s for s in shape)


def zeros(shape, dtype="float32"):
    return wrap(jnp.zeros(_shape(shape), to_jax_dtype(dtype)))


def ones(shape, dtype="float32"):
    return wrap(jnp.ones(_shape(shape), to_jax_dtype(dtype)))


def full(shape, fill_value, dtype="float32"):
    return wrap(jnp.full(_shape(shape), unwrap(fill_value), to_jax_dtype(dtype)))


@defop
def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=to_jax_dtype(dtype))


@defop
def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=to_jax_dtype(dtype))


@defop
def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=to_jax_dtype(dtype))


def arange(start=0, end=None, step=1, dtype=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    if end is None:
        start, end = 0, start
    return wrap(jnp.arange(start, end, step, dtype=to_jax_dtype(dtype)))


def linspace(start, stop, num, dtype=None):
    return wrap(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                             dtype=to_jax_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return wrap(jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                             base=base, dtype=to_jax_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype="float32"):
    return wrap(jnp.eye(num_rows, num_columns, dtype=to_jax_dtype(dtype)))


def empty(shape, dtype="float32"):
    return zeros(shape, dtype)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype=dtype)


def diag(x, offset=0, padding_value=0):
    v = unwrap(x)
    if v.ndim == 1 and padding_value != 0:
        n = v.shape[0] + abs(offset)
        out = jnp.full((n, n), padding_value, v.dtype)
        return wrap(out + jnp.diag(v, offset)
                    - jnp.diag(jnp.full(v.shape, padding_value, v.dtype), offset))
    return wrap(jnp.diag(v, offset))


def diagflat(x, offset=0):
    return wrap(jnp.diagflat(unwrap(x), offset))


def tril(x, diagonal=0):
    from .manipulation import _tril
    return _tril(x, diagonal=diagonal)


def triu(x, diagonal=0):
    from .manipulation import _triu
    return _triu(x, diagonal=diagonal)


def meshgrid(*args):
    arrs = [unwrap(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return tuple(wrap(m) for m in jnp.meshgrid(*arrs, indexing="ij"))


# -- random -----------------------------------------------------------------

def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0):  # noqa: A002
    key = jax.random.PRNGKey(seed) if seed else _rng.next_key()
    return wrap(jax.random.uniform(key, _shape(shape), to_jax_dtype(dtype),
                                   minval=unwrap(min), maxval=unwrap(max)))


def rand(shape, dtype="float32"):
    return uniform(shape, dtype, 0.0, 1.0)


def normal(mean=0.0, std=1.0, shape=None):
    if shape is None:
        shape = ()
    key = _rng.next_key()
    return wrap(jax.random.normal(key, _shape(shape)) * unwrap(std) + unwrap(mean))


def randn(shape, dtype="float32"):
    key = _rng.next_key()
    return wrap(jax.random.normal(key, _shape(shape), to_jax_dtype(dtype)))


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    key = _rng.next_key()
    return wrap(jax.random.randint(key, _shape(shape), low, high,
                                   to_jax_dtype(dtype)))


def randperm(n, dtype="int64"):
    key = _rng.next_key()
    return wrap(jax.random.permutation(key, n).astype(to_jax_dtype(dtype)))


def bernoulli(x):
    key = _rng.next_key()
    v = unwrap(x)
    return wrap(jax.random.bernoulli(key, v).astype(v.dtype))


def poisson(x):
    key = _rng.next_key()
    v = unwrap(x)
    return wrap(jax.random.poisson(key, v).astype(v.dtype))


def multinomial(x, num_samples=1, replacement=False):
    key = _rng.next_key()
    v = unwrap(x)
    logits = jnp.log(jnp.maximum(v, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(num_samples,) + v.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, v.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return wrap(out.astype(jnp.int64))


def standard_normal(shape, dtype="float32"):
    return randn(shape, dtype)
