"""Convolution / pooling / vision ops.

Parity targets: reference operators/conv_op.cc + conv_cudnn_op.cu,
conv_transpose_op.cc, pool_op.cc, interpolate_v2_op.cc, pixel_shuffle,
grid_sampler, unfold. Convs are lowered to `lax.conv_general_dilated`,
which XLA tiles onto the MXU directly (the analog of the reference's
cuDNN algo search, operators/conv_cudnn_op.cu).
Layouts: paddle default is NCHW; we pass the layout straight to XLA and let
layout assignment pick the TPU-native tiling rather than transposing by hand.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ._dispatch import defop


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _conv_padding(padding, k, stride, dilation, nd):
    """paddle padding spec -> lax padding list."""
    if isinstance(padding, str):
        p = padding.upper()
        return p  # 'SAME' / 'VALID'
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * nd:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(nd)]
    raise ValueError(f"bad padding {padding}")


@defop
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    nd = 2
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    pad = _conv_padding(padding, None, stride, dilation, nd)
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC"))
    out = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16 else None)
    if x.dtype == jnp.bfloat16:
        out = out.astype(jnp.bfloat16)
    if bias is not None:
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + jnp.reshape(bias, bshape)
    return out


@defop
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    stride = _pair(stride, 1)
    dilation = _pair(dilation, 1)
    pad = _conv_padding(padding, None, stride, dilation, 1)
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "HIO", "NHC"))
    out = lax.conv_general_dilated(x, weight, stride, pad, rhs_dilation=dilation,
                                   dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        bshape = (1, -1, 1) if data_format == "NCL" else (1, 1, -1)
        out = out + jnp.reshape(bias, bshape)
    return out


@defop
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    pad = _conv_padding(padding, None, stride, dilation, 3)
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    ("NCDHW", "OIDHW", "NCDHW"))
    out = lax.conv_general_dilated(x, weight, stride, pad, rhs_dilation=dilation,
                                   dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        out = out + jnp.reshape(bias, (1, -1, 1, 1, 1))
    return out


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, nd):
    """Transposed conv as an input-dilated forward conv (reference
    operators/conv_transpose_op.cc — which runs a col2im GEMM; XLA's
    conv_general_dilated with lhs_dilation compiles to the same MXU
    convolution). Weight layout is paddle's IO<spatial>; spatial dims are
    flipped and I/O swapped (per group)."""
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    opad = _pair(output_padding, nd)
    if isinstance(padding, str):
        raise NotImplementedError("string padding for conv_transpose")
    pad = _conv_padding(padding, None, stride, dilation, nd)
    k = weight.shape[2:]
    lax_pad = [(dilation[i] * (k[i] - 1) - pad[i][0],
                dilation[i] * (k[i] - 1) - pad[i][1] + opad[i])
               for i in range(nd)]
    spatial = tuple(range(2, 2 + nd))
    if groups > 1:
        ci_g = weight.shape[0] // groups
        co_g = weight.shape[1]
        w = jnp.reshape(jnp.swapaxes(jnp.reshape(
            weight, (groups, ci_g, co_g) + k), 1, 2), (groups * co_g, ci_g) + k)
        w = jnp.flip(w, axis=spatial)
    else:
        w = jnp.swapaxes(jnp.flip(weight, axis=spatial), 0, 1)
    sp = "DHW"[3 - nd:]
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape, (f"NC{sp}", f"OI{sp}", f"NC{sp}"))
    out = lax.conv_general_dilated(x, w, window_strides=(1,) * nd,
                                   padding=lax_pad, lhs_dilation=stride,
                                   rhs_dilation=dilation,
                                   dimension_numbers=dn,
                                   feature_group_count=groups)
    if bias is not None:
        out = out + jnp.reshape(bias, (1, -1) + (1,) * nd)
    return out


@defop
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCL"):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, nd=1)


@defop
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW"):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, nd=2)


@defop
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW"):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, nd=3)



def _ceil_adjust(pads, shape, window, strides, ceil_mode):
    """Extend high-side padding so floor-division matches paddle ceil_mode."""
    if not ceil_mode:
        return pads
    if isinstance(pads, str):
        raise NotImplementedError("ceil_mode with string padding")
    out = []
    for d, (lo, hi) in enumerate(pads):
        L, k, s = shape[d], window[d], strides[d]
        eff = L + lo + hi
        out_ceil = -((eff - k) // -s) + 1
        extra = (out_ceil - 1) * s + k - eff
        out.append((lo, hi + max(extra, 0)))
    return out


@defop
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCHW"):
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    pad = _conv_padding(padding, k, s, (1, 1), 2)
    if data_format == "NCHW":
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = [(0, 0), (0, 0)] + (pad if isinstance(pad, list) else pad)
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = [(0, 0)] + (pad if isinstance(pad, list) else pad) + [(0, 0)]
    if isinstance(pad, str):
        pads = pad
    pads = _ceil_adjust(pads, x.shape, window, strides, ceil_mode)
    # -inf init is required for XLA's reduce_window_max autodiff rule
    neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, neg, lax.max, window, strides, pads)


@defop
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCHW"):
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    pad = _conv_padding(padding, k, s, (1, 1), 2)
    if data_format == "NCHW":
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = [(0, 0), (0, 0)] + (pad if isinstance(pad, list) else pad)
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = [(0, 0)] + (pad if isinstance(pad, list) else pad) + [(0, 0)]
    if isinstance(pad, str):
        pads = pad
    pads = _ceil_adjust(pads, x.shape, window, strides, ceil_mode)
    summed = lax.reduce_window(x, jnp.array(0, x.dtype), lax.add, window,
                               strides, pads)
    if exclusive and not isinstance(pads, str):
        counts = lax.reduce_window(jnp.ones_like(x), jnp.array(0, x.dtype),
                                   lax.add, window, strides, pads)
        return summed / counts
    import numpy as np
    return summed / np.prod(k)


@defop
def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False):
    k = _pair(kernel_size, 1)
    s = _pair(stride, 1) if stride is not None else k
    pad = _conv_padding(padding, k, s, (1,), 1)
    neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    pads = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + pad
    pads = _ceil_adjust(pads, x.shape, (1, 1) + k, (1, 1) + s, ceil_mode)
    return lax.reduce_window(x, neg, lax.max, (1, 1) + k, (1, 1) + s, pads)


@defop
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    out_hw = _pair(output_size)
    if data_format != "NCHW":
        raise NotImplementedError
    n, c, h, w = x.shape
    oh, ow = out_hw
    if h % oh == 0 and w % ow == 0:
        x4 = jnp.reshape(x, (n, c, oh, h // oh, ow, w // ow))
        return jnp.mean(x4, axis=(3, 5))
    # general case: integral-image style via cumulative sums
    cs = jnp.cumsum(jnp.cumsum(x, axis=2), axis=3)
    cs = jnp.pad(cs, [(0, 0), (0, 0), (1, 0), (1, 0)])
    import numpy as np
    hs = np.floor(np.arange(oh) * h / oh).astype(int)
    he = np.ceil((np.arange(oh) + 1) * h / oh).astype(int)
    ws = np.floor(np.arange(ow) * w / ow).astype(int)
    we = np.ceil((np.arange(ow) + 1) * w / ow).astype(int)
    area = (he - hs)[:, None] * (we - ws)[None, :]
    out = (cs[:, :, he][:, :, :, we] - cs[:, :, hs][:, :, :, we]
           - cs[:, :, he][:, :, :, ws] + cs[:, :, hs][:, :, :, ws])
    return out / jnp.asarray(area, x.dtype)


@defop
def adaptive_max_pool2d(x, output_size, data_format="NCHW"):
    out_hw = _pair(output_size)
    n, c, h, w = x.shape
    oh, ow = out_hw
    if h % oh or w % ow:
        raise NotImplementedError("adaptive_max_pool2d needs divisible sizes")
    x4 = jnp.reshape(x, (n, c, oh, h // oh, ow, w // ow))
    return jnp.max(x4, axis=(3, 5))


@defop
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    n, c, h, w = x.shape
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else (scale_factor,) * 2
        size = (int(h * sf[0]), int(w * sf[1]))
    size = tuple(int(s) for s in size)
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic",
              "area": "linear"}[mode]
    xt = jnp.transpose(x, (0, 2, 3, 1))
    out = jax.image.resize(xt, (n,) + size + (c,), method=method)
    return jnp.transpose(out, (0, 3, 1, 2)).astype(x.dtype)


@defop
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    n, c, h, w = x.shape
    x = jnp.reshape(x, (n, c // (r * r), r, r, h, w))
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return jnp.reshape(x, (n, c // (r * r), h * r, w * r))


@defop
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)
    n, c, h, w = x.shape
    x = jnp.pad(x, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
    oh = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
    ow = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
    patches = []
    for i in range(k[0]):
        for j in range(k[1]):
            patches.append(x[:, :, i * d[0]: i * d[0] + oh * s[0]: s[0],
                             j * d[1]: j * d[1] + ow * s[1]: s[1]])
    out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
    return jnp.reshape(out, (n, c * k[0] * k[1], oh * ow))


# ---- 3-D pooling (reference operators/pool_op.cc pool3d; VERDICT r03
# item 4). Same reduce_window formulation as the 2-D ops, one more
# spatial dim. ----------------------------------------------------------


@defop
def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCDHW"):
    k = _pair(kernel_size, 3)
    s = _pair(stride, 3) if stride is not None else k
    pad = _conv_padding(padding, k, s, (1, 1, 1), 3)
    window = (1, 1) + k
    strides = (1, 1) + s
    pads = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + pad
    pads = _ceil_adjust(pads, x.shape, window, strides, ceil_mode)
    neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, neg, lax.max, window, strides, pads)


@defop
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCDHW"):
    k = _pair(kernel_size, 3)
    s = _pair(stride, 3) if stride is not None else k
    pad = _conv_padding(padding, k, s, (1, 1, 1), 3)
    window = (1, 1) + k
    strides = (1, 1) + s
    pads = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + pad
    pads = _ceil_adjust(pads, x.shape, window, strides, ceil_mode)
    summed = lax.reduce_window(x, jnp.array(0, x.dtype), lax.add, window,
                               strides, pads)
    if exclusive and not isinstance(pads, str):
        counts = lax.reduce_window(jnp.ones_like(x), jnp.array(0, x.dtype),
                                   lax.add, window, strides, pads)
        return summed / counts
    import numpy as np
    return summed / np.prod(k)


@defop
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    out = _pair(output_size, 3)
    n, c, d, h, w = x.shape
    od, oh, ow = out
    if d % od or h % oh or w % ow:
        raise ValueError("adaptive_avg_pool3d needs divisible sizes")
    x6 = jnp.reshape(x, (n, c, od, d // od, oh, h // oh, ow, w // ow))
    return jnp.mean(x6, axis=(3, 5, 7))


@defop
def adaptive_max_pool3d(x, output_size, data_format="NCDHW"):
    out = _pair(output_size, 3)
    n, c, d, h, w = x.shape
    od, oh, ow = out
    if d % od or h % oh or w % ow:
        raise ValueError("adaptive_max_pool3d needs divisible sizes")
    x6 = jnp.reshape(x, (n, c, od, d // od, oh, h // oh, ow, w // ow))
    return jnp.max(x6, axis=(3, 5, 7))


# -- round-4 widening (reference operators/: pool_with_index_op.cc,
#    unpool_op.cc, affine_channel_op.cc, row_conv_op.cc,
#    im2sequence_op.cc, random_crop_op.cc, shuffle_batch_op.cc,
#    detection/psroi_pool_op.cc) -----------------------------------------


@defop
def max_pool2d_with_index(x, kernel_size, stride=None, padding=0,
                          ceil_mode=False):
    """Max pool returning (out, flat h*w argmax indices) — the
    return_mask=True form (reference pool_with_index_op.cc)."""
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    pad = _conv_padding(padding, k, s, (1, 1), 2)
    if isinstance(pad, str):
        raise ValueError("max_pool2d_with_index needs explicit padding")
    n, c, h, w = x.shape
    patches = lax.conv_general_dilated_patches(
        x, k, s, pad, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    oh, ow = patches.shape[2], patches.shape[3]
    patches = jnp.reshape(patches, (n, c, k[0] * k[1], oh, ow))
    out = jnp.max(patches, axis=2)
    arg = jnp.argmax(patches, axis=2).astype(jnp.int32)   # patch-local
    # convert to flat input h*w coordinates
    ky = arg // k[1]
    kx = arg % k[1]
    oy = jnp.arange(oh, dtype=jnp.int32)[:, None]
    ox = jnp.arange(ow, dtype=jnp.int32)[None, :]
    iy = oy * s[0] - pad[0][0] + ky
    ix = ox * s[1] - pad[1][0] + kx
    idx = iy * w + ix
    return out, idx


@defop
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None):
    """reference unpool_op.cc: scatter pooled values back to their argmax
    positions."""
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    n, c, oh, ow = x.shape
    if output_size is None:
        h = (oh - 1) * s[0] + k[0] - 2 * _pair(padding)[0]
        w = (ow - 1) * s[1] + k[1] - 2 * _pair(padding)[1]
    else:
        h, w = output_size[-2], output_size[-1]
    flat = jnp.zeros((n, c, h * w), x.dtype)
    out = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        jnp.reshape(indices, (n, c, -1))].set(jnp.reshape(x, (n, c, -1)))
    return jnp.reshape(out, (n, c, h, w))


@defop
def affine_channel(x, scale, bias, data_format="NCHW"):
    shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
    return x * jnp.reshape(scale, shape) + jnp.reshape(bias, shape)


@defop
def row_conv(x, weight):
    """reference row_conv_op.cc (DeepSpeech lookahead conv): x [b, t, d],
    weight [future_context+1, d]; out[t] = sum_i x[t+i] * w[i]."""
    ctx = weight.shape[0]
    outs = 0
    for i in range(ctx):
        shifted = jnp.pad(x[:, i:], [(0, 0), (0, i), (0, 0)])
        outs = outs + shifted * weight[i]
    return outs


@defop
def im2sequence(x, kernel_size, stride=1, padding=0):
    """reference im2sequence_op.cc: sliding patches flattened to
    [n*oh*ow, c*kh*kw] sequence rows."""
    k = _pair(kernel_size)
    s = _pair(stride)
    p = _pair(padding)
    n, c = x.shape[0], x.shape[1]
    cols = unfold.raw(x, k, strides=s, paddings=p)   # [n, c*kh*kw, oh*ow]
    return jnp.reshape(jnp.swapaxes(cols, 1, 2), (-1, c * k[0] * k[1]))


@defop
def psroi_pool(x, boxes, boxes_num=None, output_channels=None,
               spatial_scale=1.0, pooled_height=7, pooled_width=7):
    """reference detection/psroi_pool_op.cc: position-sensitive ROI avg
    pooling — bin (i, j) reads channel group (i*pw + j)."""
    ph, pw = int(pooled_height), int(pooled_width)
    n, c, h, w = x.shape
    oc = output_channels or c // (ph * pw)

    def one_box(b):
        img = x[0] if n == 1 else x[0]  # single-image form
        x1, y1, x2, y2 = b[0] * spatial_scale, b[1] * spatial_scale, \
            b[2] * spatial_scale, b[3] * spatial_scale
        bh = jnp.maximum(y2 - y1, 0.1) / ph
        bw = jnp.maximum(x2 - x1, 0.1) / pw
        rows = []
        for i in range(ph):
            cells = []
            for j in range(pw):
                ys = jnp.floor(y1 + i * bh).astype(jnp.int32)
                ye = jnp.ceil(y1 + (i + 1) * bh).astype(jnp.int32)
                xs = jnp.floor(x1 + j * bw).astype(jnp.int32)
                xe = jnp.ceil(x1 + (j + 1) * bw).astype(jnp.int32)
                yy = jnp.arange(h, dtype=jnp.int32)
                xx = jnp.arange(w, dtype=jnp.int32)
                m = ((yy[:, None] >= ys) & (yy[:, None] < ye)
                     & (xx[None, :] >= xs) & (xx[None, :] < xe))
                grp = img[(i * pw + j) * oc:(i * pw + j + 1) * oc]
                cnt = jnp.maximum(jnp.sum(m), 1).astype(x.dtype)
                cells.append(jnp.sum(grp * m[None], axis=(1, 2)) / cnt)
            rows.append(jnp.stack(cells, axis=-1))
        return jnp.stack(rows, axis=-2)               # [oc, ph, pw]

    return jax.vmap(one_box)(boxes)


def random_crop(x, shape, seed=0):
    """reference random_crop_op.cc — host-random offsets, static output."""
    import numpy as np

    from ..core.tensor import Tensor
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    rng = np.random.RandomState(seed)
    starts = [0] * (xv.ndim - len(shape)) + [
        int(rng.randint(0, xv.shape[xv.ndim - len(shape) + i] - s + 1))
        for i, s in enumerate(shape)]
    sizes = list(xv.shape[:xv.ndim - len(shape)]) + list(shape)
    out = lax.dynamic_slice(xv, starts, sizes)
    return Tensor(out, _internal=True)


def shuffle_batch(x, seed=0):
    """reference shuffle_batch_op.cc — host-random batch permutation."""
    import numpy as np

    from ..core.tensor import Tensor
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    perm = np.random.RandomState(seed).permutation(xv.shape[0])
    return Tensor(xv[jnp.asarray(perm)], _internal=True)


@defop
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable convolution v1/v2 (reference
    operators/deformable_conv_op.cc / deformable_conv_v1_op.cc): each
    kernel tap samples the input at a learned fractional offset
    (bilinear), v2 additionally modulates each tap by `mask`.

    x [n, ci, h, w]; offset [n, 2*dg*kh*kw, oh, ow] with (y, x) pairs per
    tap; mask [n, dg*kh*kw, oh, ow] or None; weight [co, ci/groups, kh,
    kw]. Vectorized over space — the K tap loop is static so XLA fuses
    each tap's gather+lerp into the final contraction."""
    s = _pair(stride)
    p = _pair(padding)
    d = _pair(dilation)
    n, ci, h, w = x.shape
    co, _, kh, kw = weight.shape
    oh = (h + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
    ow = (w + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
    K = kh * kw
    dg = int(deformable_groups)
    cg = ci // dg                                    # channels per dg

    off = jnp.reshape(offset.astype(jnp.float32), (n, dg, K, 2, oh, ow))
    if mask is not None:
        m = jnp.reshape(mask.astype(jnp.float32), (n, dg, K, oh, ow))

    oy = jnp.arange(oh, dtype=jnp.float32)[:, None] * s[0] - p[0]
    ox = jnp.arange(ow, dtype=jnp.float32)[None, :] * s[1] - p[1]

    def bilinear(img, py, px):
        """img [n, dg, cg, h, w]; py/px [n, dg, oh, ow] -> samples
        [n, dg, cg, oh, ow]; out-of-bounds reads 0."""
        y0 = jnp.floor(py)
        x0 = jnp.floor(px)
        wy = (py - y0)[:, :, None]
        wx = (px - x0)[:, :, None]

        def tap(yy, xx):
            inb = ((yy >= 0) & (yy < h) & (xx >= 0)
                   & (xx < w))[:, :, None].astype(img.dtype)
            cy = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            cx = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            g = jax.vmap(jax.vmap(               # over n, then dg
                lambda im, a, b: im[:, a, b]))(img, cy, cx)
            return g * inb

        v00 = tap(y0, x0)
        v01 = tap(y0, x0 + 1)
        v10 = tap(y0 + 1, x0)
        v11 = tap(y0 + 1, x0 + 1)
        wy = wy.astype(img.dtype)
        wx = wx.astype(img.dtype)
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                + v10 * wy * (1 - wx) + v11 * wy * wx)

    xg = jnp.reshape(x, (n, dg, cg, h, w))
    cols = []
    for k in range(K):
        ky, kx = k // kw, k % kw
        py = oy[None, None] + ky * d[0] + off[:, :, k, 0]   # [n, dg, oh, ow]
        px = ox[None, None] + kx * d[1] + off[:, :, k, 1]
        smp = bilinear(xg, py, px)                   # [n, dg, cg, oh, ow]
        if mask is not None:
            smp = smp * m[:, :, k][:, :, None].astype(smp.dtype)
        cols.append(smp)
    col = jnp.stack(cols, axis=3)                    # [n, dg, cg, K, oh, ow]
    col = jnp.reshape(col, (n, ci, K, oh, ow))

    gci = ci // groups
    gco = co // groups
    colg = jnp.reshape(col, (n, groups, gci, K, oh, ow))
    wg = jnp.reshape(weight, (groups, gco, gci, kh * kw))
    out = jnp.einsum("ngckhw,gock->ngohw", colg, wg,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    out = jnp.reshape(out, (n, co, oh, ow))
    if bias is not None:
        out = out + jnp.reshape(bias, (1, -1, 1, 1))
    return out


def deformable_conv(x, offset, mask, weight, bias=None, stride=1,
                    padding=0, dilation=1, deformable_groups=1, groups=1,
                    im2col_step=None):
    """reference v1 op name (mask=None) / v2 (modulated)."""
    return deform_conv2d(x, offset, weight, bias, stride, padding,
                         dilation, deformable_groups, groups, mask)
