"""Shape / indexing / rearrangement ops.

Parity targets: reference operators/reshape_op.cc, transpose_op.cc,
concat_op.cc, split_op.cc, squeeze_op.cc, unsqueeze_op.cc, stack_op.cc,
gather(_nd)_op.cc, scatter_op.cc, slice_op.cc, strided_slice_op.cc,
expand_v2_op.cc, tile_op.cc, flip_op.cc, roll_op.cc, pad3d/pad_op.cc,
top_k_v2_op.cc, argsort_op.cc, unique_op.cc, where_op.cc, index_select_op.cc,
set_value_op.cc and python/paddle/tensor/manipulation.py.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp

from ._dispatch import defop, unwrap, wrap
from ..core.tensor import Tensor


@defop
def reshape(x, shape):
    shape = tuple(int(s) for s in shape)
    return jnp.reshape(x, shape)


@defop
def transpose(x, perm=None):
    return jnp.transpose(x, axes=perm)


@defop
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@defop
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


@defop
def t(x):
    return x.T


@defop(name="concat")
def _concat(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _concat(*x, axis=axis)


@defop(name="stack")
def _stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0):
    return _stack(*x, axis=axis)


@defop(name="split_op")
def _split(x, sections, axis):
    if isinstance(sections, int):
        return tuple(jnp.split(x, sections, axis=axis))
    idx = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        idx.append(acc)
    return tuple(jnp.split(x, idx, axis=axis))


def split(x, num_or_sections, axis=0):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if isinstance(num_or_sections, (list, tuple)):
        total = x.shape[axis]
        secs = [s if isinstance(s, int) else int(unwrap(s)) for s in num_or_sections]
        known = builtins.sum(s for s in secs if s >= 0)
        secs = [s if s >= 0 else total - known for s in secs]
        return list(_split(x, secs, axis))
    return list(_split(x, int(num_or_sections), axis))


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


@defop(name="unbind_op")
def _unbind(x, axis):
    n = x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


def unbind(x, axis=0):
    return list(_unbind(x, axis))


@defop
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a for a in axis if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axis) if axis else x


@defop
def unsqueeze(x, axis):
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.expand_dims(x, tuple(axis))


@defop
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    s = start_axis % nd
    e = stop_axis % nd
    shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return jnp.reshape(x, shape)


@defop
def expand(x, shape):
    shape = tuple(int(s) for s in shape)
    # paddle semantics: -1 keeps the original dim
    full = []
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1:
            full.append(x.shape[i - offset])
        else:
            full.append(s)
    return jnp.broadcast_to(x, tuple(full))


@defop
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@defop
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, tuple(int(s) for s in shape))


@defop
def tile(x, repeat_times):
    return jnp.tile(x, tuple(int(r) for r in repeat_times))


@defop
def flip(x, axis):
    return jnp.flip(x, axis=axis)


@defop
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@defop
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


@defop
def gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@defop
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@defop
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@defop
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@defop
def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


@defop
def put_along_axis(x, indices, values, axis):
    return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)


@defop
def scatter(x, index, updates, overwrite=True):
    # reference: operators/scatter_op.cc — row-wise scatter on axis 0
    if overwrite:
        return x.at[index].set(updates)
    base = x.at[index].set(jnp.zeros_like(updates))
    return base.at[index].add(updates)


@defop
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape):
    z = wrap(jnp.zeros(tuple(int(s) for s in shape), unwrap(updates).dtype))
    return scatter_nd_add(z, index, updates)


@defop
def where(condition, x=None, y=None):
    if x is None and y is None:
        return tuple(jnp.nonzero(condition))  # data-dependent; eager only
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple=False):
    v = unwrap(x)
    nz = jnp.nonzero(v)
    if as_tuple:
        return tuple(wrap(a[:, None]) for a in nz)
    return wrap(jnp.stack(nz, axis=1))


@defop
def masked_select(x, mask):
    return x[mask]  # data-dependent shape; eager only


@defop
def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


@defop
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    nd = x.ndim
    pad = [int(p) for p in pad]
    if len(pad) == 2 * nd:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle F.pad convention: first pair pads the LAST spatial dim
        # (left,right,top,bottom,...), so reverse the pairs into dim order
        n_spatial = len(pad) // 2
        spatial = [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)]
        spatial = spatial[::-1]
        if data_format.upper().endswith("C"):  # NHWC / NLC / NDHWC
            cfg = [(0, 0)] * (nd - n_spatial - 1) + spatial + [(0, 0)]
        else:
            cfg = [(0, 0)] * (nd - n_spatial) + spatial
    if mode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, cfg, mode=jmode)


@defop(name="topk_op")
def _topk(x, k, axis, largest):
    if axis not in (-1, x.ndim - 1):
        xm = jnp.moveaxis(x, axis, -1)
    else:
        xm = x
    vals, idx = jax.lax.top_k(xm if largest else -xm, k)
    if not largest:
        vals = -vals
    if axis not in (-1, x.ndim - 1):
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(jnp.int64)


def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    if isinstance(k, Tensor):
        k = int(k.item())
    return _topk(x, k, axis, largest)


@defop
def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


@defop
def argsort(x, axis=-1, descending=False):
    idx = jnp.argsort(x, axis=axis)
    if descending:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(jnp.int64)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    v = unwrap(x)
    out = jnp.unique(v, return_index=return_index, return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if isinstance(out, tuple):
        return tuple(wrap(o) for o in out)
    return wrap(out)


@defop
def _tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@defop
def _triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@defop
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@defop
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@defop
def as_strided_slice(x, axes, starts, ends, strides):
    # builtins.slice: the module-level paddle `slice` op shadows the
    # builtin at call time
    idx = [builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(st, en, sd)
    return x[tuple(idx)]


def slice(x, axes, starts, ends):  # noqa: A001 - paddle API name
    starts = [int(unwrap(s)) for s in starts]
    ends = [int(unwrap(e)) for e in ends]
    return as_strided_slice(x, axes, starts, ends, [1] * len(axes))


def strided_slice(x, axes, starts, ends, strides):
    return as_strided_slice(x, [int(a) for a in axes], [int(unwrap(s)) for s in starts],
                            [int(unwrap(e)) for e in ends], [int(unwrap(s)) for s in strides])


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [_unwrap_index(i) for i in idx]
    if isinstance(idx, builtins.slice):
        return builtins.slice(_unwrap_index(idx.start), _unwrap_index(idx.stop),
                              _unwrap_index(idx.step))
    return idx


@defop(name="getitem")
def _getitem(x, idx):
    return x[idx]


def getitem(x, idx):
    return _getitem(x, idx=_unwrap_index(idx))


@defop(name="setitem")
def _setitem(x, v, idx):
    v = jnp.asarray(v, x.dtype) if not hasattr(v, "dtype") else v.astype(x.dtype)
    return x.at[idx].set(v)


def setitem(x, idx, value):
    # reference: operators/set_value_op.cc; functional scatter + SSA rebind
    value = value if isinstance(value, Tensor) else wrap(jnp.asarray(value))
    return _setitem(x, value, idx=_unwrap_index(idx))


@defop
def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


@defop
def tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


@defop
def searchsorted(sorted_sequence, values, right=False):
    side = "right" if right else "left"
    return jnp.searchsorted(sorted_sequence, values, side=side).astype(jnp.int64)


@defop
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


@defop
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@defop
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@defop
def crop(x, shape, offsets):
    idx = tuple(builtins.slice(o, o + s) for o, s in zip(offsets, shape))
    return x[idx]


# -- round-4 widening (reference operators/: unbind_op.cc, unstack_op.cc,
#    reverse_op.cc, strided_slice_op.cc, space_to_depth_op.cc,
#    shuffle_channel_op.cc, temporal_shift_op.cc, shard_index_op.cc,
#    unique_op.cc, where_index_op.cc [nonzero], gather_tree_op.cc,
#    pad_constant_like_op.cc, partial_concat_op.cc, partial_sum_op.cc) ----

@defop
def unbind(x, axis=0):
    n = x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis)
                 for s in jnp.split(x, n, axis=axis))


@defop
def unstack(x, axis=0, num=None):
    return unbind.raw(x, axis=axis)


@defop
def reverse(x, axis):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, axis=axis)


@defop
def space_to_depth(x, blocksize, data_format="NCHW"):
    n, c, h, w = x.shape
    b = int(blocksize)
    x = jnp.reshape(x, (n, c, h // b, b, w // b, b))
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return jnp.reshape(x, (n, c * b * b, h // b, w // b))


@defop
def shuffle_channel(x, group):
    n, c, h, w = x.shape
    g = int(group)
    x = jnp.reshape(x, (n, g, c // g, h, w))
    x = jnp.swapaxes(x, 1, 2)
    return jnp.reshape(x, (n, c, h, w))


@defop
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = jnp.reshape(x, (n, seg_num, c, h, w))
    fold = int(c * shift_ratio)
    pre = jnp.pad(x5[:, 1:, :fold], [(0, 0), (0, 1), (0, 0), (0, 0), (0, 0)])
    post = jnp.pad(x5[:, :-1, fold:2 * fold],
                   [(0, 0), (1, 0), (0, 0), (0, 0), (0, 0)])
    keep = x5[:, :, 2 * fold:]
    out = jnp.concatenate([pre, post, keep], axis=2)
    return jnp.reshape(out, (nt, c, h, w))


@defop
def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards
    hit = (x // size) == shard_id
    return jnp.where(hit, x % size, ignore_value)


def unique(x, return_index=False, return_inverse=False,
           return_counts=False, axis=None, dtype="int64"):
    """reference unique_op.cc. Output size is data-dependent → eager
    (host) op, like the reference's CPU kernel; returns Tensors."""
    import numpy as np

    from ..core.tensor import Tensor
    xv = np.asarray(x._value if isinstance(x, Tensor) else x)
    res = np.unique(xv, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        res = (res,)
    outs = tuple(Tensor(jnp.asarray(r), _internal=True) for r in res)
    return outs if len(outs) > 1 else outs[0]


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None):
    import numpy as np

    from ..core.tensor import Tensor
    xv = np.asarray(x._value if isinstance(x, Tensor) else x)
    if axis is None:
        xv = xv.reshape(-1)
        keep = np.concatenate([[True], xv[1:] != xv[:-1]])
    else:
        moved = np.moveaxis(xv, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        keep = np.concatenate([[True], (flat[1:] != flat[:-1]).any(axis=1)])
        xv = moved
    vals = xv[keep]
    if axis is not None:
        vals = np.moveaxis(vals, 0, axis)
    outs = [Tensor(jnp.asarray(vals), _internal=True)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv), _internal=True))
    if return_counts:
        idx = np.flatnonzero(keep)
        cnt = np.diff(np.append(idx, len(keep)))
        outs.append(Tensor(jnp.asarray(cnt), _internal=True))
    return tuple(outs) if len(outs) > 1 else outs[0]


def nonzero(x, as_tuple=False):
    """reference where_index_op.cc. Data-dependent size → eager."""
    import numpy as np

    from ..core.tensor import Tensor
    xv = np.asarray(x._value if isinstance(x, Tensor) else x)
    nz = np.nonzero(xv)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(n), _internal=True) for n in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)), _internal=True)


@defop
def gather_tree(ids, parents):
    """reference gather_tree_op.cc: backtrace beam-search ids
    [max_time, batch, beam] along parent pointers."""
    T = ids.shape[0]

    def step(carry, t):
        beams = carry                              # [batch, beam]
        tok = jnp.take_along_axis(ids[t], beams, axis=-1)
        par = jnp.take_along_axis(parents[t], beams, axis=-1)
        return par, tok

    init = jnp.broadcast_to(jnp.arange(ids.shape[2], dtype=parents.dtype),
                            ids.shape[1:])
    _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return jnp.flip(toks, axis=0)


@defop
def pad_constant_like(x, y, pad_value=0.0):
    pads = [(0, int(a) - int(b)) for a, b in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=pad_value)


@defop
def partial_concat(xs, start_index=0, length=-1):
    xs = [getattr(t, "_value", t) for t in xs]
    parts = []
    for t in xs:
        end = t.shape[1] if length == -1 else start_index + length
        parts.append(t[:, start_index:end])
    return jnp.concatenate(parts, axis=1)


@defop
def partial_sum(xs, start_index=0, length=-1):
    xs = [getattr(t, "_value", t) for t in xs]
    parts = []
    for t in xs:
        end = t.shape[1] if length == -1 else start_index + length
        parts.append(t[:, start_index:end])
    return sum(parts[1:], parts[0])


def pad2d(x, paddings, mode="constant", pad_value=0.0, data_format="NCHW"):
    """reference pad2d_op.cc — 4-number [top, bottom, left, right] form."""
    t, b, l, r = (int(p) for p in paddings)
    return pad(x, [l, r, t, b], mode=mode, value=pad_value,
               data_format=data_format)


def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW"):
    """reference pad3d_op.cc — [front, back, top, bottom, left, right]."""
    f, bk, t, b, l, r = (int(p) for p in paddings)
    return pad(x, [l, r, t, b, f, bk], mode=mode, value=value,
               data_format=data_format)


@defop
def set_value(x, value, item=None):
    """reference set_value_op.cc (tensor slice assignment in static
    graphs): returns x with `item` (any basic index) replaced by value;
    whole-tensor assign when item is None."""
    if item is None:
        return jnp.broadcast_to(jnp.asarray(value, x.dtype), x.shape)
    return x.at[item].set(jnp.asarray(value, x.dtype))
