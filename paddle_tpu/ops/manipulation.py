"""Shape / indexing / rearrangement ops.

Parity targets: reference operators/reshape_op.cc, transpose_op.cc,
concat_op.cc, split_op.cc, squeeze_op.cc, unsqueeze_op.cc, stack_op.cc,
gather(_nd)_op.cc, scatter_op.cc, slice_op.cc, strided_slice_op.cc,
expand_v2_op.cc, tile_op.cc, flip_op.cc, roll_op.cc, pad3d/pad_op.cc,
top_k_v2_op.cc, argsort_op.cc, unique_op.cc, where_op.cc, index_select_op.cc,
set_value_op.cc and python/paddle/tensor/manipulation.py.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp

from ._dispatch import defop, unwrap, wrap
from ..core.tensor import Tensor


@defop
def reshape(x, shape):
    shape = tuple(int(s) for s in shape)
    return jnp.reshape(x, shape)


@defop
def transpose(x, perm=None):
    return jnp.transpose(x, axes=perm)


@defop
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@defop
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


@defop
def t(x):
    return x.T


@defop(name="concat")
def _concat(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _concat(*x, axis=axis)


@defop(name="stack")
def _stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0):
    return _stack(*x, axis=axis)


@defop(name="split_op")
def _split(x, sections, axis):
    if isinstance(sections, int):
        return tuple(jnp.split(x, sections, axis=axis))
    idx = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        idx.append(acc)
    return tuple(jnp.split(x, idx, axis=axis))


def split(x, num_or_sections, axis=0):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if isinstance(num_or_sections, (list, tuple)):
        total = x.shape[axis]
        secs = [s if isinstance(s, int) else int(unwrap(s)) for s in num_or_sections]
        known = builtins.sum(s for s in secs if s >= 0)
        secs = [s if s >= 0 else total - known for s in secs]
        return list(_split(x, secs, axis))
    return list(_split(x, int(num_or_sections), axis))


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


@defop(name="unbind_op")
def _unbind(x, axis):
    n = x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


def unbind(x, axis=0):
    return list(_unbind(x, axis))


@defop
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a for a in axis if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axis) if axis else x


@defop
def unsqueeze(x, axis):
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.expand_dims(x, tuple(axis))


@defop
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    s = start_axis % nd
    e = stop_axis % nd
    shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return jnp.reshape(x, shape)


@defop
def expand(x, shape):
    shape = tuple(int(s) for s in shape)
    # paddle semantics: -1 keeps the original dim
    full = []
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1:
            full.append(x.shape[i - offset])
        else:
            full.append(s)
    return jnp.broadcast_to(x, tuple(full))


@defop
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@defop
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, tuple(int(s) for s in shape))


@defop
def tile(x, repeat_times):
    return jnp.tile(x, tuple(int(r) for r in repeat_times))


@defop
def flip(x, axis):
    return jnp.flip(x, axis=axis)


@defop
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@defop
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


@defop
def gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@defop
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@defop
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@defop
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@defop
def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


@defop
def put_along_axis(x, indices, values, axis):
    return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)


@defop
def scatter(x, index, updates, overwrite=True):
    # reference: operators/scatter_op.cc — row-wise scatter on axis 0
    if overwrite:
        return x.at[index].set(updates)
    base = x.at[index].set(jnp.zeros_like(updates))
    return base.at[index].add(updates)


@defop
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape):
    z = wrap(jnp.zeros(tuple(int(s) for s in shape), unwrap(updates).dtype))
    return scatter_nd_add(z, index, updates)


@defop
def where(condition, x=None, y=None):
    if x is None and y is None:
        return tuple(jnp.nonzero(condition))  # data-dependent; eager only
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple=False):
    v = unwrap(x)
    nz = jnp.nonzero(v)
    if as_tuple:
        return tuple(wrap(a[:, None]) for a in nz)
    return wrap(jnp.stack(nz, axis=1))


@defop
def masked_select(x, mask):
    return x[mask]  # data-dependent shape; eager only


@defop
def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


@defop
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    nd = x.ndim
    pad = [int(p) for p in pad]
    if len(pad) == 2 * nd:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle F.pad convention: first pair pads the LAST spatial dim
        # (left,right,top,bottom,...), so reverse the pairs into dim order
        n_spatial = len(pad) // 2
        spatial = [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)]
        spatial = spatial[::-1]
        if data_format.upper().endswith("C"):  # NHWC / NLC / NDHWC
            cfg = [(0, 0)] * (nd - n_spatial - 1) + spatial + [(0, 0)]
        else:
            cfg = [(0, 0)] * (nd - n_spatial) + spatial
    if mode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, cfg, mode=jmode)


@defop(name="topk_op")
def _topk(x, k, axis, largest):
    if axis not in (-1, x.ndim - 1):
        xm = jnp.moveaxis(x, axis, -1)
    else:
        xm = x
    vals, idx = jax.lax.top_k(xm if largest else -xm, k)
    if not largest:
        vals = -vals
    if axis not in (-1, x.ndim - 1):
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(jnp.int64)


def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    if isinstance(k, Tensor):
        k = int(k.item())
    return _topk(x, k, axis, largest)


@defop
def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


@defop
def argsort(x, axis=-1, descending=False):
    idx = jnp.argsort(x, axis=axis)
    if descending:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(jnp.int64)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    v = unwrap(x)
    out = jnp.unique(v, return_index=return_index, return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if isinstance(out, tuple):
        return tuple(wrap(o) for o in out)
    return wrap(out)


@defop
def _tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@defop
def _triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@defop
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@defop
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@defop
def as_strided_slice(x, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = slice(st, en, sd)
    return x[tuple(idx)]


def slice(x, axes, starts, ends):  # noqa: A001 - paddle API name
    starts = [int(unwrap(s)) for s in starts]
    ends = [int(unwrap(e)) for e in ends]
    return as_strided_slice(x, axes, starts, ends, [1] * len(axes))


def strided_slice(x, axes, starts, ends, strides):
    return as_strided_slice(x, [int(a) for a in axes], [int(unwrap(s)) for s in starts],
                            [int(unwrap(e)) for e in ends], [int(unwrap(s)) for s in strides])


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [_unwrap_index(i) for i in idx]
    if isinstance(idx, builtins.slice):
        return builtins.slice(_unwrap_index(idx.start), _unwrap_index(idx.stop),
                              _unwrap_index(idx.step))
    return idx


@defop(name="getitem")
def _getitem(x, idx):
    return x[idx]


def getitem(x, idx):
    return _getitem(x, idx=_unwrap_index(idx))


@defop(name="setitem")
def _setitem(x, v, idx):
    v = jnp.asarray(v, x.dtype) if not hasattr(v, "dtype") else v.astype(x.dtype)
    return x.at[idx].set(v)


def setitem(x, idx, value):
    # reference: operators/set_value_op.cc; functional scatter + SSA rebind
    value = value if isinstance(value, Tensor) else wrap(jnp.asarray(value))
    return _setitem(x, value, idx=_unwrap_index(idx))


@defop
def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


@defop
def tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


@defop
def searchsorted(sorted_sequence, values, right=False):
    side = "right" if right else "left"
    return jnp.searchsorted(sorted_sequence, values, side=side).astype(jnp.int64)


@defop
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


@defop
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@defop
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@defop
def crop(x, shape, offsets):
    idx = tuple(builtins.slice(o, o + s) for o, s in zip(offsets, shape))
    return x[idx]
