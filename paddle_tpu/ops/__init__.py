"""paddle_tpu.ops — the op library (single jnp/lax kernel per op).

TPU-native replacement for the reference's operator library
(reference: paddle/fluid/operators/, 737 REGISTER_OPERATOR sites — see
SURVEY.md N30). Dispatch model in _dispatch.py.
"""
from ._dispatch import OP_REGISTRY, defop  # noqa: F401
from .math import *          # noqa: F401,F403
from .creation import *      # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .reduction import *     # noqa: F401,F403
from .logic import *         # noqa: F401,F403
from .linalg import *        # noqa: F401,F403
from .activation import *    # noqa: F401,F403
from .conv import *          # noqa: F401,F403
from .norm_ops import *      # noqa: F401,F403
from .loss import *          # noqa: F401,F403
from .sequence import *      # noqa: F401,F403
from .math_extra import *    # noqa: F401,F403
from .detection import *     # noqa: F401,F403
from .op_tail import *       # noqa: F401,F403

from . import _bind  # attaches Tensor operators/methods  # noqa: F401,E402


def _register_plain_ops():
    """Sweep every public op function into OP_REGISTRY (the OpInfoMap
    analog). Ops defined with @defop register themselves; creation/random/
    ragged ops are plain functions (no Tensor-lifting wrapper to apply) but
    are op families all the same — the registry is the library inventory
    the static executor and tooling consult. setdefault keeps defop
    entries (which carry .raw for Program unpickling) authoritative."""
    import inspect
    import sys

    mods = ("math", "creation", "manipulation", "reduction", "logic",
            "linalg", "activation", "conv", "norm_ops", "loss", "sequence",
            "math_extra", "detection", "op_tail")
    for m in mods:
        mod = sys.modules[f"{__name__}.{m}"]
        public = getattr(mod, "__all__", None) or [
            n for n in vars(mod) if not n.startswith("_")]
        for n in public:
            fn = getattr(mod, n, None)
            if not callable(fn) or inspect.isclass(fn) \
                    or inspect.ismodule(fn):
                continue
            if getattr(fn, "__module__", "").startswith("paddle_tpu") \
                    or getattr(fn, "op_name", None):
                if not hasattr(fn, "raw"):
                    try:
                        fn.raw = fn
                    except (AttributeError, TypeError):
                        pass
                OP_REGISTRY.setdefault(n, fn)


_register_plain_ops()
