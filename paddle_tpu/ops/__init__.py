"""paddle_tpu.ops — the op library (single jnp/lax kernel per op).

TPU-native replacement for the reference's operator library
(reference: paddle/fluid/operators/, 737 REGISTER_OPERATOR sites — see
SURVEY.md N30). Dispatch model in _dispatch.py.
"""
from ._dispatch import OP_REGISTRY, defop  # noqa: F401
from .math import *          # noqa: F401,F403
from .creation import *      # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .reduction import *     # noqa: F401,F403
from .logic import *         # noqa: F401,F403
from .linalg import *        # noqa: F401,F403
from .activation import *    # noqa: F401,F403
from .conv import *          # noqa: F401,F403
from .norm_ops import *      # noqa: F401,F403
from .loss import *          # noqa: F401,F403
from .sequence import *      # noqa: F401,F403
from .math_extra import *    # noqa: F401,F403
from .detection import *     # noqa: F401,F403

from . import _bind  # attaches Tensor operators/methods  # noqa: F401,E402
