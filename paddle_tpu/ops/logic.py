"""Comparison / logical / predicate ops.

Parity targets: reference operators/controlflow/compare_op.cc,
logical_op.cc, isfinite_v2_op.cc and python/paddle/tensor/logic.py.
All outputs are bool and never carry gradient.
"""
from __future__ import annotations

import jax.numpy as jnp

from ._dispatch import defop


@defop
def equal(x, y):
    return jnp.equal(x, y)


@defop
def not_equal(x, y):
    return jnp.not_equal(x, y)


@defop
def greater_than(x, y):
    return jnp.greater(x, y)


@defop
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@defop
def less_than(x, y):
    return jnp.less(x, y)


@defop
def less_equal(x, y):
    return jnp.less_equal(x, y)


@defop
def logical_and(x, y):
    return jnp.logical_and(x, y)


@defop
def logical_or(x, y):
    return jnp.logical_or(x, y)


@defop
def logical_xor(x, y):
    return jnp.logical_xor(x, y)


@defop
def logical_not(x):
    return jnp.logical_not(x)


@defop
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@defop
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@defop
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@defop
def bitwise_not(x):
    return jnp.bitwise_not(x)


@defop
def isnan(x):
    return jnp.isnan(x)


@defop
def isinf(x):
    return jnp.isinf(x)


@defop
def isfinite(x):
    return jnp.isfinite(x)


@defop
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    from ._dispatch import unwrap, wrap
    return wrap(jnp.allclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol,
                             equal_nan=equal_nan))


def equal_all(x, y):
    from ._dispatch import unwrap, wrap
    return wrap(jnp.array_equal(unwrap(x), unwrap(y)))


@defop
def is_empty(x):
    return jnp.asarray(x.size == 0)
