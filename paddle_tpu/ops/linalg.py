"""Linear algebra ops.

Parity targets: reference operators/matmul_v2_op.cc (+ math/blas.h GEMM
dispatch), mul_op.cc, dot_op.cc, bmm_op.cc, p_norm_op.cc, cholesky_op.cc,
svd, inverse_op.cc, triangular ops, and python/paddle/tensor/linalg.py.

TPU note: matmuls are the MXU hot path. `FLAGS_use_bf16_matmul` keeps
operands in bf16 with f32 accumulation via `preferred_element_type`
(SURVEY.md §7 "MXU" guidance) — the analog of the reference's cuBLAS
TF32/FP16 tensor-core paths.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._dispatch import defop


def _matmul_infer(x, y, transpose_x=False, transpose_y=False):
    """Abstract rule (registered alongside @defop): catches rank and
    contraction-dim errors at Program build/verify time with a named
    diagnostic instead of an XLA trace error."""
    import numpy as np
    xs, ys = list(x.shape), list(y.shape)
    if not xs or not ys:
        raise ValueError(
            f"matmul requires rank >= 1 operands, got {tuple(x.shape)} @ "
            f"{tuple(y.shape)}")
    if transpose_x and len(xs) > 1:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if transpose_y and len(ys) > 1:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    vec_x = len(xs) == 1
    vec_y = len(ys) == 1
    if vec_x:
        xs = [1] + xs
    if vec_y:
        ys = ys + [1]
    if xs[-1] != ys[-2]:
        raise ValueError(
            f"matmul contraction mismatch: {tuple(x.shape)} @ "
            f"{tuple(y.shape)} contracts {xs[-1]} against {ys[-2]}"
            + (" (with transpose flags applied)"
               if transpose_x or transpose_y else ""))
    batch = np.broadcast_shapes(tuple(xs[:-2]), tuple(ys[:-2]))
    out = list(batch) + [xs[-2], ys[-1]]
    if vec_y:
        out = out[:-1]
    if vec_x:
        out = out[:-2] + out[-1:] if not vec_y else out[:-1]
    return jax.ShapeDtypeStruct(tuple(out),
                                jnp.result_type(x.dtype, y.dtype))


@defop(infer=_matmul_infer)
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    from ..core.flags import flag
    pref = None
    if (flag("FLAGS_use_bf16_matmul")
            and x.dtype == jnp.bfloat16 and y.dtype == jnp.bfloat16):
        pref = jnp.float32  # accumulate in f32 on the MXU
    out = jnp.matmul(x, y, preferred_element_type=pref)
    if pref is not None:
        out = out.astype(jnp.bfloat16)
    return out


@defop
def dot(x, y):
    # paddle.dot: 1-d/2-d innermost product, batched on leading dim
    return jnp.sum(x * y, axis=-1)


@defop
def bmm(x, y):
    return jnp.matmul(x, y)


@defop
def mv(x, vec):
    return jnp.matmul(x, vec)


@defop
def outer(x, y):
    return jnp.outer(x, y)


@defop
def inner(x, y):
    return jnp.inner(x, y)


@defop
def cross(x, y, axis=None):
    return jnp.cross(x, y, axis=-1 if axis is None else axis)


@defop
def norm(x, p="fro", axis=None, keepdim=False):
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis, keepdims=keepdim))
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=ax, keepdims=keepdim),
                     1.0 / p)


@defop
def p_norm(x, porder=2.0, axis=-1, keepdim=False, epsilon=1e-12):
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), porder), axis=axis,
                             keepdims=keepdim) + epsilon, 1.0 / porder)


@defop
def dist(x, y, p=2.0):
    d = jnp.abs(x - y)
    if p == 0:
        return jnp.sum((d != 0).astype(x.dtype))
    if p == float("inf"):
        return jnp.max(d)
    if p == float("-inf"):
        return jnp.min(d)
    return jnp.power(jnp.sum(jnp.power(d, p)), 1.0 / p)


@defop
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@defop
def inverse(x):
    return jnp.linalg.inv(x)


@defop
def pinv(x, rcond=1e-15):
    return jnp.linalg.pinv(x, rtol=rcond)


@defop
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@defop
def det(x):
    return jnp.linalg.det(x)


@defop
def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return sign, logdet


@defop
def svd(x, full_matrices=False):
    return tuple(jnp.linalg.svd(x, full_matrices=full_matrices))


@defop
def qr(x, mode="reduced"):
    return tuple(jnp.linalg.qr(x, mode=mode))


@defop
def eigh(x, UPLO="L"):
    w, v = jnp.linalg.eigh(x, UPLO=UPLO)
    return w, v


@defop
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@defop
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@defop
def solve(x, y):
    return jnp.linalg.solve(x, y)


@defop
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@defop
def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@defop
def multi_dot(*xs):
    return jnp.linalg.multi_dot(xs)


@defop
def histogram(x, bins=100, min=0, max=0):  # noqa: A002
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return hist


@defop
def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)
