"""Hand-written Pallas TPU kernels.

TPU-native analog of the reference's hand-written kernel tiers —
operators/math/ (~30k LoC of CPU/CUDA primitives) and operators/jit/
(runtime x86 codegen, reference jit/gen/jitcode.h:23). Where the reference
drops to CUDA/xbyak for the ops XLA-era compilers couldn't fuse, we drop to
Pallas for the ops XLA *still* can't schedule optimally: flash attention
(O(s) memory online-softmax attention) is the first; kernels here own their
backward passes via jax.custom_vjp (the analog of hand-written *_grad
kernels).

Kernels run compiled on TPU and in Pallas interpreter mode elsewhere, so the
same code paths are testable on the CPU mesh (tests/conftest.py).
"""
from .flash_attention import flash_attention  # noqa: F401

__all__ = ["flash_attention"]
