"""Hand-written Pallas TPU kernels.

TPU-native analog of the reference's hand-written kernel tiers —
operators/math/ (~30k LoC of CPU/CUDA primitives) and operators/jit/
(runtime x86 codegen, reference jit/gen/jitcode.h:23). Where the reference
drops to CUDA/xbyak for the ops XLA-era compilers couldn't fuse, we drop to
Pallas for the ops XLA *still* can't schedule optimally: flash attention
(O(s) memory online-softmax attention), the fused linear+CE loss head, and
the single-query decode-attention kernel over StaticKVCache; train-path
kernels own their backward passes via jax.custom_vjp (the analog of
hand-written *_grad kernels).

Kernels run compiled on TPU and in Pallas interpreter mode elsewhere, so the
same code paths are testable on the CPU mesh (tests/conftest.py).

Every dispatch site goes through `run_guarded`: a kernel that fails to
trace/compile/run demotes to its jnp fallback and bumps
`pallas.fallback.{kernel}.{reason}` in core/monitor instead of aborting the
step — a Mosaic crash must never poison a bench or training run (the
BENCH_r03 failure mode, where both kernels crashed out and the whole run
silently measured the fallback paths). Eligibility-gate rejections bump
`pallas.gate_reject.{kernel}.{reason}` so bench output can report *why* a
kernel didn't engage; engagements bump `pallas.hit.{kernel}`. The counters
count call-site engagements (once per trace under jit), not per-step
executions.
"""
from __future__ import annotations

import warnings

from .flash_attention import flash_attention  # noqa: F401
from .decode_attention import decode_attention  # noqa: F401

__all__ = ["flash_attention", "decode_attention", "run_guarded",
           "gate_reject"]


def gate_reject(kernel: str, reason: str):
    """Record one eligibility-gate rejection (and return False so gates
    can `return gate_reject(k, r)`)."""
    from ...core import monitor, trace
    monitor.stat_add(f"pallas.gate_reject.{kernel}.{reason}")
    trace.instant("pallas/gate_reject", kernel=kernel, reason=reason)
    return False


def run_guarded(kernel: str, thunk, fallback):
    """Run a Pallas kernel thunk; on ANY failure demote to the jnp
    fallback thunk, bumping pallas.fallback.{kernel}.{exception-type}.
    FLAGS_pallas_strict re-raises instead (kernel development / tests
    that assert on the error itself). Every dispatch leaves a span with
    its outcome (hit / fallback+reason) in the trace ring, so a fallback
    storm shows up in a flight-recorder dump with per-call timing, not
    just a final counter value."""
    from ...core import flags as _flags
    from ...core import monitor, trace
    sp = trace.begin(f"pallas/{kernel}")
    try:
        out = thunk()
    except Exception as e:
        strict = _flags.flag("FLAGS_pallas_strict")
        # strict mode re-raises without running the fallback — the span
        # must not claim a fallback the counters won't show
        sp.attrs["outcome"] = "error" if strict else "fallback"
        sp.attrs["reason"] = type(e).__name__
        trace.end(sp)
        if strict:
            raise
        monitor.stat_add(f"pallas.fallback.{kernel}.{type(e).__name__}")
        warnings.warn(
            f"Pallas kernel '{kernel}' failed ({type(e).__name__}: {e}); "
            "demoted to the jnp fallback for this call. See "
            "monitor.stats('pallas.') and docs/pallas_kernels.md.",
            RuntimeWarning, stacklevel=2)
        return fallback()
    sp.attrs["outcome"] = "hit"
    trace.end(sp)
    monitor.stat_add(f"pallas.hit.{kernel}")
    return out
