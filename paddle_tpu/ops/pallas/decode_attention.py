"""Single-query flash attention over a StaticKVCache — the decode kernel.

The generate() hot loop attends one new token (or a small chunk) against a
preallocated [b, h, max_seq_len, d] cache that is mostly empty: after
prefilling a 32-token prompt into a 1024-slot cache, the jnp path
(nn/layer/transformer._static_cache_attention) still streams all 1024
padded K/V columns through the MXU every step and masks 90%+ of them to
-1e9 after the fact. This kernel moves both the masking and the skipping
inside the Pallas grid:

- the cache length rides in as a *scalar-prefetch* operand (SMEM), so the
  K/V BlockSpec index maps can clamp the block index to the last live
  block — Pallas skips the HBM->VMEM DMA for a revisited block, so a step
  at cache length `len` reads ~ceil(len/bk) blocks instead of
  max_seq_len/bk;
- fully-dead blocks skip their compute via pl.when on the same predicate;
- the live/dead boundary column is masked in-kernel against
  `index + row` (identical semantics to _static_cache_attention: position
  p = index + row attends to cache cols <= p).

Lengths may be a scalar (the StaticKVCache.index fast path) or a [b]
vector — ragged per-batch lengths attend each batch row to its own
prefix, which the jnp path can't express without materializing a mask.

Decode runs under no_grad inside the generation scan, so this kernel is
deliberately vjp-free: differentiating it raises, and the eligibility
gate (nn/layer/transformer._decode_kernel_eligible) keeps training-time
cache use on the jnp path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .flash_attention import (NEG_INF, _ceil_to, _cparams, _interpret,
                              _pick_block, _vmem)

__all__ = ["decode_attention", "supported",
           "paged_decode_attention", "paged_supported"]


def _decode_attn_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                        m_scr, l_scr, acc_scr, *, scale, bk, nk, s):
    """Grid (b, h, nk); nk is the sequential accumulator dim. len_ref is
    the scalar-prefetch [b] live-length vector (index + s per batch)."""
    ib, ik = pl.program_id(0), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # np.int32 scalars throughout: arithmetic mixing an SMEM-read scalar
    # with weak python ints emits scalar converts Mosaic can't lower
    length = len_ref[ib]                       # live cols for the LAST row
    index = length - np.int32(s)               # cache fill before the chunk
    last = jnp.maximum(length - np.int32(1),
                       np.int32(0)) // np.int32(bk)  # last live block

    @pl.when(ik <= last)
    def _compute():
        q = q_ref[0, 0]                        # [s, d]
        k = k_ref[0, 0]                        # [bk, d]
        sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        row = jax.lax.broadcasted_iota(jnp.int32, (s, bk), 0)
        col = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (s, bk), 1)
        # np.float32: weak-f64 scalar converts recurse Mosaic lowering on
        # some jax builds (see flash_attention._causal_mask)
        sc = jnp.where(col <= index + row, sc, np.float32(NEG_INF))
        m_prev = m_scr[:]                      # [s, 1]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(sc - m_new)                # [s, bk] f32
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0, 0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(ik == nk - 1)
    def _flush():
        denom = jnp.maximum(l_scr[:], 1e-30)   # padded rows stay finite
        o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def supported(q_shape, cache_shape) -> bool:
    """Static predicate: can the decode kernel serve this (q, cache) pair?
    q [b, h, s, d] against cache [b, h, L, d]. The query chunk is padded
    to the 8-row sublane tile in the wrapper, so any s up to 256 works;
    beyond that a chunked prefill belongs on the flash kernel instead."""
    if len(q_shape) != 4 or len(cache_shape) != 4:
        return False
    b, h, s, d = q_shape
    bl, hl, L, dl = cache_shape
    if (bl, hl, dl) != (b, h, d):
        return False
    if d > 256 or s < 1 or s > 256 or L < 8:
        return False
    return _pick_block(_ceil_to(L, 8), 128) is not None


def _call(q, kc, vc, lengths, scale, bk):
    """The pallas_call for already-tile-padded operands."""
    from jax.experimental.pallas import tpu as pltpu
    b, h, s_p, d = q.shape
    nk = kc.shape[2] // bk

    def q_map(ib, ih, ik, len_ref):
        return (ib, ih, 0, 0)

    def kv_map(ib, ih, ik, len_ref):
        # clamp to the last live block: a revisited block index skips the
        # HBM->VMEM DMA, so dead cache tail blocks are never fetched
        # (np.int32 scalars: see _decode_attn_kernel)
        last = jnp.maximum(len_ref[ib] - np.int32(1),
                           np.int32(0)) // np.int32(bk)
        return (ib, ih, jnp.minimum(ik, last), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, nk),
        in_specs=[
            pl.BlockSpec((1, 1, s_p, d), q_map),
            pl.BlockSpec((1, 1, bk, d), kv_map),
            pl.BlockSpec((1, 1, bk, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, s_p, d), q_map),
        scratch_shapes=[
            _vmem((s_p, 1), jnp.float32),
            _vmem((s_p, 1), jnp.float32),
            _vmem((s_p, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_attn_kernel, scale=float(scale),
                               bk=bk, nk=nk, s=s_p)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s_p, d), q.dtype),
        compiler_params=_cparams("parallel", "parallel", "arbitrary"),
        interpret=_interpret(),
    )(lengths, q, kc, vc)


def _pick_bk(shape, dtype, scale, measure_builder):
    """KV block size: FLAGS_decode_block_k override, else the autotune
    table, else 128 columns (one MXU lane tile; small enough that a
    33-token prompt reads one block, big enough to amortize the grid)."""
    from ...core import flags as _flags
    from . import autotune
    b, h, s_p, d, L_p = shape
    cfg = int(_flags.flag("FLAGS_decode_block_k") or 0)
    default = _pick_block(L_p, cfg or 128)
    if cfg:
        return default
    cands = [(x,) for x in (256, 128, 64) if L_p % x == 0]
    if len(cands) <= 1:
        return default
    return autotune.lookup(
        "decode_attention",
        (autotune.bucket(L_p), autotune.bucket(s_p), d),
        dtype, cands, measure_builder(), (default,))[0]


# --------------------------------------------------------------------------
# block-table (paged) variant: the serving tier's kernel
# --------------------------------------------------------------------------
#
# The contiguous kernel above assumes each batch row owns a private
# [L, d] cache slab. The continuous-batching serve loop
# (inference/serving.py) instead shares ONE physical arena
# [n_blocks, h, block_size, d] across every in-flight request
# (nn/kv_pool.py): request i's logical block j lives at physical row
# block_tables[i, j]. The only change the indirection needs is in the
# K/V BlockSpec index maps — the block table rides the scalar-prefetch
# path next to the ragged lengths, so the index map gathers the LIVE
# physical block for (batch, logical-block) and clamps past the last
# live one exactly like the contiguous kernel. Per-step HBM traffic
# therefore scales with ceil(live_len/bs) blocks per request, never
# with max_seq_len, and never with the arena size.

def _paged_decode_attn_kernel(len_ref, bt_ref, q_ref, k_ref, v_ref, o_ref,
                              m_scr, l_scr, acc_scr, *, scale, bs, nb, s):
    """Grid (b, h, nb); nb = logical blocks per request (sequential
    accumulator dim). len_ref is the [b] live-length vector (index + s
    per batch, like the contiguous kernel); bt_ref [b, nb] maps logical
    to physical arena blocks (consumed by the index maps — unused here
    beyond documentation: logical col ids already encode causality)."""
    ib, ik = pl.program_id(0), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[ib]                       # live cols for the LAST row
    index = length - np.int32(s)               # cache fill before the chunk
    last = jnp.minimum(
        jnp.maximum(length - np.int32(1), np.int32(0)) // np.int32(bs),
        np.int32(nb - 1))                      # last live logical block

    @pl.when(ik <= last)
    def _compute():
        q = q_ref[0, 0]                        # [s, d]
        k = k_ref[0, 0]                        # [bs, d]
        sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        row = jax.lax.broadcasted_iota(jnp.int32, (s, bs), 0)
        col = ik * bs + jax.lax.broadcasted_iota(jnp.int32, (s, bs), 1)
        sc = jnp.where(col <= index + row, sc, np.float32(NEG_INF))
        m_prev = m_scr[:]                      # [s, 1]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(sc - m_new)                # [s, bs] f32
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0, 0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(ik == nb - 1)
    def _flush():
        denom = jnp.maximum(l_scr[:], 1e-30)   # padded rows stay finite
        o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def paged_supported(q_shape, arena_shape) -> bool:
    """Static predicate: can the paged kernel serve q [b, h, s, d] over
    an arena [n_blocks, h, block_size, d]? block_size is fixed by the
    pool layout, so it must already be a sublane-tile multiple."""
    if len(q_shape) != 4 or len(arena_shape) != 4:
        return False
    b, h, s, d = q_shape
    nb_phys, hl, bs, dl = arena_shape
    if (hl, dl) != (h, d):
        return False
    if d > 256 or s < 1 or s > 256:
        return False
    return bs >= 8 and bs % 8 == 0 and bs <= 1024 and nb_phys >= 1


def _paged_call(q, k_arena, v_arena, block_tables, lengths, scale):
    """The pallas_call for already-tile-padded q. The arena is NOT
    padded or copied — indirection is the whole point."""
    from jax.experimental.pallas import tpu as pltpu
    b, h, s_p, d = q.shape
    bs = k_arena.shape[2]
    nb = block_tables.shape[1]

    def q_map(ib, ih, ik, len_ref, bt_ref):
        return (ib, ih, 0, 0)

    def kv_map(ib, ih, ik, len_ref, bt_ref):
        # gather ONLY live physical blocks: past the last live logical
        # block the index clamps, the physical id repeats, and Pallas
        # skips the HBM->VMEM DMA for the revisited block — per-step KV
        # bytes scale with live blocks, not arena/max_seq_len
        # (np.int32 scalars: see _decode_attn_kernel)
        last = jnp.minimum(
            jnp.maximum(len_ref[ib] - np.int32(1),
                        np.int32(0)) // np.int32(bs),
            np.int32(nb - 1))
        return (bt_ref[ib, jnp.minimum(ik, last)], ih, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, nb),
        in_specs=[
            pl.BlockSpec((1, 1, s_p, d), q_map),
            pl.BlockSpec((1, 1, bs, d), kv_map),
            pl.BlockSpec((1, 1, bs, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, s_p, d), q_map),
        scratch_shapes=[
            _vmem((s_p, 1), jnp.float32),
            _vmem((s_p, 1), jnp.float32),
            _vmem((s_p, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_decode_attn_kernel,
                               scale=float(scale), bs=bs, nb=nb, s=s_p)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s_p, d), q.dtype),
        compiler_params=_cparams("parallel", "parallel", "arbitrary"),
        interpret=_interpret(),
    )(lengths, block_tables, q, k_arena, v_arena)


def paged_decode_attention(q, k_arena, v_arena, block_tables, lengths,
                           scale=None):
    """Attention of q [b, h, s, d] over a PAGED cache: per-request block
    tables [b, max_blocks] of physical block ids into shared arenas
    k_arena/v_arena [n_blocks, h, block_size, d]. `lengths` [b] is each
    request's cache fill count BEFORE this chunk (the chunk's k/v must
    already be scattered into the arena — nn/kv_pool.write_kv). Row r of
    batch i attends to logical cache cols <= lengths[i] + r. Block-table
    entries past the allocation MUST be 0 (the pool's reserved trash
    block): padded query rows reach past the live end and the index map
    must land on a valid physical row. Eval-only (no vjp); returns
    [b, h, s, d] in q's dtype."""
    b, h, s, d = q.shape
    if v_arena.shape != k_arena.shape or k_arena.shape[3] != d \
            or k_arena.shape[1] != h:
        raise ValueError(
            f"paged_decode_attention: arena shapes k{tuple(k_arena.shape)} "
            f"v{tuple(v_arena.shape)} don't match q{tuple(q.shape)}")
    bs = k_arena.shape[2]
    if bs % 8 != 0 or bs < 8:
        raise ValueError(
            f"paged_decode_attention: block_size {bs} must be a multiple "
            "of the 8-row sublane tile")
    if scale is None:
        scale = d ** -0.5
    out_dtype = q.dtype
    if q.dtype != k_arena.dtype:
        q = q.astype(k_arena.dtype)

    s_p = _ceil_to(s, 8)   # sublane tile: pad query rows, slice back below
    if s_p != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, s_p - s), (0, 0)))
    # lengths in PADDED-row terms (kernel recovers fill as length - s_p);
    # padded rows attend a few cols past the live end — garbage rows
    # sliced off below, and their block-table lookups land on entry 0
    # (the trash block) by the pool's table convention
    lens = jnp.asarray(lengths, jnp.int32)
    lens = jnp.broadcast_to(lens.reshape(-1), (b,)) + jnp.int32(s_p)
    bt = jnp.asarray(block_tables, jnp.int32)
    out = _paged_call(q, k_arena, v_arena, bt, lens, scale)
    out = out.astype(out_dtype)
    return out[:, :, :s] if s_p != s else out


def decode_attention(q, kc, vc, index, scale=None, block_k=None):
    """Attention of q [b, h, s, d] over a partially-filled cache
    kc/vc [b, h, L, d]. `index` is the cache fill count before this chunk
    — an i32 scalar (StaticKVCache.index) or a [b] vector for ragged
    per-batch fills. Row r of the chunk attends to cache cols
    <= index + r. Returns [b, h, s, d] in q's dtype. Eval-only (no vjp).
    """
    b, h, s, d = q.shape
    L = kc.shape[2]
    if vc.shape != kc.shape or kc.shape[3] != d:
        raise ValueError(f"decode_attention: cache shapes k{tuple(kc.shape)}"
                         f" v{tuple(vc.shape)} don't match q{tuple(q.shape)}")
    if scale is None:
        scale = d ** -0.5
    out_dtype = q.dtype
    if q.dtype != kc.dtype:
        q = q.astype(kc.dtype)  # keep both matmuls on one MXU dtype

    s_p = _ceil_to(s, 8)   # sublane tile: pad query rows, slice back below
    if s_p != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, s_p - s), (0, 0)))
    # lengths are in PADDED-row terms (the kernel recovers the fill count
    # as length - s_p); padded rows attend a few cols past the live end —
    # they are garbage rows sliced off below
    lengths = jnp.asarray(index, jnp.int32)
    lengths = jnp.broadcast_to(lengths.reshape(-1), (b,)) + jnp.int32(s_p)
    L_p = _ceil_to(L, 8)
    if L_p != L:
        # ragged caches only appear in tests; padded cols are dead because
        # lengths <= L never reaches them
        kc = jnp.pad(kc, ((0, 0), (0, 0), (0, L_p - L), (0, 0)))
        vc = jnp.pad(vc, ((0, 0), (0, 0), (0, L_p - L), (0, 0)))

    def measure_builder():
        def measure(params):
            from . import autotune
            (bk_,) = params
            # measure at full cache length — the worst case every long
            # generation converges to; synthetic zeros (tracer-safe)
            qz = jnp.zeros(q.shape, q.dtype)
            kz = jnp.zeros(kc.shape, kc.dtype)
            lz = jnp.full((b,), L_p, jnp.int32)
            fn = jax.jit(lambda a, k_, v_, ln: _call(a, k_, v_, ln,
                                                     float(scale), bk_))
            return autotune.time_thunk(lambda: fn(qz, kz, kz, lz))
        return measure

    if block_k:
        bk = int(block_k)
        if L_p % bk != 0:
            # a non-divisor would floor-truncate the grid and silently
            # drop tail cache blocks from attention
            raise ValueError(f"decode_attention: block_k={bk} does not "
                             f"divide the padded cache length {L_p}")
    else:
        bk = _pick_bk((b, h, s_p, d, L_p), str(q.dtype), scale,
                      measure_builder)
    out = _call(q, kc, vc, lengths, scale, bk)
    out = out.astype(out_dtype)
    return out[:, :, :s] if s_p != s else out
