"""Flash attention — online-softmax attention as a Pallas TPU kernel.

TPU-native replacement for the unfused softmax(QK^T)V chain: the reference
hand-writes its attention-adjacent kernels in CUDA/xbyak
(reference operators/math/softmax.cu, operators/jit/gen/jitcode.h:23,
operators/fused/multihead_matmul_op.cu); on TPU the equivalent tier is
Pallas. The kernel never materializes the [s_q, s_k] score matrix in HBM —
scores live blockwise in VMEM with f32 running max/sum accumulators, so
attention memory is O(s) and both matmuls hit the MXU in bf16 with f32
accumulation.

Forward and backward are separate kernels wired through jax.custom_vjp (the
analog of the reference's hand-written *_grad kernels): backward recomputes
scores blockwise from the saved logsumexp, FlashAttention-2 style.

Layout: q, k, v are [batch*heads, seq, head_dim]; an optional additive bias
[batch, s_k] implements padding masks; `causal=True` adds the triangular
mask in-kernel. Runs compiled on TPU, interpreted elsewhere (CPU mesh
tests).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

import numpy as np

_Z = np.int32(0)  # index-map zero: a Python literal 0 traces as i64 under
                  # jax_enable_x64 and Mosaic rejects i64 index returns
                  # (numpy scalar, not jnp — index maps may not capture
                  # constant Arrays)

NEG_INF = -1e9  # finite "masked" value: keeps running-max finite even for
                # fully-padded rows (exp(NEG_INF - NEG_INF) stays sane)


def _pick_block(s: int, target: int = None, flag: str = None):
    """Largest block size <= target that divides s, no smaller than 8 (the
    f32 sublane tile); None means "not kernel-friendly, use the jnp path".
    target=None: FLAGS_flash_block_* override, else auto — 256 once the
    sequence is long enough to amortize (measured on v5e: s=2048 fwd+dq
    3.70ms at blk 256 vs 5.41ms at blk 128)."""
    if target is None:
        cfg = 0
        if flag is not None:
            from ...core import flags as _flags
            cfg = int(_flags.flag(flag) or 0)
        target = cfg if cfg else (256 if s >= 1024 else 128)
    for b in (target, 512, 256, 128, 64, 32, 16, 8):
        if b <= target and s % b == 0:
            return b
    return None


def block_candidates(s: int, cap: int = 512):
    """Block sizes worth autotuning over: divisors of s in [64, cap] (below
    64 the grid overhead always loses on the MXU), plus the sublane floor
    when s is tiny."""
    cands = [b for b in (512, 256, 128, 64) if b <= cap and s % b == 0]
    if not cands:
        cands = [b for b in (32, 16, 8) if s % b == 0][:1]
    return cands


def _ceil_to(n: int, m: int) -> int:
    return -(-int(n) // m) * m


def _interpret() -> bool:
    """Pallas execution mode. Compiled on TPU; interpreted elsewhere —
    except under FLAGS_pallas_force_compile, which forces Mosaic lowering
    even off-TPU so tools/hlo_evidence.py can AOT-lower the bench graphs
    for a TPU target on any dev box (lowering needs no TPU; only *running*
    does)."""
    from ...core import flags as _flags
    if _flags.flag("FLAGS_pallas_force_compile"):
        return False
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _causal_live(iq, ik, bq, bk, off):
    """Is block (iq, ik) at least partly unmasked under bottom-right-aligned
    causal masking (col <= row + off, off = s_k - s_q, matching _sdpa)?"""
    return ik * bk <= iq * bq + (bq - 1) + off


def _causal_mask(s, iq, ik, bq, bk, off):
    row = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    col = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    # np.float32 scalar, not the weak python float: a weak-f64 scalar
    # convert inside a kernel recurses Mosaic's lowering on some jax
    # builds (and 64-bit kernel values SIGABRT on TPU regardless)
    return jnp.where(row + off >= col, s, np.float32(NEG_INF))


def _flash_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                      m_scr, l_scr, acc_scr, *, scale, causal, bq, bk, nk,
                      off):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]                               # [bq, d]
        k = k_ref[0]                               # [bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0]                    # [1, bk] broadcasts
        if causal:
            s = _causal_mask(s, iq, ik, bq, bk, off)

        m_prev = m_scr[:]                          # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        p = jnp.exp(s - m_new)                     # [bq, bk] f32
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    if causal:
        pl.when(_causal_live(iq, ik, bq, bk, off))(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _flush():
        denom = jnp.maximum(l_scr[:], 1e-30)       # fully-masked rows -> 0
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)
        lse_ref[:] = (m_scr[:] + jnp.log(denom)).reshape(lse_ref.shape)


def _fwd(q, k, v, bias, scale, causal, heads, bq, bk, off):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // bq, sk // bk
    grid = (bh, nq, nk)

    in_specs = [
        pl.BlockSpec((1, bq, d), lambda ib, iq, ik: (ib, iq, _Z)),
        pl.BlockSpec((1, bk, d), lambda ib, iq, ik: (ib, ik, _Z)),
        pl.BlockSpec((1, bk, d), lambda ib, iq, ik: (ib, ik, _Z)),
    ]
    args = [q, k, v]
    if bias is not None:
        # [b*h, 1, sk]: tiled per head so the index map is pure indexing
        # (arithmetic like ib // heads recurses in this jax's index-map
        # tracing), and the singleton row keeps the block's sublane dim
        # equal to the array's (TPU blocks must be (8,128)-divisible or
        # full-dim)
        in_specs.append(pl.BlockSpec(
            (1, 1, bk), lambda ib, iq, ik: (ib, _Z, ik)))
        args.append(jnp.repeat(
            bias.reshape(bias.shape[0], 1, bias.shape[-1]), heads, axis=0))

    # `off` is the causal-diagonal alignment of the ORIGINAL (pre-padding)
    # shapes — sk_orig - sq_orig — so tile padding can't shift the mask
    opts = dict(scale=scale, causal=causal, bq=bq, bk=bk, nk=nk, off=off)
    if bias is not None:
        kernel = functools.partial(_flash_fwd_kernel, **opts)
    else:
        def kernel(qr, kr, vr, o, lse, m, l, a):  # noqa: E741
            return _flash_fwd_kernel(qr, kr, vr, None, o, lse, m, l, a,
                                     **opts)
        # the closure's name is the `kernel_name` stamped into the lowered
        # tpu_custom_call — tools/hlo_evidence.py greps for it
        kernel.__name__ = _flash_fwd_kernel.__name__

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda ib, iq, ik: (ib, iq, _Z)),
            # lse rides as [bh, sq, 1]: trailing singleton == array dim, and
            # the sublane dim bq is 8-divisible — legal TPU tiling, unlike a
            # (1, bq) block over [bh, sq]
            pl.BlockSpec((1, bq, 1), lambda ib, iq, ik: (ib, iq, _Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((bq, 1), jnp.float32),
            _vmem((bq, 1), jnp.float32),
            _vmem((bq, d), jnp.float32),
        ],
        compiler_params=_cparams("parallel", "parallel", "arbitrary"),
        interpret=_interpret(),
    )(*args)
    return out, lse[..., 0]


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, dtype)


def _cparams(*semantics):
    """Mosaic grid semantics: 'parallel' dims can be reordered/pipelined by
    the compiler, 'arbitrary' marks the sequential reduction dim (the
    revisiting accumulator pattern). Without this Mosaic assumes every dim
    is arbitrary and cannot overlap the next block's DMA with compute.

    The params class was renamed across jax releases (TPUCompilerParams ->
    CompilerParams); resolve whichever this build ships — the old
    single-name lookup was itself a Pallas crash mode (AttributeError at
    every kernel call on mismatched jax)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(dimension_semantics=semantics)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                         delta_ref, dq_ref, dq_scr, *, scale, causal, bq, bk,
                         nk, off):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0]
        if causal:
            s = _causal_mask(s, iq, ik, bq, bk, off)

        lse = lse_ref[:].reshape(bq, 1)
        p = jnp.exp(s - lse)                        # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[:].reshape(bq, 1)
        ds = p * (dp - delta) * scale               # [bq, bk] f32
        dq_scr[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        pl.when(_causal_live(iq, ik, bq, bk, off))(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _flush():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                          *, scale, causal, bq, bk, nq, off):
    ik, iq = pl.program_id(1), pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0]
        if causal:
            s = _causal_mask(s, iq, ik, bq, bk, off)

        lse = lse_ref[:].reshape(bq, 1)
        p = jnp.exp(s - lse)                        # [bq, bk] f32
        # dv += P^T dO   (contract over bq)
        dv_scr[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[:].reshape(bq, 1)
        ds = p * (dp - delta) * scale
        # dk += dS^T Q   (contract over bq)
        dk_scr[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        pl.when(_causal_live(iq, ik, bq, bk, off))(_compute)
    else:
        _compute()

    @pl.when(iq == nq - 1)
    def _flush():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q, k, v, bias, out, lse, do, scale, causal, heads, bq, bk, off):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // bq, sk // bk
    # lse/delta ride as [bh, sq, 1] and bias as [b, 1, sk] for legal TPU
    # block tiling (see _fwd)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[..., None]             # [bh, sq, 1]
    lse3 = lse[..., None]                           # [bh, sq, 1]
    bias3 = None if bias is None else jnp.repeat(
        bias.reshape(bias.shape[0], 1, bias.shape[-1]), heads, axis=0)

    def specs(extra_bias):
        base = [
            pl.BlockSpec((1, bq, d), lambda ib, i, j: (ib, i, _Z)),   # q
            pl.BlockSpec((1, bk, d), lambda ib, i, j: (ib, j, _Z)),   # k
            pl.BlockSpec((1, bk, d), lambda ib, i, j: (ib, j, _Z)),   # v
        ]
        if extra_bias:
            base.append(pl.BlockSpec(
                (1, 1, bk), lambda ib, i, j: (ib, _Z, j)))
        base += [
            pl.BlockSpec((1, bq, d), lambda ib, i, j: (ib, i, _Z)),   # do
            pl.BlockSpec((1, bq, 1), lambda ib, i, j: (ib, i, _Z)),   # lse
            pl.BlockSpec((1, bq, 1), lambda ib, i, j: (ib, i, _Z)),   # delta
        ]
        return base

    args = ([q, k, v, bias3] if bias is not None else [q, k, v]) \
        + [do, lse3, delta]

    # ---- dq: grid (bh, nq, nk), k-blocks innermost -----------------------
    dq_kernel = functools.partial(_flash_bwd_dq_kernel, scale=scale,
                                  causal=causal, bq=bq, bk=bk, nk=nk,
                                  off=off)
    if bias is None:
        inner_dq = dq_kernel

        def dq_kernel(qr, kr, vr, dor, lser, dr, dqr, scr):  # noqa: F811
            return inner_dq(qr, kr, vr, None, dor, lser, dr, dqr, scr)
        dq_kernel.__name__ = _flash_bwd_dq_kernel.__name__

    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=specs(bias is not None),
        out_specs=pl.BlockSpec((1, bq, d), lambda ib, i, j: (ib, i, _Z)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[_vmem((bq, d), jnp.float32)],
        compiler_params=_cparams("parallel", "parallel", "arbitrary"),
        interpret=_interpret(),
    )(*args)

    # ---- dk/dv: grid (bh, nk, nq), q-blocks innermost --------------------
    def specs_kv(extra_bias):
        base = [
            pl.BlockSpec((1, bq, d), lambda ib, i, j: (ib, j, _Z)),   # q
            pl.BlockSpec((1, bk, d), lambda ib, i, j: (ib, i, _Z)),   # k
            pl.BlockSpec((1, bk, d), lambda ib, i, j: (ib, i, _Z)),   # v
        ]
        if extra_bias:
            base.append(pl.BlockSpec(
                (1, 1, bk), lambda ib, i, j: (ib, _Z, i)))
        base += [
            pl.BlockSpec((1, bq, d), lambda ib, i, j: (ib, j, _Z)),   # do
            pl.BlockSpec((1, bq, 1), lambda ib, i, j: (ib, j, _Z)),   # lse
            pl.BlockSpec((1, bq, 1), lambda ib, i, j: (ib, j, _Z)),   # delta
        ]
        return base

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, scale=scale,
                                   causal=causal, bq=bq, bk=bk, nq=nq,
                                   off=off)
    if bias is None:
        inner_dkv = dkv_kernel

        def dkv_kernel(qr, kr, vr, dor, lser, dr, dkr, dvr, ks, vs):  # noqa: F811,E501
            return inner_dkv(qr, kr, vr, None, dor, lser, dr, dkr, dvr,
                             ks, vs)
        dkv_kernel.__name__ = _flash_bwd_dkv_kernel.__name__

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=specs_kv(bias is not None),
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda ib, i, j: (ib, i, _Z)),
            pl.BlockSpec((1, bk, d), lambda ib, i, j: (ib, i, _Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[_vmem((bk, d), jnp.float32),
                        _vmem((bk, d), jnp.float32)],
        compiler_params=_cparams("parallel", "parallel", "arbitrary"),
        interpret=_interpret(),
    )(*args)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public op (custom_vjp)
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, bias, scale, causal, heads, bq, bk, off):
    out, _ = _fwd(q, k, v, bias, scale, causal, heads, bq, bk, off)
    return out


def _flash_fwd(q, k, v, bias, scale, causal, heads, bq, bk, off):
    out, lse = _fwd(q, k, v, bias, scale, causal, heads, bq, bk, off)
    return out, (q, k, v, bias, out, lse)


def _flash_bwd(scale, causal, heads, bq, bk, off, res, g):
    q, k, v, bias, out, lse = res
    dq, dk, dv = _bwd(q, k, v, bias, out, lse, g, scale, causal, heads,
                      bq, bk, off)
    dbias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_with_lse(q, k, v, bias, scale, causal, heads, bq, bk, off):
    return _fwd(q, k, v, bias, scale, causal, heads, bq, bk, off)


def _flash_with_lse_fwd(q, k, v, bias, scale, causal, heads, bq, bk, off):
    out, lse = _fwd(q, k, v, bias, scale, causal, heads, bq, bk, off)
    return (out, lse), (q, k, v, bias, out, lse)


def _flash_with_lse_bwd(scale, causal, heads, bq, bk, off, res, g):
    q, k, v, bias, out, lse = res
    g_out, _g_lse = g  # lse is a statistic; cotangents through it are
    # not propagated (ring merges treat it as weighting data)
    dq, dk, dv = _bwd(q, k, v, bias, out, lse, g_out, scale, causal, heads,
                      bq, bk, off)
    dbias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, dbias


_flash_with_lse.defvjp(_flash_with_lse_fwd, _flash_with_lse_bwd)


def supported(q_shape, k_shape, v_shape, mask_shape=None) -> bool:
    """Static predicate: can flash_attention handle these shapes? Anything
    rejected here must take the jnp fallback (_sdpa), which handles general
    broadcasting. Sequence lengths are unconstrained: the wrapper pads
    q/k/v to (8,128)-tile-friendly multiples of 8 and slices the output
    back, so ragged lengths are kernel-eligible too."""
    if len(q_shape) != 4 or len(k_shape) != 4 or len(v_shape) != 4:
        return False
    b, h, sq, d = q_shape
    sk = k_shape[2]
    if d > 256 or k_shape[3] != d or v_shape[3] != d or v_shape[2] != sk:
        return False
    if sq < 1 or sk < 1:
        return False
    if mask_shape is not None:
        # exactly [b, 1, 1, sk]: the kernel's bias path does no broadcasting
        if tuple(mask_shape) != (b, 1, 1, sk):
            return False
    return True


def _pick_blocks(sq, sk, d, dtype, causal, with_bias, measure_builder):
    """Resolve (bq, bk): explicit FLAGS_flash_block_* overrides win, then
    the autotune table (ops/pallas/autotune.py), then the static
    heuristic. sq/sk are already tile-padded (multiples of 8)."""
    from ...core import flags as _flags
    from . import autotune
    cfg_q = int(_flags.flag("FLAGS_flash_block_q") or 0)
    cfg_k = int(_flags.flag("FLAGS_flash_block_k") or 0)
    default = (_pick_block(sq, cfg_q or None),
               _pick_block(sk, cfg_k or None))
    if cfg_q or cfg_k:
        return default
    cands = [(bq, bk) for bq in block_candidates(sq)
             for bk in block_candidates(sk)]
    return autotune.lookup(
        "flash_fwd",
        (autotune.bucket(sq), autotune.bucket(sk), d, int(bool(causal)),
         int(with_bias)),  # the bias operand changes per-block VMEM traffic
        dtype, cands, measure_builder(), default)


def flash_attention(q, k, v, bias=None, causal=False, scale=None,
                    return_lse=False):
    """Online-softmax attention, O(s) memory.

    q: [b, h, s_q, d]; k, v: [b, h, s_k, d]; bias: optional additive mask
    [b, s_k] (f32; use NEG_INF-scale values for masked keys — treated as
    non-differentiable data). Returns [b, h, s_q, d] in q's dtype; with
    return_lse=True also the per-row logsumexp [b, h, s_q] (f32), which
    lets callers merge partial-attention blocks exactly — the ring
    attention merge (distributed/ring_attention.py).

    Ragged lengths are handled here, not by the caller: q/k/v are padded
    up to a multiple of 8 (f32 sublane tile), padded key columns are
    masked through the bias, and the output is sliced back — the docstring
    contract is "any 4-D shape with matching head dims either runs the
    kernel or falls back", never a ValueError about padding.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if k.shape[3] != d or v.shape[3] != d or v.shape[2] != sk:
        raise ValueError(
            f"flash_attention needs matching head_dim/seq for k and v; got "
            f"q{tuple(q.shape)} k{tuple(k.shape)} v{tuple(v.shape)}")
    if scale is None:
        scale = d ** -0.5
    off = sk - sq  # causal alignment of the ORIGINAL shapes
    sq_p, sk_p = _ceil_to(sq, 8), _ceil_to(sk, 8)
    if bias is not None:
        bias = bias.astype(jnp.float32)
    if sk_p != sk:
        # padded key columns must never win the softmax: mask via bias —
        # except under causal with no bias, where the original-shape
        # diagonal (off = sk - sq) already caps every real row at
        # col <= sk-1, so manufacturing a bias would only add the
        # per-head bias materialization and kernel loads for nothing
        if bias is not None or not causal:
            if bias is None:
                bias = jnp.zeros((b, sk), jnp.float32)
            bias = jnp.pad(bias, ((0, 0), (0, sk_p - sk)),
                           constant_values=NEG_INF)
        k = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    if sq_p != sq:
        # padded query rows compute garbage rows that are sliced off below
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    qf = q.reshape(b * h, sq_p, d)
    kf = k.reshape(b * h, sk_p, d)
    vf = v.reshape(b * h, sk_p, d)
    if bias is not None:
        bias = jax.lax.stop_gradient(bias)

    def measure_builder():
        # synthetic concrete inputs of the call's shape/dtype: the real
        # q/k/v are usually tracers (this runs mid-jit), and TPU matmul
        # timing is data-independent, so zeros measure the same kernel
        def measure(params):
            from . import autotune
            bq_, bk_ = params
            qz = jnp.zeros((b * h, sq_p, d), q.dtype)
            kz = jnp.zeros((b * h, sk_p, d), k.dtype)
            vz = jnp.zeros((b * h, sk_p, d), v.dtype)
            bz = None if bias is None else jnp.zeros((b, sk_p), jnp.float32)
            fn = jax.jit(lambda a, b_, c: _flash(
                a, b_, c, bz, float(scale), bool(causal), h, bq_, bk_, off))
            return autotune.time_thunk(lambda: fn(qz, kz, vz))
        return measure

    bq, bk = _pick_blocks(sq_p, sk_p, d, str(q.dtype), causal,
                          bias is not None, measure_builder)
    if return_lse:
        out, lse = _flash_with_lse(qf, kf, vf, bias, float(scale),
                                   bool(causal), h, bq, bk, off)
        out = out.reshape(b, h, sq_p, d)
        lse = lse.reshape(b, h, sq_p)
        if sq_p != sq:
            out, lse = out[:, :, :sq], lse[:, :, :sq]
        return out, lse
    out = _flash(qf, kf, vf, bias, float(scale), bool(causal), h, bq, bk,
                 off)
    out = out.reshape(b, h, sq_p, d)
    return out[:, :, :sq] if sq_p != sq else out
