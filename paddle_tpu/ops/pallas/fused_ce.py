"""Fused linear + softmax-cross-entropy — the vocab-projection loss kernel.

The MLM/LM loss chain `logits = h @ W^T + b; loss = CE(logits, y)` is the
single largest non-attention memory consumer in transformer training: for
BERT-base at b32/s128 the f32 logits are [4096, 30522] ≈ 500 MB of HBM
traffic per materialization (and the reference's kernels materialize them —
operators/softmax_with_cross_entropy_op.cu). This kernel never does: vocab
tiles of the projection are computed blockwise in VMEM (bf16 on the MXU,
f32 accumulation), reduced into a running logsumexp + gathered label logit,
and discarded. Backward recomputes tiles from the saved logsumexp and feeds
them straight into the dh / dW matmuls — FlashAttention's trick applied to
the classifier, with the vocab axis playing the role of keys.

API: per-token losses (f32, 0 where ignored) so the caller owns the
mean/sum reduction; jax.custom_vjp carries dh, dW, db.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .flash_attention import NEG_INF, _Z, _ceil_to, _cparams, _interpret, \
    _vmem


def _pick(n, target):
    for b in (target, 1024, 512, 256, 128, 64, 32, 16, 8):
        if b <= target and n % b == 0:
            return b
    return None


# --------------------------------------------------------------------------
# forward: loss[i] = lse_i - logit_i[y_i]   (0 where y_i == ignore_index)
# --------------------------------------------------------------------------

def _ce_fwd_kernel(h_ref, w_ref, b_ref, y_ref, loss_ref, lse_ref,
                   m_scr, l_scr, t_scr, *, bn, bv, nv, vocab, ignore):
    iv = pl.program_id(1)

    @pl.when(iv == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        t_scr[:] = jnp.zeros_like(t_scr)

    h = h_ref[0]                                   # [bn, H]
    w = w_ref[0]                                   # [bv, H]
    s = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if b_ref is not None:
        s = s + b_ref[:]                           # [1, bv]
    col = iv * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    # np.float32 scalars in kernel jnp.where: weak-f64 scalar converts
    # recurse Mosaic lowering on some jax builds (see _causal_mask)
    s = jnp.where(col < vocab, s, np.float32(NEG_INF))  # ragged vocab tile

    y = y_ref[:].reshape(bn, 1)                    # [bn, 1] int32
    t_scr[:] += jnp.sum(jnp.where(col == y, s, np.float32(0.0)),
                        axis=-1, keepdims=True)

    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    l_scr[:] = l_scr[:] * jnp.exp(m_prev - m_new) \
        + jnp.sum(jnp.exp(s - m_new), axis=-1, keepdims=True)
    m_scr[:] = m_new

    @pl.when(iv == nv - 1)
    def _flush():
        lse = m_scr[:] + jnp.log(jnp.maximum(l_scr[:], 1e-30))
        y2 = y_ref[:].reshape(bn, 1)
        valid = y2 != ignore
        loss_ref[:] = jnp.where(valid, lse - t_scr[:],
                                np.float32(0.0)).reshape(loss_ref.shape)
        lse_ref[:] = lse.reshape(lse_ref.shape)


def _fwd(h, w, b, y, ignore, bn, bv, vocab):
    """`vocab` is the LOGICAL vocab; w may carry tile-padding rows beyond
    it (wrapper pads to a multiple of 128) which the col<vocab masks keep
    out of the softmax."""
    n, hd = h.shape
    v_rows = w.shape[0]
    nv = pl.cdiv(v_rows, bv)
    args = [h.reshape(1, n, hd), w.reshape(1, v_rows, hd)]
    in_specs = [
        pl.BlockSpec((1, bn, hd), lambda i, j: (_Z, i, _Z)),
        pl.BlockSpec((1, bv, hd), lambda i, j: (_Z, j, _Z)),
    ]
    if b is not None:
        args.append(b.reshape(1, v_rows))
        in_specs.append(pl.BlockSpec((1, bv), lambda i, j: (_Z, j)))
    args.append(y.reshape(1, n))
    in_specs.append(pl.BlockSpec((1, bn), lambda i, j: (_Z, i)))

    opts = dict(bn=bn, bv=bv, nv=nv, vocab=vocab, ignore=ignore)
    if b is not None:
        kernel = functools.partial(_ce_fwd_kernel, **opts)
    else:
        def kernel(hr, wr, yr, lo, ls, m, l, t):  # noqa: E741
            return _ce_fwd_kernel(hr, wr, None, yr, lo, ls, m, l, t, **opts)
        # stamped into the lowered custom call; hlo_evidence greps for it
        kernel.__name__ = _ce_fwd_kernel.__name__

    loss, lse = pl.pallas_call(
        kernel,
        grid=(n // bn, nv),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, bn), lambda i, j: (_Z, i)),
                   pl.BlockSpec((1, bn), lambda i, j: (_Z, i))],
        out_shape=[jax.ShapeDtypeStruct((1, n), jnp.float32),
                   jax.ShapeDtypeStruct((1, n), jnp.float32)],
        scratch_shapes=[_vmem((bn, 1), jnp.float32),
                        _vmem((bn, 1), jnp.float32),
                        _vmem((bn, 1), jnp.float32)],
        compiler_params=_cparams("parallel", "arbitrary"),
        interpret=_interpret(),
    )(*args)
    return loss.reshape(n), lse.reshape(n)


# --------------------------------------------------------------------------
# backward: dlogits = (softmax - onehot(y)) * g   (0 for ignored rows)
# --------------------------------------------------------------------------

def _ds_tile(h, w, b_ref, y, lse, g, iv, bn, bv, vocab, ignore):
    """Recompute one [bn, bv] tile of dlogits in f32."""
    s = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if b_ref is not None:
        s = s + b_ref[:]
    col = iv * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    p = jnp.exp(jnp.where(col < vocab, s, np.float32(NEG_INF)) - lse)
    # (col == y).astype, NOT jnp.where(col == y, 1.0, 0.0): scalar-scalar
    # where defaults to f64 under jax_enable_x64 and Mosaic aborts on any
    # 64-bit kernel value (layout.h bitwidth check)
    ds = p - (col == y).astype(jnp.float32)
    return ds * jnp.where(y != ignore, g, np.float32(0.0))  # [bn, bv] f32


def _ce_bwd_dh_kernel(h_ref, w_ref, b_ref, y_ref, lse_ref, g_ref, dh_ref,
                      dh_scr, *, bn, bv, nv, vocab, ignore):
    iv = pl.program_id(1)

    @pl.when(iv == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)

    h, w = h_ref[0], w_ref[0]
    y = y_ref[:].reshape(bn, 1)
    lse = lse_ref[:].reshape(bn, 1)
    g = g_ref[:].reshape(bn, 1)
    ds = _ds_tile(h, w, b_ref, y, lse, g, iv, bn, bv, vocab, ignore)
    # zero the ragged tile's out-of-range w rows: they're uninitialized
    # padding, and 0 * garbage in the contraction would poison dh.
    # The zero must be a strong scalar of w's dtype: a weak `0` promotes
    # to a weak-f32 scalar whose convert loops Mosaic's lowering forever
    row = iv * bv + jax.lax.broadcasted_iota(jnp.int32, (bv, 1), 0)
    wm = jnp.where(row < vocab, w, jnp.zeros((), w.dtype))
    dh_scr[:] += jax.lax.dot_general(ds.astype(w.dtype), wm,
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(iv == nv - 1)
    def _flush():
        dh_ref[0] = dh_scr[:].astype(dh_ref.dtype)


def _ce_bwd_dw_kernel(h_ref, w_ref, b_ref, y_ref, lse_ref, g_ref,
                      dw_ref, db_ref, dw_scr, db_scr,
                      *, bn, bv, nn_, vocab, ignore, with_bias):
    iv, i_n = pl.program_id(1), pl.program_id(2)

    @pl.when(i_n == 0)
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    h, w = h_ref[0], w_ref[0]
    y = y_ref[:].reshape(bn, 1)
    lse = lse_ref[:].reshape(bn, 1)
    g = g_ref[:].reshape(bn, 1)
    ds = _ds_tile(h, w, b_ref, y, lse, g, iv, bn, bv, vocab, ignore)
    # dW[v,:] += ds^T @ h  (contract over tokens)
    dw_scr[:] += jax.lax.dot_general(ds.astype(h.dtype), h,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
    if with_bias:
        db_scr[:] += jnp.sum(ds, axis=0, keepdims=True)

    @pl.when(i_n == nn_ - 1)
    def _flush():
        dw_ref[0] = dw_scr[:].astype(dw_ref.dtype)
        if with_bias:
            db_ref[:] = db_scr[:].astype(db_ref.dtype)


def _bwd(h, w, b, y, lse, g, ignore, bn, bv, vocab):
    n, hd = h.shape
    v_rows = w.shape[0]
    nv = pl.cdiv(v_rows, bv)
    nn_ = n // bn
    h3 = h.reshape(1, n, hd)
    w3 = w.reshape(1, v_rows, hd)
    y2 = y.reshape(1, n)
    lse2 = lse.reshape(1, n)
    g2 = g.astype(jnp.float32).reshape(1, n)
    base_args = [h3, w3] + ([b.reshape(1, v_rows)] if b is not None else []) \
        + [y2, lse2, g2]

    def base_specs(ij_h, ij_w, ij_b, ij_n):
        specs = [pl.BlockSpec((1, bn, hd), ij_h),
                 pl.BlockSpec((1, bv, hd), ij_w)]
        if b is not None:
            specs.append(pl.BlockSpec((1, bv), ij_b))
        specs += [pl.BlockSpec((1, bn), ij_n)] * 3
        return specs

    # ---- dh: grid (n/bn, nv), vocab tiles innermost ----------------------
    opts = dict(bn=bn, bv=bv, nv=nv, vocab=vocab, ignore=ignore)
    if b is not None:
        dh_kernel = functools.partial(_ce_bwd_dh_kernel, **opts)
    else:
        def dh_kernel(hr, wr, yr, lr, gr, dhr, scr):
            return _ce_bwd_dh_kernel(hr, wr, None, yr, lr, gr, dhr, scr,
                                     **opts)
        dh_kernel.__name__ = _ce_bwd_dh_kernel.__name__

    dh = pl.pallas_call(
        dh_kernel,
        grid=(nn_, nv),
        in_specs=base_specs(lambda i, j: (_Z, i, _Z), lambda i, j: (_Z, j, _Z),
                            lambda i, j: (_Z, j), lambda i, j: (_Z, i)),
        out_specs=pl.BlockSpec((1, bn, hd), lambda i, j: (_Z, i, _Z)),
        out_shape=jax.ShapeDtypeStruct((1, n, hd), h.dtype),
        scratch_shapes=[_vmem((bn, hd), jnp.float32)],
        compiler_params=_cparams("parallel", "arbitrary"),
        interpret=_interpret(),
    )(*base_args).reshape(n, hd)

    # ---- dw/db: grid (1, nv, n/bn), token blocks innermost ---------------
    wopts = dict(bn=bn, bv=bv, nn_=nn_, vocab=vocab, ignore=ignore,
                 with_bias=b is not None)
    if b is not None:
        dw_kernel = functools.partial(_ce_bwd_dw_kernel, **wopts)
    else:
        def dw_kernel(hr, wr, yr, lr, gr, dwr, dbr, ws, bs):
            return _ce_bwd_dw_kernel(hr, wr, None, yr, lr, gr, dwr, dbr,
                                     ws, bs, **wopts)
        dw_kernel.__name__ = _ce_bwd_dw_kernel.__name__

    dw, db = pl.pallas_call(
        dw_kernel,
        grid=(1, nv, nn_),
        in_specs=base_specs(
            lambda z, j, i: (_Z, i, _Z), lambda z, j, i: (_Z, j, _Z),
            lambda z, j, i: (_Z, j), lambda z, j, i: (_Z, i)),
        out_specs=[pl.BlockSpec((1, bv, hd), lambda z, j, i: (_Z, j, _Z)),
                   pl.BlockSpec((1, bv), lambda z, j, i: (_Z, j))],
        out_shape=[jax.ShapeDtypeStruct((1, v_rows, hd), w.dtype),
                   jax.ShapeDtypeStruct((1, v_rows), jnp.float32)],
        scratch_shapes=[_vmem((bv, hd), jnp.float32),
                        _vmem((1, bv), jnp.float32)],
        compiler_params=_cparams("parallel", "parallel", "arbitrary"),
        interpret=_interpret(),
    )(*base_args)
    dw = dw.reshape(v_rows, hd)
    db_out = None if b is None else db.reshape(v_rows).astype(
        b.dtype if hasattr(b, "dtype") else jnp.float32)
    return dh, dw, db_out


# --------------------------------------------------------------------------
# public op
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _fused_ce(h, w, b, y, ignore, bn, bv, vocab):
    loss, _ = _fwd(h, w, b, y, ignore, bn, bv, vocab)
    return loss


def _fused_ce_fwd(h, w, b, y, ignore, bn, bv, vocab):
    loss, lse = _fwd(h, w, b, y, ignore, bn, bv, vocab)
    return loss, (h, w, b, y, lse)


def _fused_ce_bwd(ignore, bn, bv, vocab, res, g):
    h, w, b, y, lse = res
    dh, dw, db = _bwd(h, w, b, y, lse, g, ignore, bn, bv, vocab)
    return dh, dw, db, None


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def supported(n: int, hidden: int, vocab: int) -> bool:
    """Any vocab size works — the wrapper pads the weight rows up to a
    multiple of 128 (lane tile) and masks the padding out of the softmax,
    so a 30522-row BERT head is as kernel-eligible as a 30720-row one."""
    return _pick(n, 512) is not None and hidden % 8 == 0 and vocab >= 1


def _pick_blocks(n, v_rows, hd, dtype, with_bias, measure_builder):
    """(bn, bv) resolution: FLAGS_fused_ce_block_* overrides, then the
    autotune table, then the static heuristic (512/512)."""
    from ...core import flags as _flags
    from . import autotune
    bn_cfg = int(_flags.flag("FLAGS_fused_ce_block_n") or 0)
    bv_cfg = int(_flags.flag("FLAGS_fused_ce_block_v") or 0)
    bn_default = _pick(n, bn_cfg or 512)
    bv_default = bv_cfg or next(x for x in (512, 256, 128)
                                if v_rows % x == 0)
    if bn_cfg or bv_cfg:
        return bn_default, min(bv_default, v_rows)
    cands = [(bn, bv)
             for bn in (512, 256, 128) if n % bn == 0
             for bv in (512, 256, 128) if v_rows % bv == 0]
    if not cands:
        return bn_default, min(bv_default, v_rows)
    return autotune.lookup(
        "fused_ce",
        (autotune.bucket(n), autotune.bucket(v_rows), hd, int(with_bias)),
        dtype, cands, measure_builder(), (bn_default, bv_default))


def fused_linear_cross_entropy(hidden, weight, bias, labels,
                               ignore_index=-100):
    """Per-token CE of `hidden @ weight^T + bias` against `labels`, without
    materializing the [n_tokens, vocab] logits in HBM.

    hidden: [n, H] (bf16/f32); weight: [vocab, H] (tied-embedding layout);
    bias: [vocab] or None; labels: [n] int. Returns f32 [n] losses, 0 where
    labels == ignore_index. Reduce (mean over valid) in the caller.

    Non-tile-aligned vocab sizes are padded here (weight rows to a
    multiple of 128, zeros) and masked in-kernel by the logical `vocab`;
    padded dW/db rows come back ~0 and jnp.pad's vjp slices them off.
    """
    from ...core import flags as _flags
    n, hd = hidden.shape
    vocab = weight.shape[0]
    bn_target = int(_flags.flag("FLAGS_fused_ce_block_n") or 0) or 512
    if _pick(n, bn_target) is None:
        raise ValueError(f"fused CE: n_tokens {n} has no block factor")
    v_pad = _ceil_to(vocab, 128)
    if v_pad != vocab:
        weight = jnp.pad(weight, ((0, v_pad - vocab), (0, 0)))
        if bias is not None:
            bias = jnp.pad(bias, (0, v_pad - vocab))
    labels = labels.astype(jnp.int32)

    def measure_builder():
        def measure(params):
            from . import autotune
            bn_, bv_ = params
            hz = jnp.zeros((n, hd), hidden.dtype)
            wz = jnp.zeros((v_pad, hd), weight.dtype)
            bz = None if bias is None else jnp.zeros((v_pad,), bias.dtype)
            yz = jnp.zeros((n,), jnp.int32)
            fn = jax.jit(lambda a, b_, c: _fused_ce(
                a, b_, bz, c, int(ignore_index), bn_, bv_, vocab))
            return autotune.time_thunk(lambda: fn(hz, wz, yz))
        return measure

    bn, bv = _pick_blocks(n, v_pad, hd, str(hidden.dtype),
                          bias is not None, measure_builder)
    return _fused_ce(hidden, weight, bias, labels, int(ignore_index),
                     bn, min(bv, v_pad), vocab)
