"""Shape-keyed block-size autotuning for the Pallas kernel tier.

Replaces the static largest-divisor heuristics (`_pick_block` in
flash_attention.py, `_pick` in fused_ce.py) with a measured table: the
first call at a new (kernel, shape-bucket, dtype, backend) key times the
candidate block configurations on the real inputs and records the winner.
This is the TPU analog of the reference's runtime kernel selection
(operators/jit/gen_base.cc JitCodeCreator picks an implementation per
shape-key and caches it in a per-op map) — except the "implementations"
here are grid/block parametrizations of one Pallas kernel, and the cost
model is a wall-clock measurement instead of a heuristic table.

Resolution order at a call site (all kernels follow it):

1. explicit `FLAGS_*_block_*` flag overrides — always win, never measured;
2. in-process table hit;
3. disk cache hit (`PADDLE_TPU_PALLAS_AUTOTUNE_CACHE=<path>.json`), so a
   fleet job pays the measurement once per shape family, not once per
   process;
4. measure-and-record — only when measuring is meaningful (compiled TPU
   backend, or `FLAGS_pallas_autotune_force` for interpreter-mode tests);
5. otherwise the caller's heuristic default (what `_pick_block` chose
   before this module existed).

Shape keys are *bucketed* (next power of two) so s=1000 and s=1024 share
an entry — the measured optimum is a property of the magnitude, not the
exact length, and an exact-shape table would re-measure every ragged
batch.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["bucket", "lookup", "clear", "table_snapshot", "cache_path"]

_LOCK = threading.RLock()
_TABLE = {}          # key tuple -> params tuple (measured winners only)
_LOADED_PATH = None  # disk cache file already merged into _TABLE


def bucket(n: int) -> int:
    """Next power of two >= n (shape-family key, not the exact length)."""
    n = int(n)
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def cache_path():
    return os.environ.get("PADDLE_TPU_PALLAS_AUTOTUNE_CACHE") or None


def _key(kernel, shape_key, dtype):
    import jax
    return (str(kernel), tuple(int(x) for x in shape_key), str(dtype),
            jax.default_backend())


def _key_str(key):
    kernel, shape_key, dtype, backend = key
    return "|".join([kernel, ",".join(str(x) for x in shape_key), dtype,
                     backend])


def _load_disk_locked():
    """Merge the disk cache into the in-process table (once per path)."""
    global _LOADED_PATH
    path = cache_path()
    if path is None or path == _LOADED_PATH:
        return
    _LOADED_PATH = path
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        return
    except Exception:
        return  # a corrupt cache is a missed optimization, never an error
    for ks, entry in data.get("entries", {}).items():
        parts = ks.split("|")
        if len(parts) != 4:
            continue
        kernel, shape_s, dtype, backend = parts
        shape_key = tuple(int(x) for x in shape_s.split(",") if x)
        _TABLE.setdefault((kernel, shape_key, dtype, backend),
                          tuple(entry["params"]))


def _save_disk_locked(key, params, seconds):
    path = cache_path()
    if path is None:
        return
    # serialize concurrent fleet writers on a sidecar lock: without it the
    # read-modify-write below is last-writer-wins and a simultaneously
    # measured entry from another process is silently dropped (that
    # process' measurement gets re-paid by everyone else forever)
    lock_f = None
    try:
        try:
            import fcntl
            lock_f = open(f"{path}.lock", "w")
            fcntl.flock(lock_f, fcntl.LOCK_EX)
        except Exception:
            lock_f = None  # locking is best-effort (e.g. non-POSIX fs)
        try:
            with open(path) as f:
                data = json.load(f)
        except Exception:
            data = {"version": 1, "entries": {}}
        data.setdefault("entries", {})[_key_str(key)] = {
            "params": list(params), "seconds": seconds}
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
            os.replace(tmp, path)  # atomic: readers see old or new
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    finally:
        if lock_f is not None:
            lock_f.close()


def _should_measure():
    import jax

    from ...core import flags as _flags
    if not _flags.flag("FLAGS_pallas_autotune"):
        return False
    if _flags.flag("FLAGS_pallas_autotune_force"):
        return True  # tests: exercise the measuring path off-TPU
    # off-TPU the kernels run interpreted — timings there say nothing
    # about MXU/VMEM behavior, so the heuristic default wins
    return jax.default_backend() == "tpu"


def lookup(kernel, shape_key, dtype, candidates, measure, default):
    """Resolve block params for one kernel call.

    kernel: short name ("flash_fwd", "fused_ce", "decode_attention");
    shape_key: tuple of *bucketed* ints describing the shape family;
    candidates: list of param tuples worth trying (caller guarantees each
    is legal for the real — unbucketed — shape); measure: params ->
    seconds (compile + run; exceptions disqualify the candidate);
    default: params returned when measuring is off.
    """
    from ...core import monitor
    key = _key(kernel, shape_key, dtype)
    with _LOCK:
        _load_disk_locked()
        hit = _TABLE.get(key)
    if hit is not None:
        # the disk cache may hold a candidate the current call can't use
        # (different divisibility inside one bucket): fall back if so
        if hit in [tuple(c) for c in candidates]:
            return hit
        return default
    if not _should_measure() or measure is None or len(candidates) <= 1:
        return default
    best, best_t = None, None
    for cand in candidates:
        try:
            t = measure(tuple(cand))
        except Exception:
            monitor.stat_add(f"pallas.autotune.failed_candidate.{kernel}")
            continue
        if t is not None and (best_t is None or t < best_t):
            best, best_t = tuple(cand), float(t)
    if best is None:
        return default
    with _LOCK:
        _TABLE[key] = best
        _save_disk_locked(key, best, best_t)
    monitor.stat_add(f"pallas.autotune.measured.{kernel}")
    return best


def time_thunk(thunk, repeats=3):
    """Measure a jitted thunk: one untimed call (compile + warmup), then
    best-of-`repeats` wall clock. Returns seconds."""
    import jax
    jax.block_until_ready(thunk())
    best = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(thunk())
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best
    return best


def clear():
    """Drop the in-process table (tests)."""
    global _LOADED_PATH
    with _LOCK:
        _TABLE.clear()
        _LOADED_PATH = None


def table_snapshot():
    with _LOCK:
        return dict(_TABLE)
