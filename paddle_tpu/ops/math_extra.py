"""Extended math / manipulation op families.

Coverage push toward the reference's ~830 op families (reference
operators/: activation_op.cc, cum_op.cc, index_add_op, put_along_axis_op,
histogram_op, searchsorted (bucketize), renorm_op, lgamma/digamma/
polygamma ops, i0/i1 ops, unfold/fold (im2col, operators/math/im2col.cc),
cov/corrcoef (python/paddle/tensor/linalg.py), cdist/pdist, lu/lu_unpack,
cholesky_solve, random ops standard_gamma/binomial/log_normal). Each op is
one jnp/lax lowering behind `defop`, so it serves eager, jitted, and
static frontends alike.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import rng as _rng
from ._dispatch import defop

__all__ = [
    "polygamma", "gammaln", "igamma", "igammac", "trapezoid",
    "cumulative_trapezoid", "vander", "nextafter", "hypot", "copysign",
    "signbit", "sinc", "ldexp", "renorm", "frexp", "i0", "i0e", "i1",
    "i1e", "fix", "cummax", "cummin", "nanmedian", "nanquantile",
    "bucketize", "index_add", "index_fill", "index_put", "masked_scatter",
    "diagonal_scatter", "select_scatter", "slice_scatter", "unflatten",
    "view_as", "cdist", "pdist", "corrcoef", "cov", "cholesky_solve",
    "lu", "lu_unpack", "fold", "histogramdd", "standard_gamma", "binomial",
    "log_normal", "channel_shuffle", "pixel_unshuffle", "affine_grid",
    "grid_sample",
]


# -- special functions ------------------------------------------------------

@defop
def polygamma(x, n=1):
    return jax.scipy.special.polygamma(n, x)


@defop
def gammaln(x):
    return jax.scipy.special.gammaln(x)


@defop
def igamma(a, x):
    return jax.scipy.special.gammainc(a, x)


@defop
def igammac(a, x):
    return jax.scipy.special.gammaincc(a, x)


@defop
def i0(x):
    return jax.scipy.special.i0(x)


@defop
def i0e(x):
    return jax.scipy.special.i0e(x)


@defop
def i1(x):
    return jax.scipy.special.i1(x)


@defop
def i1e(x):
    return jax.scipy.special.i1e(x)


@defop
def sinc(x):
    return jnp.sinc(x)


# -- elementwise ------------------------------------------------------------

@defop
def nextafter(x, y):
    return jnp.nextafter(x, y)


@defop
def hypot(x, y):
    return jnp.hypot(x, y)


@defop
def copysign(x, y):
    return jnp.copysign(x, y)


@defop
def signbit(x):
    return jnp.signbit(x)


@defop
def ldexp(x, y):
    return jnp.ldexp(x, y)


@defop
def fix(x):
    return jnp.trunc(x)


@defop
def frexp(x):
    return jnp.frexp(x)


@defop
def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


@defop
def renorm(x, p, axis, max_norm):
    axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12),
                      1.0)
    return x * scale


# -- reductions / scans -----------------------------------------------------

@defop
def trapezoid(y, x=None, dx=1.0, axis=-1):
    return jnp.trapezoid(y, x=x, dx=dx, axis=axis)


@defop
def cumulative_trapezoid(y, x=None, dx=1.0, axis=-1):
    y = jnp.moveaxis(y, axis, -1)
    if x is not None:
        x = jnp.moveaxis(jnp.broadcast_to(x, y.shape), axis, -1) \
            if jnp.ndim(x) > 1 else x
        d = jnp.diff(x, axis=-1)
    else:
        d = dx
    avg = (y[..., 1:] + y[..., :-1]) * 0.5
    out = jnp.cumsum(avg * d, axis=-1)
    return jnp.moveaxis(out, -1, axis)


@defop
def cummax(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = lax.cummax(x, axis=axis)
    eq = x == vals
    n = x.shape[axis]
    idx_in = jnp.arange(n).reshape([-1 if i == axis else 1
                                    for i in range(x.ndim)])
    idx = lax.cummax(jnp.where(eq, idx_in, 0), axis=axis)
    return vals, idx.astype(jnp.int64)


@defop
def cummin(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = lax.cummin(x, axis=axis)
    eq = x == vals
    n = x.shape[axis]
    idx_in = jnp.arange(n).reshape([-1 if i == axis else 1
                                    for i in range(x.ndim)])
    idx = lax.cummax(jnp.where(eq, idx_in, 0), axis=axis)
    return vals, idx.astype(jnp.int64)


@defop
def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


@defop
def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x, q, axis=axis, keepdims=keepdim)


@defop
def histogramdd(x, bins=10, ranges=None, weights=None, density=False):
    return jnp.histogramdd(x, bins=bins, range=ranges, weights=weights,
                           density=density)


# -- indexing ---------------------------------------------------------------

@defop
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@defop
def index_add(x, index, axis, value):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].add(value)


@defop
def index_fill(x, index, axis, value):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(value)


@defop
def index_put(x, indices, value, accumulate=False):
    ref = x.at[tuple(indices)]
    return ref.add(value) if accumulate else ref.set(value)


@defop
def masked_scatter(x, mask, value):
    flat_val = value.reshape(-1)
    m = jnp.broadcast_to(mask, x.shape).reshape(-1)
    # position of each True among the mask (clamped gather for False)
    pos = jnp.cumsum(m) - 1
    take = flat_val[jnp.clip(pos, 0, flat_val.shape[0] - 1)]
    return jnp.where(m, take, x.reshape(-1)).reshape(x.shape)


@defop
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    n = min(x.shape[axis1], x.shape[axis2])
    i = jnp.arange(y.shape[-1])
    r = i - min(offset, 0)
    c = i + max(offset, 0)
    idx = [slice(None)] * x.ndim
    idx[axis1] = r
    idx[axis2] = c
    return x.at[tuple(idx)].set(jnp.moveaxis(y, -1, 0)
                                if x.ndim > 2 else y)


@defop
def select_scatter(x, y, axis, index):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(y)


@defop
def slice_scatter(x, y, axes, starts, ends, strides=None):
    strides = strides or [1] * len(axes)
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return x.at[tuple(idx)].set(y)


@defop
def unflatten(x, axis, shape):
    axis = axis % x.ndim
    new = list(x.shape[:axis]) + list(shape) + list(x.shape[axis + 1:])
    return x.reshape(new)


def view_as(x, other):
    from . import reshape
    return reshape(x, list(other.shape))


# -- distances / statistics -------------------------------------------------

@defop
def cdist(x, y, p=2.0):
    d = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.maximum(jnp.sum(d * d, -1), 0.0))
    return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)


@defop
def pdist(x, p=2.0):
    n = x.shape[0]
    d = x[:, None, :] - x[None, :, :]
    if p == 2.0:
        full = jnp.sqrt(jnp.maximum(jnp.sum(d * d, -1), 0.0))
    else:
        full = jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)
    iu = jnp.triu_indices(n, k=1)
    return full[iu]


@defop
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@defop
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


# -- linalg -----------------------------------------------------------------

@defop
def cholesky_solve(x, y, upper=False):
    # solve A X = B given y = chol factor of A
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@defop
def lu(x, pivot=True):
    lu_mat, piv = jax.scipy.linalg.lu_factor(x)
    return lu_mat, (piv + 1).astype(jnp.int32)  # 1-based like the reference


@defop
def lu_unpack(lu_mat, pivots, unpack_ludata=True, unpack_pivots=True):
    n = lu_mat.shape[-2]
    low = jnp.tril(lu_mat, -1) + jnp.eye(n, lu_mat.shape[-1],
                                         dtype=lu_mat.dtype)
    up = jnp.triu(lu_mat)
    piv = pivots.astype(jnp.int32) - 1
    perm = jnp.arange(n, dtype=jnp.int32)

    def body(i, p):
        j = piv[i]
        pi, pj = p[i], p[j]
        return p.at[i].set(pj).at[j].set(pi)

    perm = lax.fori_loop(0, piv.shape[-1], body, perm)
    pmat = jnp.eye(n, dtype=lu_mat.dtype)[perm].T
    return pmat, low, up


# -- im2col inverse ---------------------------------------------------------

@defop
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im (reference operators/math/im2col.cc inverse; unfold exists
    in ops/conv.py). x: [N, C*kh*kw, L] -> [N, C, H, W]."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings)
    dh, dw = pair(dilations)
    H, W = pair(output_sizes)
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    oh = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    x = x.reshape(n, c, kh, kw, oh, ow)
    out = jnp.zeros((n, c, H + 2 * ph, W + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            hs = i * dh
            ws = j * dw
            out = out.at[:, :, hs:hs + oh * sh:sh,
                         ws:ws + ow * sw:sw].add(x[:, :, i, j])
    return out[:, :, ph:ph + H, pw:pw + W]


# -- random -----------------------------------------------------------------

@defop
def standard_gamma(alpha):
    return jax.random.gamma(_rng.next_key(), alpha)


@defop
def binomial(count, prob):
    return jax.random.binomial(_rng.next_key(), count, prob)


@defop
def log_normal(mean=1.0, std=2.0, shape=None):
    shape = shape or ()
    return jnp.exp(mean + std * jax.random.normal(_rng.next_key(),
                                                  tuple(shape)))


@defop
def channel_shuffle(x, groups, data_format="NCHW"):
    """reference channel_shuffle_op.cc."""
    if data_format == "NCHW":
        n, c, h, w = x.shape
        return x.reshape(n, groups, c // groups, h, w) \
                .transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
    n, h, w, c = x.shape
    return x.reshape(n, h, w, groups, c // groups) \
            .transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)


@defop
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    """reference pixel_unshuffle_op.cc (inverse of pixel_shuffle)."""
    r = downscale_factor
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    return x.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r,
                                                 w // r)


@defop
def affine_grid(theta, out_shape, align_corners=True):
    """reference affine_grid_op.cc: 2D affine sampling grid from theta
    [N, 2, 3] for an output [N, C, H, W] -> grid [N, H, W, 2] (x, y) in
    [-1, 1] normalized coordinates."""
    n, _, H, W = [int(s) for s in out_shape]

    def lin(m):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, m)
        return (jnp.arange(m, dtype=jnp.float32) * 2 + 1) / m - 1.0

    ys, xs = lin(H), lin(W)
    xg, yg = jnp.meshgrid(xs, ys)                        # [H, W]
    ones = jnp.ones_like(xg)
    base = jnp.stack([xg, yg, ones], axis=-1)            # [H, W, 3]
    return jnp.einsum("hwk,njk->nhwj", base, theta.astype(jnp.float32))


@defop
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """reference grid_sampler_op.cc: sample x [N,C,H,W] at grid
    [N,Ho,Wo,2] (x,y in [-1,1])."""
    n, c, H, W = x.shape

    def unnorm(g, size):
        if align_corners:
            return (g + 1) * (size - 1) / 2
        return ((g + 1) * size - 1) / 2

    gx = unnorm(grid[..., 0].astype(jnp.float32), W)     # [N, Ho, Wo]
    gy = unnorm(grid[..., 1].astype(jnp.float32), H)
    if padding_mode == "border":
        gx = jnp.clip(gx, 0, W - 1)
        gy = jnp.clip(gy, 0, H - 1)
    if mode == "nearest":
        ix = jnp.clip(jnp.round(gx), 0, W - 1).astype(jnp.int32)
        iy = jnp.clip(jnp.round(gy), 0, H - 1).astype(jnp.int32)
        valid = ((gx >= -0.5) & (gx <= W - 0.5)
                 & (gy >= -0.5) & (gy <= H - 0.5)) \
            if padding_mode == "zeros" else jnp.ones_like(gx, bool)
        out = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, iy, ix)
        return out * valid[:, None].astype(x.dtype)

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def tap(ix, iy):
        inb = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
        cx = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
        cy = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
        v = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, cy, cx)
        if padding_mode == "zeros":
            v = v * inb[:, None].astype(x.dtype)
        return v

    v00 = tap(x0, y0)
    v01 = tap(x0 + 1, y0)
    v10 = tap(x0, y0 + 1)
    v11 = tap(x0 + 1, y0 + 1)
    wxe = wx[:, None].astype(x.dtype)
    wye = wy[:, None].astype(x.dtype)
    return (v00 * (1 - wxe) * (1 - wye) + v01 * wxe * (1 - wye)
            + v10 * (1 - wxe) * wye + v11 * wxe * wye)


# -- round-4 widening: reference operators/ families still absent ----------
# (addmm_op.cc, trace, diag_embed, allclose_op.cc, multiplex_op.cc,
#  cos_sim_op.cc, bilinear_tensor_product_op.cc, mv, squared_l2_norm_op.cc,
#  squared_l2_distance_op.cc, l1_norm_op.cc, clip_by_norm_op.cc)

__all__ += ["addmm", "trace", "diag_embed", "allclose", "multiplex",
            "cos_sim", "bilinear_tensor_product", "mv", "squared_l2_norm",
            "squared_l2_distance", "l1_norm", "clip_by_norm"]


@defop
def addmm(input, x, y, beta=1.0, alpha=1.0):  # noqa: A002
    return beta * input + alpha * (x @ y)


@defop
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@defop
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1] + abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    out = base.at[..., r, c].set(x)
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
    return out


@defop
def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(x, y, rtol=float(rtol), atol=float(atol),
                        equal_nan=equal_nan)


@defop
def multiplex(inputs, index):
    inputs = [getattr(t, "_value", t) for t in inputs]
    stacked = jnp.stack(inputs, axis=0)              # [k, n, ...]
    idx = jnp.reshape(index, (-1,)).astype(jnp.int32)
    rows = jnp.arange(stacked.shape[1], dtype=jnp.int32)
    return stacked[idx, rows]


@defop
def cos_sim(x, y, eps=1e-8):
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1))
    dot_ = jnp.sum(x * y, axis=-1)
    return dot_ / jnp.maximum(xn * yn, eps)


@defop
def bilinear_tensor_product(x, y, weight, bias=None):
    # weight [K, M, N]; out[b, k] = x[b] @ W_k @ y[b]
    out = jnp.einsum("bm,kmn,bn->bk", x, weight, y)
    if bias is not None:
        out = out + bias
    return out


@defop
def mv(x, vec):
    return x @ vec


@defop
def squared_l2_norm(x):
    return jnp.sum(jnp.square(x))


@defop
def squared_l2_distance(x, y):
    d = x - y
    return jnp.sum(jnp.square(d), axis=tuple(range(1, d.ndim)))


@defop
def l1_norm(x):
    return jnp.sum(jnp.abs(x))


@defop
def clip_by_norm(x, max_norm):
    norm = jnp.sqrt(jnp.maximum(jnp.sum(jnp.square(x)), 1e-12))
    return x * jnp.minimum(1.0, max_norm / norm).astype(x.dtype)


# -- metric-ish ops (reference operators/: edit_distance_op.cc,
#    mean_iou_op.cc, chunk_eval_op.cc is in metric/) -----------------------

__all__ += ["edit_distance", "mean_iou"]


def edit_distance(hyps, refs, normalized=True):
    """reference edit_distance_op.cc: Levenshtein distance per sequence
    pair. Accepts lists of sequences / RaggedTensor; host DP (the
    reference's kernel is likewise a CPU loop). Returns (distances [n,1],
    sequence_num)."""
    import numpy as np

    from ..core.ragged import RaggedTensor
    from ..core.tensor import Tensor

    def rows(x):
        if isinstance(x, RaggedTensor):
            return [np.asarray(r) for r in x.to_list()]
        if isinstance(x, Tensor):
            return [np.asarray(x._value[i]) for i in range(x.shape[0])]
        return [np.asarray(r) for r in x]

    H, R = rows(hyps), rows(refs)
    out = []
    for h, r in zip(H, R):
        m, n = len(h), len(r)
        dp = np.arange(n + 1, dtype=np.float64)
        for i in range(1, m + 1):
            prev = dp.copy()
            dp[0] = i
            for j in range(1, n + 1):
                cost = 0 if h[i - 1] == r[j - 1] else 1
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1, prev[j - 1] + cost)
        d = dp[n]
        if normalized:
            d = d / max(n, 1)
        out.append(d)
    from ..core.tensor import to_tensor
    return to_tensor(np.asarray(out, np.float32).reshape(-1, 1)), len(out)


@defop
def mean_iou(input, label, num_classes):  # noqa: A002
    """reference mean_iou_op.cc: mean intersection-over-union across
    classes present in pred∪label. Returns (miou, out_wrong, out_correct)."""
    pred = input.reshape(-1).astype(jnp.int32)
    lab = label.reshape(-1).astype(jnp.int32)
    n = int(num_classes)
    correct = jnp.zeros((n,), jnp.int64).at[lab].add(
        (pred == lab).astype(jnp.int64))
    pred_cnt = jnp.zeros((n,), jnp.int64).at[pred].add(1)
    lab_cnt = jnp.zeros((n,), jnp.int64).at[lab].add(1)
    union = pred_cnt + lab_cnt - correct
    present = union > 0
    iou = jnp.where(present, correct / jnp.maximum(union, 1), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(present), 1)
    wrong = (pred_cnt - correct).astype(jnp.int32)
    return miou.astype(jnp.float32), wrong, correct.astype(jnp.int32)


# -- round-4 batch 4: industrial/CTR + misc reference families -------------
# (cvm_op.cc, hash_op.cc, batch_fc_op.cu, rank_attention_op.cu,
#  match_matrix_tensor_op.cc, fsp_op.cc, conv_shift_op.cc,
#  filter_by_instag_op.cc, fake_quantize_op.cc, chunk_eval_op.cc,
#  gru_unit_op.cc, lstm_unit_op.cc)

__all__ += ["cvm", "hash_bucket", "batch_fc", "rank_attention",
            "match_matrix_tensor", "fsp_matrix", "conv_shift",
            "filter_by_instag", "fake_quantize_abs_max",
            "fake_quantize_moving_average_abs_max",
            "fake_channel_wise_quantize_abs_max", "dequantize_abs_max",
            "chunk_eval", "gru_unit", "lstm_unit"]


@defop
def cvm(x, cvm_in=None, use_cvm=True):
    """reference cvm_op.cc (CTR show/click feature): x's first two columns
    are (show, click); use_cvm keeps them log-transformed, else drops."""
    show = jnp.log(x[:, 0:1] + 1.0)
    click = jnp.log(x[:, 1:2] + 1.0) - jnp.log(x[:, 0:1] + 1.0)
    rest = x[:, 2:]
    if use_cvm:
        return jnp.concatenate([show, click, rest], axis=1)
    return rest


@defop(version=2)
def hash_bucket(x, num_hash=1, mod_by=100000007):
    """reference hash_op.cc: ids -> num_hash bucket ids (multiplicative
    hashing with distinct seeds).

    version 2: buckets are masked non-negative before the modulo (v1
    could emit negative bucket ids on int64 wraparound); artifacts saved
    by this build refuse to load into v1 frameworks via program_serde's
    op-version check."""
    ids = x.astype(jnp.int64)
    seeds = jnp.asarray([(0x9E3779B1 * (i + 1)) | 1
                         for i in range(num_hash)], jnp.int64)
    h = ids[..., None] * seeds
    h = h ^ (h >> 16)
    # mask to non-negative rather than abs(): the int64 product can wrap
    # to INT64_MIN, where abs() stays negative and the modulo would yield
    # a negative bucket id
    return (h & jnp.int64(0x7FFFFFFFFFFFFFFF)) % mod_by


@defop
def batch_fc(x, w, bias=None):
    """reference batch_fc_op.cu: per-slot FC — x [slot, b, in],
    w [slot, in, out]."""
    out = jnp.einsum("sbi,sio->sbo", x, w)
    if bias is not None:
        out = out + bias[:, None]
    return out


@defop
def rank_attention(x, rank_offset, rank_param, max_rank=3):
    """reference rank_attention_op.cu (rank-aware CTR attention): each
    row picks the parameter block of its rank pair. rank_offset [n, 1+2k]
    with (ins_rank, (rank_i, index_i)...); simplified single-block form:
    out[i] = x[i] @ rank_param[block(i)] where block = ins_rank-1."""
    blk = jnp.clip(rank_offset[:, 0].astype(jnp.int32) - 1, 0,
                   rank_param.shape[0] - 1)
    return jnp.einsum("ni,nio->no", x, rank_param[blk])


@defop
def match_matrix_tensor(x, y, w):
    """reference match_matrix_tensor_op.cc: bilinear match
    x [n, lx, d], y [n, ly, d], w [d, t, d] -> [n, t, lx, ly]."""
    return jnp.einsum("nad,dte,nbe->ntab", x, w, y)


@defop
def fsp_matrix(x, y):
    """reference fsp_op.cc (distillation flow matrix):
    x [n, c1, h, w], y [n, c2, h, w] -> [n, c1, c2] = mean_hw outer."""
    n, c1, h, w = x.shape
    c2 = y.shape[1]
    return jnp.einsum("nahw,nbhw->nab", x, y) / (h * w)


@defop
def conv_shift(x, y):
    """reference conv_shift_op.cc (NTM circular convolution):
    x [b, m], y [b, n] (n odd, n<=m) -> circular correlation."""
    m = x.shape[1]
    n = y.shape[1]
    half = n // 2
    outs = []
    for j in range(n):
        shift = j - half
        outs.append(jnp.roll(x, -shift, axis=1) * y[:, j:j + 1])
    return sum(outs)


def filter_by_instag(x, ins_tag, filter_tag):
    """reference filter_by_instag_op.cc: keep rows whose tag set
    intersects filter_tag (eager: output size data-dependent). x rows
    align with ins_tag rows (list of per-row tag arrays or RaggedTensor).
    Returns (filtered_rows Tensor, kept row indices)."""
    import numpy as np

    from ..core.ragged import RaggedTensor
    from ..core.tensor import Tensor
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    tags = ins_tag.to_list() if isinstance(ins_tag, RaggedTensor) \
        else [np.asarray(t).reshape(-1) for t in ins_tag]
    fset = set(np.asarray(filter_tag).reshape(-1).tolist())
    keep = [i for i, t in enumerate(tags)
            if fset & set(np.asarray(t).tolist())]
    idx = jnp.asarray(np.asarray(keep, np.int64))
    from ._dispatch import wrap
    return wrap(xv[idx]), wrap(idx)


# ---- fake quantization family (reference fake_quantize_op.cc; the
# QAT/PTQ layer machinery in paddle_tpu.quantization builds on these) ----

@defop
def fake_quantize_abs_max(x, bit_length=8):
    """Returns (quantized-dequantized x, scale). STE handled by callers
    (quantization module wraps with custom_vjp)."""
    n = float(2 ** (bit_length - 1) - 1)
    scale = jnp.max(jnp.abs(x))
    q = jnp.round(x / jnp.maximum(scale, 1e-12) * n)
    return jnp.clip(q, -n, n) / n * scale, scale


@defop
def fake_quantize_moving_average_abs_max(x, in_state, bit_length=8,
                                         moving_rate=0.9):
    n = float(2 ** (bit_length - 1) - 1)
    cur = jnp.max(jnp.abs(x))
    state = moving_rate * in_state + (1 - moving_rate) * cur
    q = jnp.round(x / jnp.maximum(state, 1e-12) * n)
    return jnp.clip(q, -n, n) / n * state, state


@defop
def fake_channel_wise_quantize_abs_max(x, bit_length=8, quant_axis=0):
    n = float(2 ** (bit_length - 1) - 1)
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    q = jnp.round(x / jnp.maximum(scale, 1e-12) * n)
    return jnp.clip(q, -n, n) / n * scale, jnp.squeeze(scale)


@defop
def dequantize_abs_max(x, scale, max_range):
    return x.astype(jnp.float32) * scale / max_range


__all__ += ["fake_quantize_range_abs_max", "fake_quantize_dequantize_abs_max",
            "fake_quantize_dequantize_moving_average_abs_max",
            "fake_channel_wise_quantize_dequantize_abs_max",
            "fake_channel_wise_dequantize_max_abs", "fake_dequantize_max_abs",
            "tdm_child", "tdm_sampler"]


@defop
def fake_quantize_range_abs_max(x, in_scale, bit_length=8, window_size=10000,
                                is_test=False):
    """reference fake_quantize_op.cc range_abs_max: scale tracks the
    running max of per-batch abs maxima (window semantics collapse to a
    running max under jit — the window array is a CPU-loop artifact)."""
    n = float(2 ** (bit_length - 1) - 1)
    cur = jnp.max(jnp.abs(x))
    scale = in_scale if is_test else jnp.maximum(in_scale, cur)
    q = jnp.round(x / jnp.maximum(scale, 1e-12) * n)
    return jnp.clip(q, -n, n) / n * scale, scale


@defop
def fake_quantize_dequantize_abs_max(x, bit_length=8):
    """reference fake_quantize_dequantize composite — one shared kernel
    with fake_quantize_abs_max (the reference splits them only because
    its int8 path materializes the codes)."""
    return fake_quantize_abs_max.raw(x, bit_length)


@defop
def fake_quantize_dequantize_moving_average_abs_max(x, in_state,
                                                    bit_length=8,
                                                    moving_rate=0.9):
    return fake_quantize_moving_average_abs_max.raw(x, in_state,
                                                    bit_length, moving_rate)


@defop
def fake_channel_wise_quantize_dequantize_abs_max(x, bit_length=8,
                                                  quant_axis=0):
    return fake_channel_wise_quantize_abs_max.raw(x, bit_length, quant_axis)


@defop
def fake_channel_wise_dequantize_max_abs(x, scales, max_range=None,
                                         quant_axis=0, bit_length=8):
    """reference fake_dequantize_op.cc channel-wise: codes * scale/n per
    channel."""
    n = float(2 ** (bit_length - 1) - 1) if max_range is None \
        else float(max_range)
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    return x.astype(jnp.float32) * scales.reshape(shape) / n


@defop
def fake_dequantize_max_abs(x, scale, max_range):
    """Shared kernel with dequantize_abs_max (fake_dequantize_op.cc names
    the same math twice)."""
    return dequantize_abs_max.raw(x, scale, max_range)


@defop
def tdm_child(x, tree_info, child_nums):
    """reference tdm_child_op.cc (tree-based deep match): gather each
    node's children ids + leaf mask from the tree_info table
    (tree_info rows: [item_id, layer, parent, child_0..child_n-1])."""
    ids = x.astype(jnp.int32)
    info = tree_info.astype(jnp.int32)
    children = info[:, 3:3 + child_nums]
    ch = children[ids.reshape(-1)].reshape(ids.shape + (child_nums,))
    # a child is a leaf when its own child list is all zeros
    child_children = children[ch.reshape(-1)].reshape(
        ch.shape + (child_nums,))
    leaf_mask = ((ch != 0)
                 & (child_children == 0).all(-1)).astype(jnp.int32)
    return ch, leaf_mask


def tdm_sampler(x, travel_list, layer_list, neg_samples_num_list,
                layer_node_num_list, leaf_node_num, output_positive=True,
                seed=0):
    """reference tdm_sampler_op.cc: per tree layer, emit the positive
    node on each sample's root-to-leaf path plus uniform negatives from
    the same layer. Host-side sampler (data-prep op; matches the
    reference's CPU-only kernel). Returns (out, label, mask) stacked as
    [batch, sum(neg+pos per layer)]."""
    from ..core.tensor import Tensor
    rng = np.random.RandomState(seed or None)
    ids = np.asarray(x._value if isinstance(x, Tensor) else x,
                     np.int64).reshape(-1)
    travel = np.asarray(travel_list, np.int64)
    layers = [np.asarray(l, np.int64) for l in layer_list]
    outs, labels, masks = [], [], []
    for item in ids:
        row_o, row_l, row_m = [], [], []
        for li, (layer_nodes, n_neg) in enumerate(
                zip(layers, neg_samples_num_list)):
            pos = int(travel[item, li])
            if output_positive:
                row_o.append(pos)
                row_l.append(1)
                row_m.append(0 if pos == 0 else 1)
            cand = layer_nodes[layer_nodes != pos]
            # exactly n_neg entries per layer (reference pads with
            # mask=0 instead of emitting ragged rows)
            n_take = min(n_neg, len(cand))
            take = rng.choice(cand, size=n_take, replace=False) \
                if n_take else np.zeros(0, np.int64)
            for t in take:
                row_o.append(int(t))
                row_l.append(0)
                row_m.append(1)
            for _ in range(n_neg - n_take):
                row_o.append(0)
                row_l.append(0)
                row_m.append(0)
        outs.append(row_o)
        labels.append(row_l)
        masks.append(row_m)
    import jax.numpy as _jnp
    return (Tensor(_jnp.asarray(np.asarray(outs, np.int64)), _internal=True),
            Tensor(_jnp.asarray(np.asarray(labels, np.int64)),
                   _internal=True),
            Tensor(_jnp.asarray(np.asarray(masks, np.int64)),
                   _internal=True))


def chunk_eval(inferences, labels, chunk_scheme="IOB", num_chunk_types=1,
               seq_lengths=None):
    """reference chunk_eval_op.cc: chunk-level precision/recall/F1 for
    sequence labeling (IOB scheme). Host metric (eager), matching the
    reference's CPU-only kernel. Returns (precision, recall, f1,
    num_infer, num_label, num_correct)."""
    import numpy as np

    def extract(seq):
        chunks = set()
        start = None
        ctype = None
        for i, t in enumerate(list(seq) + [-1]):
            t = int(t)
            # IOB over num_chunk_types: tag = type*2 (B) / type*2+1 (I);
            # anything >= 2*num_chunk_types (or -1) is Outside
            if t < 0 or t >= 2 * num_chunk_types:
                b, ty = None, None
            else:
                ty, isB = t // 2, (t % 2 == 0)
                b = "B" if isB else "I"
            if start is not None and (b is None or b == "B" or ty != ctype):
                chunks.add((start, i - 1, ctype))
                start, ctype = None, None
            if b == "B":
                start, ctype = i, ty
            elif b == "I" and start is None:
                start, ctype = i, ty
        return chunks

    inferences = np.asarray(
        getattr(inferences, "numpy", lambda: inferences)())
    labels = np.asarray(getattr(labels, "numpy", lambda: labels)())
    if inferences.ndim == 1:
        inferences, labels = inferences[None], labels[None]
    n_inf = n_lab = n_cor = 0
    for row in range(inferences.shape[0]):
        L = int(seq_lengths[row]) if seq_lengths is not None \
            else inferences.shape[1]
        ic = extract(inferences[row][:L])
        lc = extract(labels[row][:L])
        n_inf += len(ic)
        n_lab += len(lc)
        n_cor += len(ic & lc)
    p = n_cor / n_inf if n_inf else 0.0
    r = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    return p, r, f1, n_inf, n_lab, n_cor


@defop
def gru_unit(x, hidden_prev, weight, bias=None):
    """reference gru_unit_op.cc: one GRU step. x [b, 3d] (pre-projected
    input), hidden_prev [b, d], weight [d, 3d] (hidden projections,
    update|reset|candidate)."""
    d = hidden_prev.shape[1]
    hw = hidden_prev @ weight[:, :2 * d]
    gates = x[:, :2 * d] + hw
    if bias is not None:
        gates = gates + bias[:2 * d]
    u = jax.nn.sigmoid(gates[:, :d])
    r = jax.nn.sigmoid(gates[:, d:2 * d])
    c = x[:, 2 * d:] + (r * hidden_prev) @ weight[:, 2 * d:]
    if bias is not None:
        c = c + bias[2 * d:]
    c = jnp.tanh(c)
    h = u * hidden_prev + (1 - u) * c
    return h, r, c


@defop
def lstm_unit(x, cell_prev, forget_bias=0.0):
    """reference lstm_unit_op.cc: one LSTM step from pre-projected gates
    x [b, 4d] (i|f|c|o), cell_prev [b, d] -> (hidden, cell)."""
    d = cell_prev.shape[1]
    i = jax.nn.sigmoid(x[:, :d])
    f = jax.nn.sigmoid(x[:, d:2 * d] + forget_bias)
    g = jnp.tanh(x[:, 2 * d:3 * d])
    o = jax.nn.sigmoid(x[:, 3 * d:])
    c = f * cell_prev + i * g
    return o * jnp.tanh(c), c


__all__ += ["accuracy", "auc"]


@defop
def accuracy(input, label, k=1):  # noqa: A002
    """reference accuracy_op.cc: top-k accuracy of logits vs labels."""
    topk_idx = jax.lax.top_k(input, k)[1]
    lab = label.reshape(-1, 1).astype(topk_idx.dtype)
    return jnp.mean(jnp.any(topk_idx == lab, axis=1).astype(jnp.float32))


@defop
def auc(predict, label, num_thresholds=200):
    """reference auc_op.cc: ROC-AUC by thresholded TP/FP accumulation
    (same binned estimator; single-batch functional form — the streaming
    stat lives in paddle_tpu.metric.Auc)."""
    pos_prob = predict[:, -1] if predict.ndim == 2 else predict.reshape(-1)
    lab = label.reshape(-1).astype(jnp.float32)
    thr = jnp.linspace(0.0, 1.0, num_thresholds)
    pred = pos_prob[None, :] > thr[:, None]          # [t, n]
    tp = jnp.sum(pred * lab[None], axis=1)
    fp = jnp.sum(pred * (1 - lab[None]), axis=1)
    tpr = tp / jnp.maximum(jnp.sum(lab), 1e-12)
    fpr = fp / jnp.maximum(jnp.sum(1 - lab), 1e-12)
    # integrate tpr over fpr; lexsort (fpr primary, tpr secondary) so the
    # staircase runs lower-left to upper-right — a float32 epsilon
    # tie-break underflows and leaves diagonal artifacts
    order = jnp.lexsort((tpr, fpr))
    return jnp.trapezoid(tpr[order], fpr[order])


__all__ += ["py_func"]


def py_func(func, x, out_shapes=None, out_dtypes="float32",
            backward_func=None):
    """Host-Python op inside compiled graphs (reference
    operators/py_func_op.cc + fluid/layers/nn.py py_func): the callable
    runs on the HOST each step via jax.pure_callback — XLA inserts the
    device<->host transfer, so this composes with jit/static Programs
    (the reference's escape hatch for ops without kernels).

    func: numpy-in/numpy-out callable; x: Tensor or list of Tensors;
    out_shapes/out_dtypes: result specs (default: same as first input).
    backward_func: optional numpy grad callable (inputs..., grad_out) ->
    grads tuple, wired through jax.custom_vjp (itself a callback)."""
    import numpy as _np

    from ..core.dtype import to_jax_dtype
    from ..core.tensor import Tensor

    xs = x if isinstance(x, (list, tuple)) else [x]
    vals = [v._value if isinstance(v, Tensor) else jnp.asarray(v)
            for v in xs]
    if out_shapes is None:
        out_shapes = [tuple(vals[0].shape)]
        single = True
    else:
        single = not isinstance(out_shapes[0], (list, tuple))
        out_shapes = [tuple(out_shapes)] if single \
            else [tuple(s) for s in out_shapes]
    if isinstance(out_dtypes, str):
        out_dtypes = [out_dtypes] * len(out_shapes)
    specs = [jax.ShapeDtypeStruct(s, to_jax_dtype(d))
             for s, d in zip(out_shapes, out_dtypes)]

    def host(*arrs):
        out = func(*[_np.asarray(a) for a in arrs])
        outs = out if isinstance(out, (list, tuple)) else [out]
        return tuple(_np.asarray(o, spec.dtype)
                     for o, spec in zip(outs, specs))

    def call(*vals_):
        res = jax.pure_callback(host, tuple(specs), *vals_)
        return res[0] if single else tuple(res)

    if backward_func is not None:
        in_specs = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in vals]

        def bwd_host(*args):
            grads = backward_func(*[_np.asarray(a) for a in args])
            gs = grads if isinstance(grads, (list, tuple)) else [grads]
            return tuple(_np.asarray(g, s.dtype)
                         for g, s in zip(gs, in_specs))

        call_vjp = jax.custom_vjp(call)

        def fwd(*vals_):
            return call(*vals_), vals_

        def bwd(res, g):
            gouts = g if isinstance(g, (tuple, list)) else (g,)
            return jax.pure_callback(bwd_host, tuple(in_specs),
                                     *res, *gouts)

        call_vjp.defvjp(fwd, bwd)
        call = call_vjp

    from ._dispatch import defop
    op = defop(call, name="py_func_call")
    return op(*xs)


__all__ += ["tree_conv"]


@defop
def tree_conv(nodes_vector, edge_set, filter, max_depth=2):  # noqa: A002
    """Tree-based convolution (reference tree_conv_op.cc, TBCNN "continuous
    binary tree": each node's window is itself + its direct children; the
    child at position j of k mixes the left/right weight matrices with
    eta_r = (j-1)/(k-1), eta_l = 1-eta_r, and the parent uses the top
    matrix).

    nodes_vector [B, n, d]; edge_set [B, e, 2] int (parent, child) pairs,
    1-based with 0 padding (the reference's layout); filter
    [d, 3, out, num_filters] with axis 1 = (top, left, right).
    Returns [B, n, out, num_filters]."""
    x = nodes_vector
    b, n, d = x.shape
    _, three, out_dim, nf = filter.shape
    wt, wl, wr = filter[:, 0], filter[:, 1], filter[:, 2]   # [d, out, nf]

    edges = edge_set.astype(jnp.int32)                      # [B, e, 2]
    parent = edges[..., 0]
    child = edges[..., 1]
    valid = (parent > 0) & (child > 0)
    p_idx = jnp.clip(parent - 1, 0, n - 1)
    c_idx = jnp.clip(child - 1, 0, n - 1)

    # children counts + positions per parent (order of appearance)
    one = valid.astype(jnp.float32)
    counts = jnp.zeros((b, n))
    counts = jax.vmap(lambda cnt, pi, v: cnt.at[pi].add(v))(counts, p_idx,
                                                            one)
    # position of each edge among its parent's children: cumulative count
    def pos_scan(pi, v):
        def body(carry, inp):
            cnt = carry
            idx, vv = inp
            pos = cnt[idx]
            cnt = cnt.at[idx].add(vv)
            return cnt, pos
        _, pos = jax.lax.scan(body, jnp.zeros((n,)), (pi, v))
        return pos
    pos = jax.vmap(pos_scan)(p_idx, one)                    # 0-based

    k = jnp.take_along_axis(counts, p_idx, axis=1)          # [B, e]
    denom = jnp.maximum(k - 1.0, 1.0)
    eta_r = jnp.where(k > 1, pos / denom, 0.5)
    eta_l = 1.0 - eta_r

    cx = jnp.take_along_axis(x, c_idx[..., None], axis=1)   # [B, e, d]
    contrib = (jnp.einsum("bed,dof->beof", cx, wl)
               * eta_l[..., None, None]
               + jnp.einsum("bed,dof->beof", cx, wr)
               * eta_r[..., None, None])
    contrib = contrib * valid[..., None, None]
    # scatter-add child contributions onto their parents
    acc = jnp.zeros((b, n, out_dim, nf), contrib.dtype)
    acc = jax.vmap(lambda a, pi, c: a.at[pi].add(c))(acc, p_idx, contrib)
    # parent (top) term for every node
    acc = acc + jnp.einsum("bnd,dof->bnof", x, wt)
    return jnp.tanh(acc)
