"""Activation functional ops.

Parity targets: reference operators/activation_op.cc (~40 activations),
softmax_op.cc (cudnn path), gelu_op.cc, prelu_op.cc.
XLA fuses these into neighboring matmuls/convs (VPU work), which is the
TPU analog of the reference's fused_ops/fusion_group CUDA codegen.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._dispatch import defop


@defop
def relu(x):
    return jax.nn.relu(x)


@defop
def relu6(x):
    return jax.nn.relu6(x)


@defop
def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


@defop
def prelu(x, weight):
    return jnp.where(x >= 0, x, weight * x)


@defop
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


@defop
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@defop
def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


@defop
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


@defop
def sigmoid(x):
    return jax.nn.sigmoid(x)


@defop
def hardsigmoid(x, slope=0.1666667, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@defop
def hardswish(x):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@defop
def hardtanh(x, min=-1.0, max=1.0):  # noqa: A002
    return jnp.clip(x, min, max)


@defop
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@defop
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@defop
def tanhshrink(x):
    return x - jnp.tanh(x)


@defop
def silu(x):
    return jax.nn.silu(x)


swish = silu


@defop
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@defop
def softplus(x, beta=1.0, threshold=20.0):
    bx = beta * x
    # clamp the exp argument so the unselected branch can't produce inf,
    # whose vjp would poison the gradient with NaN
    safe = jnp.log1p(jnp.exp(jnp.minimum(bx, threshold))) / beta
    return jnp.where(bx > threshold, x, safe)


@defop
def softsign(x):
    return jax.nn.soft_sign(x)


@defop
def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@defop
def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@defop
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@defop
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    from ..core import rng as _rng
    # key drawn inside the kernel: per-run randomness in recorded programs
    g = jax.random.gumbel(_rng.next_key(), x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
        y = y_hard + (y - jax.lax.stop_gradient(y))  # straight-through
    return y


@defop
def maxout(x, groups, axis=1):
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(jnp.reshape(x, new_shape), axis=axis + 1)


@defop
def thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, 0.0)


@defop
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@defop
def normalize(x, p=2, axis=1, epsilon=1e-12):
    denom = jnp.maximum(jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True),
                        epsilon)
    return x / denom
