"""Op definition layer.

TPU-native analog of the reference's op registry + kernel dispatch
(reference: paddle/fluid/framework/op_registry.h:256 REGISTER_OPERATOR;
framework/operator.cc:1166 ChooseKernel). Design delta (SURVEY.md §7.1):
there is exactly ONE kernel per op — a pure jnp/lax function — and XLA's
layout assignment replaces ChooseKernel/PrepareData. `defop` lifts the raw
function to Tensor-land through the autograd recorder (core/tape.py), so the
same definition serves eager dygraph, jit-compiled steps, and the static
Program interpreter. OP_REGISTRY is the OpInfoMap equivalent consulted by
paddle_tpu.static when pretty-printing programs.
"""
from __future__ import annotations

import functools

from ..core.tape import record_op
from ..core.tensor import Tensor

OP_REGISTRY = {}

# op_name -> abstract shape/dtype rule, consulted by
# paddle_tpu.static.shape_infer before falling back to jax.eval_shape.
# A rule takes the op's inputs with every tensor replaced by a
# jax.ShapeDtypeStruct (literals pass through) and returns the output
# aval(s); it raises ValueError on ill-formed inputs.
SHAPE_INFER_REGISTRY = {}


def defop(raw_fn=None, *, name=None, version=1, infer=None):
    """Lift a raw jnp function into a Tensor-level differentiable op.

    `version` is the op's schema version recorded into saved models
    (reference framework.proto:186 op-version map; checked on load by
    framework/program_serde.py). Bump it when an op's attrs or semantics
    change incompatibly.

    `infer` optionally registers an abstract shape/dtype rule for the op
    (the compile-time InferShape analog, framework/op_desc.cc); ops
    without one are inferred through `jax.eval_shape` on the kernel."""
    def deco(f):
        opname = name or f.__name__.lstrip("_")

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return record_op(f, args, kwargs, opname)

        wrapper.raw = f
        wrapper.op_name = opname
        wrapper.op_version = int(version)
        f.op_name = opname  # lets recorded Programs serialize ops by name
        f.op_version = int(version)
        OP_REGISTRY[opname] = wrapper
        if infer is not None:
            SHAPE_INFER_REGISTRY[opname] = infer
        return wrapper

    return deco(raw_fn) if raw_fn is not None else deco


def unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def wrap(v, stop_gradient=True):
    return Tensor(v, stop_gradient=stop_gradient, _internal=True)
