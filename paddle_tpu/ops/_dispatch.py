"""Op definition layer.

TPU-native analog of the reference's op registry + kernel dispatch
(reference: paddle/fluid/framework/op_registry.h:256 REGISTER_OPERATOR;
framework/operator.cc:1166 ChooseKernel). Design delta (SURVEY.md §7.1):
there is exactly ONE kernel per op — a pure jnp/lax function — and XLA's
layout assignment replaces ChooseKernel/PrepareData. `defop` lifts the raw
function to Tensor-land through the autograd recorder (core/tape.py), so the
same definition serves eager dygraph, jit-compiled steps, and the static
Program interpreter. OP_REGISTRY is the OpInfoMap equivalent consulted by
paddle_tpu.static when pretty-printing programs.
"""
from __future__ import annotations

import functools

from ..core.tape import record_op
from ..core.tensor import Tensor

OP_REGISTRY = {}


def defop(raw_fn=None, *, name=None, version=1):
    """Lift a raw jnp function into a Tensor-level differentiable op.

    `version` is the op's schema version recorded into saved models
    (reference framework.proto:186 op-version map; checked on load by
    framework/program_serde.py). Bump it when an op's attrs or semantics
    change incompatibly."""
    def deco(f):
        opname = name or f.__name__.lstrip("_")

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return record_op(f, args, kwargs, opname)

        wrapper.raw = f
        wrapper.op_name = opname
        wrapper.op_version = int(version)
        f.op_name = opname  # lets recorded Programs serialize ops by name
        f.op_version = int(version)
        OP_REGISTRY[opname] = wrapper
        return wrapper

    return deco(raw_fn) if raw_fn is not None else deco


def unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def wrap(v, stop_gradient=True):
    return Tensor(v, stop_gradient=stop_gradient, _internal=True)
