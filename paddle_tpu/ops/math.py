"""Elementwise + scalar math ops.

Parity targets: reference paddle/fluid/operators/elementwise/*,
activation_op.cc (non-nn parts), scale_op.cc, clip_op.cc, cumsum_op.cc,
matmul_v2_op.cc (linalg half lives in linalg.py). One jnp kernel per op;
broadcasting follows numpy rules (the reference's axis-based broadcast is
subsumed).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._dispatch import defop
from ..core.dtype import to_jax_dtype


@defop
def add(x, y):
    return jnp.add(x, y)


@defop
def subtract(x, y):
    return jnp.subtract(x, y)


@defop
def multiply(x, y):
    return jnp.multiply(x, y)


@defop
def divide(x, y):
    return jnp.divide(x, y)


@defop
def floor_divide(x, y):
    return jnp.floor_divide(x, y)


@defop
def remainder(x, y):
    return jnp.remainder(x, y)


mod = remainder


@defop
def pow(x, y):  # noqa: A001 - paddle API name
    return jnp.power(x, y)


@defop
def maximum(x, y):
    return jnp.maximum(x, y)


@defop
def minimum(x, y):
    return jnp.minimum(x, y)


@defop
def fmax(x, y):
    return jnp.fmax(x, y)


@defop
def fmin(x, y):
    return jnp.fmin(x, y)


@defop
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    # reference: operators/scale_op.cc
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return out


@defop
def neg(x):
    return jnp.negative(x)


@defop
def abs(x):  # noqa: A001
    return jnp.abs(x)


@defop
def sign(x):
    return jnp.sign(x)


@defop
def exp(x):
    return jnp.exp(x)


@defop
def expm1(x):
    return jnp.expm1(x)


@defop
def log(x):
    return jnp.log(x)


@defop
def log2(x):
    return jnp.log2(x)


@defop
def log10(x):
    return jnp.log10(x)


@defop
def log1p(x):
    return jnp.log1p(x)


@defop
def sqrt(x):
    return jnp.sqrt(x)


@defop
def rsqrt(x):
    return jax.lax.rsqrt(x)


@defop
def square(x):
    return jnp.square(x)


@defop
def reciprocal(x):
    return jnp.reciprocal(x)


@defop
def sin(x):
    return jnp.sin(x)


@defop
def cos(x):
    return jnp.cos(x)


@defop
def tan(x):
    return jnp.tan(x)


@defop
def asin(x):
    return jnp.arcsin(x)


@defop
def acos(x):
    return jnp.arccos(x)


@defop
def atan(x):
    return jnp.arctan(x)


@defop
def atan2(x, y):
    return jnp.arctan2(x, y)


@defop
def sinh(x):
    return jnp.sinh(x)


@defop
def cosh(x):
    return jnp.cosh(x)


@defop
def tanh(x):
    return jnp.tanh(x)


@defop
def asinh(x):
    return jnp.arcsinh(x)


@defop
def acosh(x):
    return jnp.arccosh(x)


@defop
def atanh(x):
    return jnp.arctanh(x)


@defop
def erf(x):
    return jax.scipy.special.erf(x)


@defop
def erfinv(x):
    return jax.scipy.special.erfinv(x)


@defop
def floor(x):
    return jnp.floor(x)


@defop
def ceil(x):
    return jnp.ceil(x)


@defop
def round(x):  # noqa: A001
    return jnp.round(x)


@defop
def trunc(x):
    return jnp.trunc(x)


@defop
def clip(x, min=None, max=None):  # noqa: A002
    return jnp.clip(x, min, max)


@defop
def lerp(x, y, weight):
    return x + weight * (y - x)


@defop
def cumsum(x, axis=None):
    return jnp.cumsum(x, axis=axis)


@defop
def cumprod(x, dim=None):
    return jnp.cumprod(x, axis=dim)


@defop
def logcumsumexp(x, axis=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=axis)


@defop
def logaddexp(x, y):
    return jnp.logaddexp(x, y)


@defop
def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@defop
def digamma(x):
    return jax.scipy.special.digamma(x)


@defop
def lgamma(x):
    return jax.scipy.special.gammaln(x)


@defop
def multiply_no_nan(x, y):
    return jnp.where(y == 0, 0.0, x * y)


@defop
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@defop
def cast(x, dtype):
    # reference: operators/cast_op.cc; float->float casts carry gradient
    return x.astype(to_jax_dtype(dtype))


@defop
def increment(x, value=1.0):
    return x + value


@defop
def kron(x, y):
    return jnp.kron(x, y)


@defop
def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


@defop
def angle(x):
    return jnp.angle(x)


@defop
def conj(x):
    return jnp.conj(x)


@defop
def real(x):
    return jnp.real(x)


@defop
def imag(x):
    return jnp.imag(x)


@defop
def frac(x):
    return x - jnp.trunc(x)


@defop
def rad2deg(x):
    return jnp.rad2deg(x)


@defop
def deg2rad(x):
    return jnp.deg2rad(x)


@defop
def gcd(x, y):
    return jnp.gcd(x, y)


@defop
def lcm(x, y):
    return jnp.lcm(x, y)


@defop
def heaviside(x, y):
    return jnp.heaviside(x, y)


@defop
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@defop
def assign(x):
    # reference: operators/assign_op.cc — identity/copy
    return jnp.asarray(x)
