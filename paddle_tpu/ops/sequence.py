"""Sequence ops — the reference's sequence_* family on TPU-native forms.

Reference: paddle/fluid/operators/sequence_ops/ (~20 ops walking LoD
offsets: sequence_pool_op.cc, sequence_expand_op.cc, sequence_concat,
sequence_reverse, sequence_softmax, sequence_slice ...). Design delta
(SURVEY hard part 1): instead of per-sequence loops over offsets, every op
is a segment-reduction or mask over the packed (values, row_splits) form —
jax.ops.segment_* map straight onto efficient XLA scatter/reduce-window —
with RaggedTensor (core/ragged.py) carrying the structure.

All ops accept a RaggedTensor or a (values, row_splits) pair.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.ragged import RaggedTensor

__all__ = ["sequence_pool", "sequence_softmax", "sequence_expand",
           "sequence_concat", "sequence_reverse", "sequence_first_step",
           "sequence_last_step", "sequence_slice", "sequence_pad",
           "sequence_unpad"]


def _as_ragged(x, row_splits=None):
    if isinstance(x, RaggedTensor):
        return x
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._value
    return RaggedTensor(x, row_splits)


def sequence_pool(x, pool_type="sum", row_splits=None):
    """reference sequence_pool_op.cc: {sum, average, max, min, sqrt, first,
    last} over each sequence. Returns [nrows, ...]."""
    r = _as_ragged(x, row_splits)
    sid = r.segment_ids()
    n = r.nrows
    pt = pool_type.lower()
    if pt == "sum":
        return jax.ops.segment_sum(r.values, sid, num_segments=n)
    if pt in ("average", "mean"):
        s = jax.ops.segment_sum(r.values, sid, num_segments=n)
        cnt = jnp.maximum(r.lengths, 1).astype(s.dtype)
        return s / cnt.reshape((n,) + (1,) * (s.ndim - 1))
    if pt == "sqrt":
        s = jax.ops.segment_sum(r.values, sid, num_segments=n)
        cnt = jnp.maximum(r.lengths, 1).astype(s.dtype)
        return s / jnp.sqrt(cnt).reshape((n,) + (1,) * (s.ndim - 1))
    if pt == "max":
        return jax.ops.segment_max(r.values, sid, num_segments=n)
    if pt == "min":
        return jax.ops.segment_min(r.values, sid, num_segments=n)
    if pt == "first":
        return sequence_first_step(r)
    if pt == "last":
        return sequence_last_step(r)
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(x, row_splits=None):
    r = _as_ragged(x, row_splits)
    return r.values[r.row_splits[:-1]]


def sequence_last_step(x, row_splits=None):
    r = _as_ragged(x, row_splits)
    return r.values[jnp.maximum(r.row_splits[1:] - 1, 0)]


def sequence_softmax(x, row_splits=None):
    """reference sequence_softmax_op.cc: softmax within each sequence."""
    r = _as_ragged(x, row_splits)
    sid = r.segment_ids()
    n = r.nrows
    mx = jax.ops.segment_max(r.values, sid, num_segments=n)
    e = jnp.exp(r.values - mx[sid])
    denom = jax.ops.segment_sum(e, sid, num_segments=n)
    return RaggedTensor(e / denom[sid], r.row_splits)


def sequence_expand(x, ref, row_splits=None):
    """reference sequence_expand_op.cc: repeat row i of `x` to the length
    of sequence i in `ref` (eager: output size is data-dependent)."""
    r = _as_ragged(ref) if isinstance(ref, RaggedTensor) \
        else _as_ragged(ref, row_splits)
    from ..core.tensor import Tensor
    vals = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    reps = np.asarray(r.lengths)
    idx = np.repeat(np.arange(len(reps)), reps)
    return RaggedTensor(vals[jnp.asarray(idx)], r.row_splits)


def sequence_concat(xs):
    """reference sequence_concat_op.cc: concat per-sequence (not global)."""
    rs = [x if isinstance(x, RaggedTensor) else _as_ragged(x) for x in xs]
    n = rs[0].nrows
    if any(r.nrows != n for r in rs):
        raise ValueError("sequence_concat needs equal sequence counts")
    rows = []
    lists = [r.to_list() for r in rs]
    for i in range(n):
        rows.append(np.concatenate([ls[i] for ls in lists], axis=0))
    return RaggedTensor.from_rows([jnp.asarray(r) for r in rows])


def sequence_reverse(x, row_splits=None):
    """reference sequence_reverse_op.h: reverse within each sequence."""
    r = _as_ragged(x, row_splits)
    starts = r.row_splits[:-1]
    ends = r.row_splits[1:]
    sid = r.segment_ids()
    pos = jnp.arange(r.values.shape[0], dtype=jnp.int32)
    mirrored = starts[sid] + (ends[sid] - 1 - pos)
    return RaggedTensor(r.values[mirrored], r.row_splits)


def sequence_slice(x, offset, length, row_splits=None):
    """reference sequence_slice_op.h: per-sequence [offset, offset+length)."""
    r = _as_ragged(x, row_splits)
    offset = np.asarray(offset).reshape(-1)
    length = np.asarray(length).reshape(-1)
    rows = r.to_list()
    out = [rows[i][int(offset[i]):int(offset[i]) + int(length[i])]
           for i in range(r.nrows)]
    return RaggedTensor.from_rows([jnp.asarray(o) for o in out])


def sequence_pad(x, pad_value=0, maxlen=None, row_splits=None):
    """reference sequence_pad_op.cc: packed -> (padded, lengths)."""
    r = _as_ragged(x, row_splits)
    return r.to_padded(maxlen=maxlen, pad_value=pad_value), r.lengths


def sequence_unpad(x, lengths):
    """reference sequence_unpad_op.cc: (padded, lengths) -> packed."""
    from ..core.tensor import Tensor
    vals = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return RaggedTensor.from_padded(vals, lengths)
