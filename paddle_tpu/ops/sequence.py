"""Sequence ops — the reference's sequence_* family on TPU-native forms.

Reference: paddle/fluid/operators/sequence_ops/ (~20 ops walking LoD
offsets: sequence_pool_op.cc, sequence_expand_op.cc, sequence_concat,
sequence_reverse, sequence_softmax, sequence_slice ...). Design delta
(SURVEY hard part 1): instead of per-sequence loops over offsets, every op
is a segment-reduction or mask over the packed (values, row_splits) form —
jax.ops.segment_* map straight onto efficient XLA scatter/reduce-window —
with RaggedTensor (core/ragged.py) carrying the structure.

All ops accept a RaggedTensor or a (values, row_splits) pair.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.ragged import RaggedTensor

__all__ = ["sequence_pool", "sequence_softmax", "sequence_expand",
           "sequence_concat", "sequence_reverse", "sequence_first_step",
           "sequence_last_step", "sequence_slice", "sequence_pad",
           "sequence_unpad", "sequence_mask", "sequence_expand_as",
           "sequence_enumerate", "sequence_erase", "sequence_reshape",
           "sequence_scatter", "sequence_conv",
           "sequence_topk_avg_pooling"]


def _as_ragged(x, row_splits=None):
    if isinstance(x, RaggedTensor):
        return x
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._value
    return RaggedTensor(x, row_splits)


def sequence_pool(x, pool_type="sum", row_splits=None):
    """reference sequence_pool_op.cc: {sum, average, max, min, sqrt, first,
    last} over each sequence. Returns [nrows, ...]."""
    r = _as_ragged(x, row_splits)
    sid = r.segment_ids()
    n = r.nrows
    pt = pool_type.lower()
    if pt == "sum":
        return jax.ops.segment_sum(r.values, sid, num_segments=n)
    if pt in ("average", "mean"):
        s = jax.ops.segment_sum(r.values, sid, num_segments=n)
        cnt = jnp.maximum(r.lengths, 1).astype(s.dtype)
        return s / cnt.reshape((n,) + (1,) * (s.ndim - 1))
    if pt == "sqrt":
        s = jax.ops.segment_sum(r.values, sid, num_segments=n)
        cnt = jnp.maximum(r.lengths, 1).astype(s.dtype)
        return s / jnp.sqrt(cnt).reshape((n,) + (1,) * (s.ndim - 1))
    if pt == "max":
        return jax.ops.segment_max(r.values, sid, num_segments=n)
    if pt == "min":
        return jax.ops.segment_min(r.values, sid, num_segments=n)
    if pt == "first":
        return sequence_first_step(r)
    if pt == "last":
        return sequence_last_step(r)
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(x, row_splits=None):
    r = _as_ragged(x, row_splits)
    return r.values[r.row_splits[:-1]]


def sequence_last_step(x, row_splits=None):
    r = _as_ragged(x, row_splits)
    return r.values[jnp.maximum(r.row_splits[1:] - 1, 0)]


def sequence_softmax(x, row_splits=None):
    """reference sequence_softmax_op.cc: softmax within each sequence."""
    r = _as_ragged(x, row_splits)
    sid = r.segment_ids()
    n = r.nrows
    mx = jax.ops.segment_max(r.values, sid, num_segments=n)
    e = jnp.exp(r.values - mx[sid])
    denom = jax.ops.segment_sum(e, sid, num_segments=n)
    return RaggedTensor(e / denom[sid], r.row_splits)


def sequence_expand(x, ref, row_splits=None):
    """reference sequence_expand_op.cc: repeat row i of `x` to the length
    of sequence i in `ref` (eager: output size is data-dependent)."""
    r = _as_ragged(ref) if isinstance(ref, RaggedTensor) \
        else _as_ragged(ref, row_splits)
    from ..core.tensor import Tensor
    vals = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    reps = np.asarray(r.lengths)
    idx = np.repeat(np.arange(len(reps)), reps)
    return RaggedTensor(vals[jnp.asarray(idx)], r.row_splits)


def sequence_concat(xs):
    """reference sequence_concat_op.cc: concat per-sequence (not global)."""
    rs = [x if isinstance(x, RaggedTensor) else _as_ragged(x) for x in xs]
    n = rs[0].nrows
    if any(r.nrows != n for r in rs):
        raise ValueError("sequence_concat needs equal sequence counts")
    rows = []
    lists = [r.to_list() for r in rs]
    for i in range(n):
        rows.append(np.concatenate([ls[i] for ls in lists], axis=0))
    return RaggedTensor.from_rows([jnp.asarray(r) for r in rows])


def sequence_reverse(x, row_splits=None):
    """reference sequence_reverse_op.h: reverse within each sequence."""
    r = _as_ragged(x, row_splits)
    starts = r.row_splits[:-1]
    ends = r.row_splits[1:]
    sid = r.segment_ids()
    pos = jnp.arange(r.values.shape[0], dtype=jnp.int32)
    mirrored = starts[sid] + (ends[sid] - 1 - pos)
    return RaggedTensor(r.values[mirrored], r.row_splits)


def sequence_slice(x, offset, length, row_splits=None):
    """reference sequence_slice_op.h: per-sequence [offset, offset+length)."""
    r = _as_ragged(x, row_splits)
    offset = np.asarray(offset).reshape(-1)
    length = np.asarray(length).reshape(-1)
    rows = r.to_list()
    out = [rows[i][int(offset[i]):int(offset[i]) + int(length[i])]
           for i in range(r.nrows)]
    return RaggedTensor.from_rows([jnp.asarray(o) for o in out])


def sequence_pad(x, pad_value=0, maxlen=None, row_splits=None):
    """reference sequence_pad_op.cc: packed -> (padded, lengths)."""
    r = _as_ragged(x, row_splits)
    return r.to_padded(maxlen=maxlen, pad_value=pad_value), r.lengths


def sequence_unpad(x, lengths):
    """reference sequence_unpad_op.cc: (padded, lengths) -> packed."""
    from ..core.tensor import Tensor
    vals = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return RaggedTensor.from_padded(vals, lengths)


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    """reference sequence_mask_op.cc: [n] lengths -> [n, maxlen] 0/1 mask.
    Static-shape friendly: pass maxlen explicitly under jit."""
    from ..core.dtype import to_jax_dtype
    from ..core.tensor import Tensor
    lv = lengths._value if isinstance(lengths, Tensor) \
        else jnp.asarray(lengths)
    if maxlen is None:
        maxlen = int(np.asarray(lv).max())
    col = jnp.arange(maxlen, dtype=lv.dtype)
    return (col[None, :] < lv[..., None]).astype(to_jax_dtype(dtype))


def sequence_expand_as(x, ref, row_splits=None):
    """reference sequence_expand_as_op.cc: like sequence_expand but x rows
    map 1:1 onto ref sequences (x must have nrows rows)."""
    return sequence_expand(x, ref, row_splits)


def sequence_enumerate(x, win_size, pad_value=0, row_splits=None):
    """reference sequence_enumerate_op.cc: per position, the window of the
    next win_size ids (padded past each sequence end)."""
    r = _as_ragged(x, row_splits)
    ends = r.row_splits[1:]
    sid = r.segment_ids()
    pos = jnp.arange(r.values.shape[0], dtype=jnp.int32)
    cols = []
    for w in range(win_size):
        idx = pos + w
        valid = idx < ends[sid]
        gathered = r.values[jnp.minimum(idx, r.values.shape[0] - 1)]
        cols.append(jnp.where(valid, gathered,
                              jnp.asarray(pad_value, r.values.dtype)))
    return RaggedTensor(jnp.stack(cols, axis=-1), r.row_splits)


def sequence_erase(x, tokens, row_splits=None):
    """reference sequence_erase_op.cc: drop listed tokens from every
    sequence (eager: output length is data-dependent)."""
    r = _as_ragged(x, row_splits)
    rows = r.to_list()
    tokens = set(np.asarray(tokens).reshape(-1).tolist())
    out = []
    for row in rows:
        arr = np.asarray(row)
        keep = ~np.isin(arr, list(tokens))
        out.append(jnp.asarray(arr[keep]))
    return RaggedTensor.from_rows(out)


def sequence_reshape(x, new_dim, row_splits=None):
    """reference sequence_reshape_op.cc: re-chunk each sequence's flattened
    payload into rows of width new_dim (per-sequence element counts must
    divide new_dim)."""
    r = _as_ragged(x, row_splits)
    old_dim = int(np.prod(r.values.shape[1:])) or 1
    lens = np.asarray(r.lengths)
    total = lens * old_dim
    if (total % new_dim).any():
        raise ValueError("sequence_reshape: per-sequence payload must be "
                         "divisible by new_dim")
    new_lens = total // new_dim
    vals = jnp.reshape(r.values, (-1, new_dim))
    splits = np.zeros(len(new_lens) + 1, np.int32)
    np.cumsum(new_lens, out=splits[1:])
    return RaggedTensor(vals, splits)


def sequence_scatter(x, index, updates):
    """reference sequence_scatter_op.cc: scatter-add `updates` (ragged,
    per-sequence positions `index`) into dense x rows."""
    from ..core.tensor import Tensor
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    idx = index if isinstance(index, RaggedTensor) else _as_ragged(index)
    upd = updates.values if isinstance(updates, RaggedTensor) \
        else (updates._value if isinstance(updates, Tensor)
              else jnp.asarray(updates))
    sid = idx.segment_ids()
    flat_pos = idx.values.astype(jnp.int32)
    return xv.at[sid, flat_pos].add(upd.astype(xv.dtype))


def sequence_conv(x, weight, context_length, context_start=None,
                  bias=None, row_splits=None):
    """reference sequence_conv_op.cc: 1-D conv along each sequence with a
    [context_length * d_in, d_out] filter; windows never cross sequence
    boundaries (out-of-sequence taps read 0)."""
    r = _as_ragged(x, row_splits)
    if context_start is None:
        context_start = -((context_length - 1) // 2)
    d_in = r.values.shape[-1]
    sid = r.segment_ids()
    starts = r.row_splits[:-1]
    ends = r.row_splits[1:]
    pos = jnp.arange(r.values.shape[0], dtype=jnp.int32)
    taps = []
    for c in range(context_length):
        idx = pos + context_start + c
        valid = (idx >= starts[sid]) & (idx < ends[sid])
        g = r.values[jnp.clip(idx, 0, r.values.shape[0] - 1)]
        taps.append(jnp.where(valid[:, None], g, 0))
    ctx = jnp.concatenate(taps, axis=-1)          # [total, ctx*d_in]
    from ..core.tensor import Tensor
    w = weight._value if isinstance(weight, Tensor) else jnp.asarray(weight)
    out = ctx @ w
    if bias is not None:
        b = bias._value if isinstance(bias, Tensor) else jnp.asarray(bias)
        out = out + b
    return RaggedTensor(out, r.row_splits)


def sequence_topk_avg_pooling(x, topks, row_splits=None):
    """reference sequence_topk_avg_pooling_op.cc: for each sequence and
    each k in topks, the mean of its top-k values (per feature column)."""
    r = _as_ragged(x, row_splits)
    padded = r.to_padded(pad_value=-np.inf)       # [n, maxlen, ...]
    srt = jnp.sort(padded, axis=1)[:, ::-1]       # descending
    lens = r.lengths
    outs = []
    for k in topks:
        take = jnp.where(jnp.isfinite(srt[:, :k]), srt[:, :k], 0)
        cnt = jnp.minimum(lens, k).astype(take.dtype)
        outs.append(take.sum(axis=1)
                    / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (take.ndim - 2)))
    return jnp.stack(outs, axis=1) if len(topks) > 1 else outs[0]


# Register in the op inventory (OP_REGISTRY is the OpInfoMap analog). These
# ops consume/produce RaggedTensor rather than Tensor, so they skip the
# defop Tensor-lifting wrapper but are first-class op families.
from ._dispatch import OP_REGISTRY as _REG  # noqa: E402

for _n in __all__:
    _REG.setdefault(_n, globals()[_n])
