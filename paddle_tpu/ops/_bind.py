"""Attach operators and tensor methods to Tensor.

Analog of the reference's monkey-patching of VarBase with math methods
(reference: python/paddle/fluid/dygraph/math_op_patch.py and
varbase_patch_methods.py): the Tensor class stays minimal and the op
library decorates it at import time, avoiding an import cycle.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import (activation, creation, linalg, logic, loss, manipulation, math,
               norm_ops, reduction)

_BINARY = {
    "__add__": math.add, "__radd__": lambda x, y: math.add(y, x),
    "__sub__": math.subtract, "__rsub__": lambda x, y: math.subtract(y, x),
    "__mul__": math.multiply, "__rmul__": lambda x, y: math.multiply(y, x),
    "__truediv__": math.divide, "__rtruediv__": lambda x, y: math.divide(y, x),
    "__floordiv__": math.floor_divide,
    "__rfloordiv__": lambda x, y: math.floor_divide(y, x),
    "__mod__": math.remainder, "__rmod__": lambda x, y: math.remainder(y, x),
    "__pow__": math.pow, "__rpow__": lambda x, y: math.pow(y, x),
    "__matmul__": linalg.matmul, "__rmatmul__": lambda x, y: linalg.matmul(y, x),
    "__eq__": logic.equal, "__ne__": logic.not_equal,
    "__lt__": logic.less_than, "__le__": logic.less_equal,
    "__gt__": logic.greater_than, "__ge__": logic.greater_equal,
    "__and__": logic.bitwise_and, "__or__": logic.bitwise_or,
    "__xor__": logic.bitwise_xor,
}

for name, fn in _BINARY.items():
    def make(fn):
        def method(self, other):
            return fn(self, other)
        return method
    setattr(Tensor, name, make(fn))

Tensor.__neg__ = lambda self: math.neg(self)
Tensor.__abs__ = lambda self: math.abs(self)
Tensor.__invert__ = lambda self: logic.bitwise_not(self)

_METHODS = dict(
    # math
    add=math.add, subtract=math.subtract, multiply=math.multiply,
    divide=math.divide, pow=math.pow, abs=math.abs, sign=math.sign,
    exp=math.exp, log=math.log, log2=math.log2, log10=math.log10,
    log1p=math.log1p, sqrt=math.sqrt, rsqrt=math.rsqrt, square=math.square,
    reciprocal=math.reciprocal, sin=math.sin, cos=math.cos, tan=math.tan,
    tanh=math.tanh, floor=math.floor, ceil=math.ceil, round=math.round,
    clip=math.clip, cumsum=math.cumsum, cumprod=math.cumprod,
    scale=math.scale, neg=math.neg, erf=math.erf, lerp=math.lerp,
    maximum=math.maximum, minimum=math.minimum, remainder=math.remainder,
    mod=math.remainder, floor_divide=math.floor_divide, kron=math.kron,
    trunc=math.trunc, frac=math.frac, conj=math.conj, real=math.real,
    imag=math.imag, angle=math.angle, digamma=math.digamma,
    lgamma=math.lgamma, logit=math.logit, isnan=logic.isnan,
    isinf=logic.isinf, isfinite=logic.isfinite,
    # reduction
    sum=reduction.sum, mean=reduction.mean, max=reduction.max,
    min=reduction.min, prod=reduction.prod, std=reduction.std,
    var=reduction.var, argmax=reduction.argmax, argmin=reduction.argmin,
    all=reduction.all, any=reduction.any, logsumexp=reduction.logsumexp,
    amax=reduction.amax, amin=reduction.amin, median=reduction.median,
    quantile=reduction.quantile, count_nonzero=reduction.count_nonzero,
    kthvalue=reduction.kthvalue, nansum=reduction.nansum,
    nanmean=reduction.nanmean,
    # manipulation
    reshape=manipulation.reshape, transpose=manipulation.transpose,
    squeeze=manipulation.squeeze, unsqueeze=manipulation.unsqueeze,
    flatten=manipulation.flatten, expand=manipulation.expand,
    expand_as=manipulation.expand_as, broadcast_to=manipulation.broadcast_to,
    tile=manipulation.tile, flip=manipulation.flip, roll=manipulation.roll,
    gather=manipulation.gather, gather_nd=manipulation.gather_nd,
    index_select=manipulation.index_select, scatter=manipulation.scatter,
    scatter_nd_add=manipulation.scatter_nd_add, split=manipulation.split,
    chunk=manipulation.chunk, unbind=manipulation.unbind,
    topk=manipulation.topk, sort=manipulation.sort,
    argsort=manipulation.argsort, unique=manipulation.unique,
    masked_select=manipulation.masked_select,
    masked_fill=manipulation.masked_fill, tril=manipulation._tril,
    triu=manipulation._triu, diagonal=manipulation.diagonal,
    repeat_interleave=manipulation.repeat_interleave,
    take_along_axis=manipulation.take_along_axis,
    put_along_axis=manipulation.put_along_axis, where=manipulation.where,
    moveaxis=manipulation.moveaxis, swapaxes=manipulation.swapaxes,
    nonzero=manipulation.nonzero, bincount=manipulation.bincount,
    # linalg
    matmul=linalg.matmul, dot=linalg.dot, bmm=linalg.bmm, mv=linalg.mv,
    norm=linalg.norm, dist=linalg.dist, cholesky=linalg.cholesky,
    inverse=linalg.inverse, t=manipulation.t, outer=linalg.outer,
    inner=linalg.inner, cross=linalg.cross,
    # logic
    equal=logic.equal, not_equal=logic.not_equal,
    greater_than=logic.greater_than, greater_equal=logic.greater_equal,
    less_than=logic.less_than, less_equal=logic.less_equal,
    logical_and=logic.logical_and, logical_or=logic.logical_or,
    logical_not=logic.logical_not, logical_xor=logic.logical_xor,
    isclose=logic.isclose, allclose=logic.allclose, equal_all=logic.equal_all,
    bitwise_and=logic.bitwise_and, bitwise_or=logic.bitwise_or,
    bitwise_xor=logic.bitwise_xor, bitwise_not=logic.bitwise_not,
    # activation-ish tensor methods
    sigmoid=activation.sigmoid, softmax=activation.softmax,
    # creation-likes
    zeros_like=creation.zeros_like, ones_like=creation.ones_like,
    full_like=creation.full_like,
)

for name, fn in _METHODS.items():
    def make_m(fn):
        def method(self, *args, **kwargs):
            return fn(self, *args, **kwargs)
        return method
    if not hasattr(Tensor, name):
        setattr(Tensor, name, make_m(fn))


def _numel(self):
    from ._dispatch import wrap
    import jax.numpy as jnp
    return wrap(jnp.asarray(self.size, jnp.int64))


Tensor.numel = _numel

# T property (paddle's .T)
Tensor.T = property(lambda self: manipulation.t(self))
