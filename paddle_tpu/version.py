"""Version metadata (reference python/paddle/version.py, generated at
build time there; static here)."""

full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = False
commit = "unknown"
with_mkl = "OFF"


def show():
    print(f"paddle_tpu {full_version}")
    print("compute backend: XLA/PJRT (TPU-first; CPU for tests)")


def cuda():
    """Reference parity: the CUDA toolkit version. TPU-native build — no
    CUDA in the loop."""
    return False


def cudnn():
    return False


def xpu():
    return False
