"""Shared load harness: one place that drives traffic at the system.

Absorbs the four per-tool load loops that used to be hand-rolled in
tools/{serve_load_test,ps_load_test,online_drill,cluster_obs_drill}.py:

- `drive_serve`: submit a list of `Submission`s at a ServeLoop from N
  client threads — jittered-delay or schedule-paced arrivals — and
  collect results/latencies/errors (serve_load_test's client loop and
  the drills' serve phases).
- `run_worker_pool`: start N worker threads, optionally kill a server
  mid-run and record the promotion latency from a monitor counter
  (ps_load_test's three thread-pool + kill + promotion-watch loops).
- `Window`: expose a StreamingDataset to train_from_dataset a fixed
  number of batches at a time (previously duplicated in online_drill
  and cluster_obs_drill).
- `run_spec`: the full closed loop — replay a `workload.WorkloadSpec`
  schedule through a tiny-GPT ServeLoop with the TelemetryHub as the
  single scorekeeper; `tools/capacity_plan.py --validate` asserts the
  capacity model's predictions against this report.

Latency percentiles everywhere come from core/slo.py (the ONE shared
estimator across the load tools).
"""
from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Submission", "ServeStats", "drive_serve", "run_worker_pool",
           "PoolRun", "Window", "run_spec", "HarnessReport",
           "submissions_from_prompts", "submissions_from_events",
           "TTFT_BUCKETS_MS", "TOKEN_BUCKETS_MS"]

# fine-grained histogram bounds for the hub-scored serve latencies:
# ~12%-wide geometric steps so hub-side hist_quantile p50/p99 estimates
# are apples-to-apples with the capacity model's error band
TTFT_BUCKETS_MS = tuple(round(0.25 * 1.12 ** i, 4) for i in range(90))
TOKEN_BUCKETS_MS = tuple(round(0.05 * 1.12 ** i, 4) for i in range(90))


@dataclass
class Submission:
    """One request for `drive_serve`. Either `delay_s` (sleep before
    submit — the load-test jitter idiom) or `t_arrival` (absolute
    schedule seconds, paced against the drive's t0) may be set."""

    index: int
    prompt: np.ndarray
    new_tokens: int
    delay_s: float = 0.0
    t_arrival: Optional[float] = None


def submissions_from_prompts(prompts, new_tokens, delays=None):
    return [Submission(i, np.asarray(p, np.int64), int(new_tokens),
                       delay_s=float(delays[i]) if delays else 0.0)
            for i, p in enumerate(prompts)]


def submissions_from_events(events, time_scale=1.0):
    """Map a workload schedule onto paced submissions."""
    return [Submission(e.index, e.prompt, e.new_tokens,
                       t_arrival=e.t * float(time_scale))
            for e in events]


@dataclass
class ServeStats:
    """What one `drive_serve` pass observed."""

    requests: List = field(default_factory=list)   # ServeRequest | None
    outs: List = field(default_factory=list)       # np.int64 [n] | None
    tokens: int = 0
    ttfts_ms: List[float] = field(default_factory=list)
    token_ms: List[float] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)
    wall_s: float = 0.0

    def collect_latencies(self):
        self.ttfts_ms = [r.ttft_s * 1e3 for r in self.requests
                         if r is not None and r.ttft_s is not None]
        self.token_ms = [r.per_token_s * 1e3 for r in self.requests
                         if r is not None and r.per_token_s is not None]
        return self

    def outputs_digest(self) -> str:
        """Byte-identity oracle over the generated tokens (replay
        proofs: same seed => same per-request token draws)."""
        import hashlib
        h = hashlib.sha256()
        for o in self.outs:
            h.update(b"-" if o is None else
                     np.ascontiguousarray(o, np.int64).tobytes())
            h.update(b"\n")
        return h.hexdigest()


def drive_serve(loop, subs, *, clients=1, wait="result",
                result_timeout_s=600.0) -> ServeStats:
    """Submit every Submission (partitioned round-robin across `clients`
    threads, each honoring its submissions' delays/arrival times), then
    wait per `wait`:

      "result":      block on every request future (loop must be
                     started — background-server mode)
      "idle":        loop.run_until_idle() on the caller thread; request
                     futures are left to the caller
      "idle+result": run_until_idle, then collect every result

    Errors are recorded as strings (`submit[i]: ...` / `result[i]: ...`)
    rather than raised — load tools report and count them.
    """
    subs = list(subs)
    n = len(subs)
    stats = ServeStats(requests=[None] * n, outs=[None] * n)
    lock = threading.Lock()
    t0 = time.perf_counter()

    def client(cid):
        for i in range(cid, n, max(1, clients)):
            s = subs[i]
            if s.t_arrival is not None:
                d = (t0 + s.t_arrival) - time.perf_counter()
                if d > 0:
                    time.sleep(d)
            elif s.delay_s:
                time.sleep(s.delay_s)
            try:
                stats.requests[i] = loop.submit(
                    s.prompt, max_new_tokens=s.new_tokens)
            except Exception as e:  # noqa: BLE001 — reported, not raised
                with lock:
                    stats.errors.append(
                        f"submit[{i}]: {type(e).__name__}: {e}")

    if clients <= 1 and wait in ("idle", "idle+result"):
        client(0)             # drill idiom: submit inline, then drive
    else:
        ths = [threading.Thread(target=client, args=(c,))
               for c in range(max(1, clients))]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
    if wait in ("idle", "idle+result"):
        loop.run_until_idle()
    if wait in ("result", "idle+result"):
        for i, r in enumerate(stats.requests):
            if r is None:
                continue
            try:
                stats.outs[i] = r.result(timeout=result_timeout_s)
                stats.tokens += len(stats.outs[i])
            except Exception as e:  # noqa: BLE001 — reported, not raised
                stats.errors.append(
                    f"result[{i}]: {type(e).__name__}: {e}")
    stats.wall_s = time.perf_counter() - t0
    return stats.collect_latencies()


# ---------------------------------------------------------------------------
# worker pools (the ps_load_test loop family)
# ---------------------------------------------------------------------------

@dataclass
class PoolRun:
    wall_s: float = 0.0
    promote_latency_s: Optional[float] = None


def run_worker_pool(worker, n_workers, *, kill_after_s=None, on_kill=None,
                    promotion_stat="ps.replica.promotions",
                    promote_timeout_s=30.0, poll_s=0.005) -> PoolRun:
    """Run `worker(wid)` on `n_workers` threads. If `kill_after_s` is
    set, fire `on_kill()` that long after start and record the latency
    until `promotion_stat` ticks (None if it never does) — the
    kill-and-promote drill loop shared by the PS load modes."""
    from ..core import monitor

    run = PoolRun()
    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(n_workers)]
    base = monitor.stat_get(promotion_stat) if kill_after_s is not None \
        else 0
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    if kill_after_s is not None:
        time.sleep(kill_after_s)
        t_kill = time.perf_counter()
        on_kill()
        while time.perf_counter() - t_kill < promote_timeout_s:
            if monitor.stat_get(promotion_stat) > base:
                run.promote_latency_s = time.perf_counter() - t_kill
                break
            time.sleep(poll_s)
    for t in threads:
        t.join()
    run.wall_s = time.perf_counter() - t0
    return run


class Window:
    """Expose a shared StreamingDataset generator to train_from_dataset
    a fixed number of batches at a time (one trainer session per round
    over the same exactly-once stream)."""

    def __init__(self, ds):
        self.ds = ds
        self._gen = None
        self.n = 0

    def take(self, n):
        self.n = int(n)
        return self

    def batches(self, start_batch=0):
        if self._gen is None:
            self._gen = self.ds.batches(start_batch=start_batch)
        return itertools.islice(self._gen, self.n)


# ---------------------------------------------------------------------------
# closed-loop spec replay with the TelemetryHub as scorekeeper
# ---------------------------------------------------------------------------

@dataclass
class HarnessReport:
    """Hub-scored observation of one workload-spec replay."""

    spec: str = ""
    seed: int = 0
    events: int = 0
    completed: int = 0
    errors: int = 0
    duration_s: float = 0.0
    wall_s: float = 0.0
    offered_rps: float = 0.0
    throughput_rps: float = 0.0
    tokens_per_s: float = 0.0
    ttft_ms: Dict = field(default_factory=dict)    # {"p50","p99"}
    token_ms: Dict = field(default_factory=dict)
    backpressure_waits: int = 0
    preempted: int = 0
    truncated: int = 0
    schedule_digest: str = ""
    outputs_digest: str = ""
    scored_by: str = "monitor"                      # "hub" | "monitor"

    def as_dict(self) -> Dict:
        return dict(self.__dict__)


def _hub_observed(hub_snapshot):
    """p50/p99 + counters out of a TelemetryHub snapshot's merged
    histograms — the hub, not the client, is the scorekeeper."""
    from ..core import slo
    hists = hub_snapshot.get("hists", {})
    counters = hub_snapshot.get("counters", {})

    def q(name, p):
        h = hists.get(name)
        v = slo.hist_quantile(h, p) if h else None
        return None if v is None else round(float(v), 3)

    return {"ttft_ms": {"p50": q("serve/ttft_ms", 50),
                        "p99": q("serve/ttft_ms", 99)},
            "token_ms": {"p50": q("serve/token_ms", 50),
                         "p99": q("serve/token_ms", 99)},
            "completed": int(counters.get("serve.requests_completed", 0)),
            "tokens": int(counters.get("serve.tokens_generated", 0)),
            "backpressure": int(counters.get("serve.backpressure_waits",
                                             0)),
            "preempted": int(counters.get("serve.preempted", 0))}


def build_tiny_loop(serve_cfg=None, on_complete=None):
    """The CPU tiny-GPT ServeLoop every closed-loop drill shapes traffic
    at. `serve_cfg` maps ServeConfig fields; weights are seeded so two
    builds serve byte-identical token streams."""
    import paddle_tpu as paddle
    from ..inference import ServeConfig, ServeLoop
    from ..text.models.gpt import GPT, GPTConfig

    paddle.seed(0)
    cfg = GPTConfig.tiny()
    net = GPT(cfg)
    net.eval()
    sc = dict(serve_cfg or {})
    sc.setdefault("max_active", 8)
    sc.setdefault("kv_blocks", 48)
    sc.setdefault("block_size", 8)
    sc.setdefault("max_seq_len", 48)
    return net, ServeLoop(net, ServeConfig(**sc), on_complete=on_complete)


def run_spec(spec, seed=0, *, loop=None, serve_cfg=None, clients=None,
             time_scale=None, hub=None, warm=True,
             result_timeout_s=600.0) -> HarnessReport:
    """Replay one WorkloadSpec schedule through a ServeLoop and score it.

    The schedule is generated deterministically from (spec, seed), paced
    onto the wall clock by `time_scale` (PADDLE_TRAFFIC_TIME_SCALE), and
    submitted from `clients` threads (PADDLE_TRAFFIC_CLIENTS). When a
    TelemetryHub is passed, serve metrics ship through a TelemetryShipper
    and the report is computed from the HUB's merged histograms/counters;
    otherwise the local monitor registry scores the run."""
    from ..core import flags as _flags
    from ..core import monitor
    from . import workload as W

    if clients is None:
        clients = int(_flags.flag("PADDLE_TRAFFIC_CLIENTS"))
    if time_scale is None:
        time_scale = float(_flags.flag("PADDLE_TRAFFIC_TIME_SCALE"))
    gen = W.WorkloadGenerator(spec, seed)
    events = list(gen)
    own_loop = loop is None
    if own_loop:
        _net, loop = build_tiny_loop(serve_cfg)
    report = HarnessReport(spec=spec.name, seed=int(seed),
                           events=len(events),
                           duration_s=float(spec.duration_s),
                           truncated=int(gen.stats["truncated"]),
                           schedule_digest=W.schedule_digest(events))
    if events and max(e.tokens_total() for e in events) > loop._cap:
        raise ValueError("spec draws exceed the serve cap "
                         f"({loop._cap}); raise max_seq_len or shrink "
                         "the samplers")
    if warm:
        # one prefill per bucket the schedule can land in, outside the
        # scored window (a cold XLA compile inside the run would be
        # scored as queueing delay)
        buckets = {}
        for e in events:
            b = 8
            while b < e.prompt.size:
                b *= 2
            buckets.setdefault(b, e.prompt)
        for p in buckets.values():
            loop.serve([p], max_new_tokens=2)
    monitor.reset(prefix="serve.")
    monitor.reset(prefix="serve/")
    monitor.ensure_hist("serve/ttft_ms", TTFT_BUCKETS_MS)
    monitor.ensure_hist("serve/token_ms", TOKEN_BUCKETS_MS)

    shipper = None
    if hub is not None:
        from ..core import telemetry
        shipper = telemetry.TelemetryShipper(
            hub.endpoint, member_id=f"traffic-{spec.name}-{seed}",
            role="traffic", flush_s=0.2).start()
    loop.start()
    try:
        stats = drive_serve(
            loop, submissions_from_events(events, time_scale),
            clients=max(1, int(clients)), wait="result",
            result_timeout_s=result_timeout_s)
    finally:
        loop.stop()
        if shipper is not None:
            shipper.close(drain_timeout=20.0)
        if own_loop:
            del loop

    report.completed = sum(1 for o in stats.outs if o is not None)
    report.errors = len(stats.errors)
    report.wall_s = round(stats.wall_s, 3)
    report.outputs_digest = stats.outputs_digest()
    dur = max(spec.duration_s, 1e-9) * max(time_scale, 1e-9)
    report.offered_rps = round(len(events) / dur, 3)
    report.throughput_rps = round(report.completed
                                  / max(stats.wall_s, 1e-9), 3)
    report.tokens_per_s = round(stats.tokens / max(stats.wall_s, 1e-9), 2)
    if hub is not None:
        obs = _hub_observed(hub.snapshot())
        report.ttft_ms = obs["ttft_ms"]
        report.token_ms = obs["token_ms"]
        report.backpressure_waits = obs["backpressure"]
        report.preempted = obs["preempted"]
        report.scored_by = "hub"
    else:
        # same bucketized estimator the hub path uses (slo.hist_quantile
        # over the monitor histogram) so "monitor"- and "hub"-scored
        # reports are comparable sample for sample
        from ..core import slo

        def q(name, p):
            h = monitor.histogram_summary(name)
            v = slo.hist_quantile(h, p) if h else None
            return None if v is None else round(float(v), 3)

        report.ttft_ms = {"p50": q("serve/ttft_ms", 50),
                          "p99": q("serve/ttft_ms", 99)}
        report.token_ms = {"p50": q("serve/token_ms", 50),
                           "p99": q("serve/token_ms", 99)}
        report.backpressure_waits = int(
            monitor.stat_get("serve.backpressure_waits"))
        report.preempted = int(monitor.stat_get("serve.preempted"))
        report.scored_by = "monitor"
    return report
