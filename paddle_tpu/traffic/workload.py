"""Deterministic workload generator: what millions of users look like,
as a replayable event schedule.

A `WorkloadSpec` names an arrival process (steady Poisson, diurnal
wave, flash crowd, or explicit piecewise-rate windows — zero-rate
windows included), a tenant mix, and per-tenant heavy-tailed prompt /
generation-length samplers (LLM generate streams, or hybrid sessions
that pair recsys embedding lookups with a generate call). A
`WorkloadGenerator` turns (spec, seed) into a stream of `Event`s.

Determinism contract (the PR 7/12 splitmix64 idiom, see
distributed/ps/table.py): EVERY random draw comes from a named
splitmix64 stream keyed by `(seed, stream, index)` — counter-based,
never stateful. Two runs of the same (spec, seed) are byte-identical
(`schedule_digest`), draws are independent of Python iteration order,
and a generator resumed from `state_dict()` mid-wave emits exactly the
events the uninterrupted run would have. Wall clocks and stateful RNGs
(`time.time()`, `random.*`, bare `numpy.random`) are banned here by
`tools/framework_lint.py check_traffic_determinism`.

Event times are in *schedule seconds* from t=0; the harness maps them
onto the wall clock (`PADDLE_TRAFFIC_TIME_SCALE`).
"""
from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

__all__ = ["Stream", "WorkloadSpec", "WorkloadGenerator", "Event",
           "schedule", "schedule_digest", "builtin_spec", "BUILTIN_SPECS"]

_MASK64 = (1 << 64) - 1
_NORMAL_XOR = 0xD6E8FEB86659FD93  # second stream for Box-Muller


def _splitmix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return (x ^ (x >> 31)) & _MASK64


class Stream:
    """One named draw stream. `u01(i)` is a pure function of
    (seed, name, i): the i-th draw exists without drawing the first
    i-1, which is what makes schedules replayable and resumable."""

    __slots__ = ("key",)

    def __init__(self, seed: int, name: str):
        k = _splitmix64(int(seed) & _MASK64)
        for ch in name.encode("utf-8"):
            k = _splitmix64(k ^ ch)
        self.key = k

    def bits(self, index: int) -> int:
        return _splitmix64(self.key ^ _splitmix64(int(index) & _MASK64))

    def u01(self, index: int) -> float:
        """Uniform [0, 1) from the top 53 bits (table.py idiom)."""
        return (self.bits(index) >> 11) * (1.0 / (1 << 53))

    def normal(self, index: int) -> float:
        """Standard normal via Box-Muller over two decorrelated draws."""
        h = self.bits(index)
        u1 = max((h >> 11) * (1.0 / (1 << 53)), 1e-12)
        u2 = (_splitmix64(h ^ _NORMAL_XOR) >> 11) * (1.0 / (1 << 53))
        return math.sqrt(-2.0 * math.log(u1)) * math.cos(2.0 * math.pi * u2)

    def randint(self, index: int, lo: int, hi: int) -> int:
        """Integer in [lo, hi) — hi exclusive, like np.random.randint."""
        lo, hi = int(lo), int(hi)
        if hi <= lo:
            return lo
        return lo + int(self.u01(index) * (hi - lo))

    def exp(self, index: int, rate: float) -> float:
        """Exponential inter-arrival draw with the given rate."""
        return -math.log(max(1.0 - self.u01(index), 1e-300)) / float(rate)


# ---------------------------------------------------------------------------
# spec grammar (docs/traffic_lab.md)
# ---------------------------------------------------------------------------

# length-sampler grammar: {"kind": ..., **params}, truncated to
# [lo, min(hi, cap)] at draw time; truncations are counted in
# generator.stats["truncated"].
#   fixed:     {"kind": "fixed", "value": n}
#   uniform:   {"kind": "uniform", "lo": a, "hi": b}       (inclusive)
#   lognormal: {"kind": "lognormal", "median": m, "sigma": s, "lo", "hi"}
#   pareto:    {"kind": "pareto", "alpha": a, "scale": xm, "lo", "hi"}

def _sample_len(dist: Dict, stream: Stream, index: int, cap: int):
    """Returns (length, truncated)."""
    kind = dist.get("kind", "fixed")
    lo = int(dist.get("lo", 1))
    hi = min(int(dist.get("hi", cap)), int(cap))
    if kind == "fixed":
        raw = float(dist["value"])
    elif kind == "uniform":
        raw = float(stream.randint(index, lo, hi + 1))
    elif kind == "lognormal":
        mu = math.log(max(float(dist.get("median", 8)), 1e-9))
        raw = math.exp(mu + float(dist.get("sigma", 0.6))
                       * stream.normal(index))
    elif kind == "pareto":
        alpha = max(float(dist.get("alpha", 2.0)), 1e-6)
        xm = max(float(dist.get("scale", lo)), 1e-9)
        raw = xm / max(1.0 - stream.u01(index), 1e-12) ** (1.0 / alpha)
    else:
        raise ValueError(f"unknown length sampler kind {kind!r}")
    n = int(round(raw))
    truncated = n > hi
    return max(lo, min(n, hi)), truncated


# arrival grammar: {"kind": ..., **params}; rate(t) in requests/s.
#   poisson: {"kind": "poisson", "rate": r}
#   diurnal: {"kind": "diurnal", "base": b, "peak": p, "period_s": T}
#            rate(t) = b + (p-b) * (1 - cos(2*pi*t/T)) / 2
#   flash:   {"kind": "flash", "base": b, "burst_rate": r,
#             "burst_at_s": t0, "burst_len_s": d}
#   windows: {"kind": "windows", "windows": [[dur_s, rate], ...]}
#            piecewise-constant; rate 0 windows emit nothing.

def arrival_rate(arrival: Dict, t: float) -> float:
    kind = arrival.get("kind", "poisson")
    if kind == "poisson":
        return float(arrival["rate"])
    if kind == "diurnal":
        base = float(arrival.get("base", 0.0))
        peak = float(arrival["peak"])
        period = max(float(arrival.get("period_s", 60.0)), 1e-9)
        return base + (peak - base) * 0.5 * (
            1.0 - math.cos(2.0 * math.pi * t / period))
    if kind == "flash":
        t0 = float(arrival.get("burst_at_s", 0.0))
        if t0 <= t < t0 + float(arrival.get("burst_len_s", 1.0)):
            return float(arrival["burst_rate"])
        return float(arrival.get("base", 0.0))
    if kind == "windows":
        edge = 0.0
        for dur, rate in arrival["windows"]:
            edge += float(dur)
            if t < edge:
                return float(rate)
        return 0.0
    raise ValueError(f"unknown arrival kind {kind!r}")


def arrival_peak_rate(arrival: Dict) -> float:
    kind = arrival.get("kind", "poisson")
    if kind == "poisson":
        return float(arrival["rate"])
    if kind == "diurnal":
        return max(float(arrival.get("base", 0.0)), float(arrival["peak"]))
    if kind == "flash":
        return max(float(arrival.get("base", 0.0)),
                   float(arrival["burst_rate"]))
    if kind == "windows":
        return max([float(r) for _, r in arrival["windows"]] or [0.0])
    raise ValueError(f"unknown arrival kind {kind!r}")


_DEFAULT_TENANT = {
    "name": "default", "weight": 1.0, "kind": "llm",
    "prompt": {"kind": "lognormal", "median": 8, "sigma": 0.5, "lo": 2},
    "new": {"kind": "fixed", "value": 8},
}


@dataclass(frozen=True)
class WorkloadSpec:
    """One deterministic workload: arrival process x tenant mix x
    length samplers, bounded by (duration_s, max_events)."""

    name: str
    arrival: Dict
    duration_s: float
    tenants: Tuple[Dict, ...] = ()
    vocab: int = 1024
    max_seq_len: int = 64
    max_events: int = 100_000

    def resolved_tenants(self) -> List[Dict]:
        return [dict(t) for t in (self.tenants or (_DEFAULT_TENANT,))]

    def canonical(self) -> Dict:
        return {"name": self.name, "arrival": self.arrival,
                "duration_s": self.duration_s,
                "tenants": self.resolved_tenants(),
                "vocab": self.vocab, "max_seq_len": self.max_seq_len,
                "max_events": self.max_events}

    def digest(self) -> str:
        blob = json.dumps(self.canonical(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclass
class Event:
    """One scheduled request. `t` is schedule seconds from start."""

    index: int
    t: float
    tenant: str
    kind: str                       # "llm" | "hybrid"
    prompt: np.ndarray              # int64 token ids, len >= 1
    new_tokens: int
    lookup_ids: Optional[np.ndarray] = None   # hybrid recsys pulls
    session: int = 0

    def tokens_total(self) -> int:
        return int(self.prompt.size) + int(self.new_tokens)


# events encode prompt token ids as sub-draws of one stream: event k,
# position j keys index (k << _SUBSHIFT) | j, so a schedule prefix
# never depends on how many tokens later events drew
_SUBSHIFT = 20


class WorkloadGenerator:
    """Iterator of `Event`s for (spec, seed). Resumable: `state_dict()`
    mid-iteration captures the exact position; a fresh generator given
    `load_state_dict(state)` continues byte-identically."""

    def __init__(self, spec: WorkloadSpec, seed: int = 0):
        if spec.max_seq_len < 4:
            raise ValueError("max_seq_len must be >= 4")
        self.spec = spec
        self.seed = int(seed)
        self.tenants = spec.resolved_tenants()
        w = [max(float(t.get("weight", 1.0)), 0.0) for t in self.tenants]
        tot = sum(w) or 1.0
        self._cum_weights = np.cumsum([x / tot for x in w])
        s = lambda name: Stream(self.seed, f"{spec.name}/{name}")  # noqa: E731
        self._arrive = s("arrival")
        self._thin = s("thin")
        self._tenant = s("tenant")
        self._plen = s("prompt_len")
        self._nlen = s("gen_len")
        self._ptok = s("prompt_tok")
        self._lookup = s("lookup")
        self._t = 0.0
        self._proposals = 0
        self._emitted = 0
        self.stats = {"events": 0, "truncated": 0, "by_tenant": {}}

    # -- resume contract -----------------------------------------------------
    def state_dict(self) -> Dict:
        return {"spec_digest": self.spec.digest(), "seed": self.seed,
                "t": self._t, "proposals": self._proposals,
                "emitted": self._emitted,
                "stats": json.loads(json.dumps(self.stats))}

    def load_state_dict(self, state: Dict) -> "WorkloadGenerator":
        if state.get("spec_digest") != self.spec.digest():
            raise ValueError("state_dict is for a different WorkloadSpec")
        if int(state.get("seed", -1)) != self.seed:
            raise ValueError("state_dict is for a different seed")
        self._t = float(state["t"])
        self._proposals = int(state["proposals"])
        self._emitted = int(state["emitted"])
        self.stats = json.loads(json.dumps(state["stats"]))
        return self

    # -- iteration -----------------------------------------------------------
    def __iter__(self) -> Iterator[Event]:
        while True:
            ev = self.next_event()
            if ev is None:
                return
            yield ev

    def next_event(self) -> Optional[Event]:
        """Thinning (Lewis-Shedler) over the time-varying rate: propose
        at the spec's peak rate, accept with prob rate(t)/peak — which
        makes zero-rate windows emit nothing while keeping every draw
        counter-keyed."""
        spec = self.spec
        peak = arrival_peak_rate(spec.arrival)
        if peak <= 0.0:
            return None
        while True:
            if self._emitted >= spec.max_events:
                return None
            i = self._proposals
            self._proposals += 1
            self._t += self._arrive.exp(i, peak)
            if self._t >= spec.duration_s:
                return None
            lam = arrival_rate(spec.arrival, self._t)
            if lam <= 0.0 or self._thin.u01(i) * peak >= lam:
                continue
            return self._emit(self._t)

    def _emit(self, t: float) -> Event:
        spec = self.spec
        k = self._emitted
        self._emitted += 1
        ti = int(np.searchsorted(self._cum_weights,
                                 self._tenant.u01(k), side="right"))
        tenant = self.tenants[min(ti, len(self.tenants) - 1)]
        cap = spec.max_seq_len - 1
        plen, p_trunc = _sample_len(
            tenant.get("prompt", _DEFAULT_TENANT["prompt"]),
            self._plen, k, cap)
        n_cap = spec.max_seq_len - plen
        nlen, n_trunc = _sample_len(
            tenant.get("new", _DEFAULT_TENANT["new"]),
            self._nlen, k, n_cap)
        base = k << _SUBSHIFT
        prompt = np.fromiter(
            (self._ptok.randint(base | j, 1, spec.vocab)
             for j in range(plen)), np.int64, count=plen)
        lookups = None
        if tenant.get("kind", "llm") == "hybrid":
            n_look = int(tenant.get("lookups", 8))
            lvocab = int(tenant.get("lookup_vocab", 100_000))
            lookups = np.fromiter(
                (self._lookup.randint(base | j, 0, lvocab)
                 for j in range(n_look)), np.int64, count=n_look)
        self.stats["events"] += 1
        self.stats["truncated"] += int(p_trunc) + int(n_trunc)
        name = tenant.get("name", "default")
        self.stats["by_tenant"][name] = \
            self.stats["by_tenant"].get(name, 0) + 1
        return Event(index=k, t=float(t), tenant=name,
                     kind=tenant.get("kind", "llm"), prompt=prompt,
                     new_tokens=int(nlen), lookup_ids=lookups, session=k)


def schedule(spec: WorkloadSpec, seed: int = 0) -> List[Event]:
    """The full replayable event schedule for (spec, seed)."""
    return list(WorkloadGenerator(spec, seed))


def schedule_digest(events) -> str:
    """SHA-256 over the canonical byte encoding of a schedule — the
    byte-identity oracle the replay tests assert on."""
    h = hashlib.sha256()
    for e in events:
        h.update(f"{e.index}|{e.t!r}|{e.tenant}|{e.kind}|"
                 f"{e.new_tokens}|".encode())
        h.update(np.ascontiguousarray(e.prompt, np.int64).tobytes())
        if e.lookup_ids is not None:
            h.update(b"|L|")
            h.update(np.ascontiguousarray(e.lookup_ids,
                                          np.int64).tobytes())
        h.update(b"\n")
    return h.hexdigest()


# ---------------------------------------------------------------------------
# canonical specs: the capacity-validation trio (CPU tiny-model shape)
# ---------------------------------------------------------------------------

def _tiny_tenants() -> Tuple[Dict, ...]:
    return (
        {"name": "chat", "weight": 0.7, "kind": "llm",
         "prompt": {"kind": "lognormal", "median": 6, "sigma": 0.45,
                    "lo": 2, "hi": 16},
         "new": {"kind": "uniform", "lo": 4, "hi": 8}},
        {"name": "recsys", "weight": 0.3, "kind": "hybrid", "lookups": 8,
         "lookup_vocab": 65_536,
         "prompt": {"kind": "lognormal", "median": 5, "sigma": 0.35,
                    "lo": 2, "hi": 12},
         "new": {"kind": "fixed", "value": 4}},
    )


def builtin_spec(name: str, *, rate: float = 30.0,
                 duration_s: float = 6.0) -> WorkloadSpec:
    """The named validation workloads (`steady`, `diurnal`, `flash`):
    same tenant mix and samplers, three arrival shapes. `rate` is the
    mean offered load in requests/s."""
    if name == "steady":
        arrival = {"kind": "poisson", "rate": rate}
    elif name == "diurnal":
        # mean of base + (peak-base)/2 == rate
        arrival = {"kind": "diurnal", "base": rate * 0.4,
                   "peak": rate * 1.6, "period_s": duration_s}
    elif name == "flash":
        # quiet base with a 4x burst over the middle fifth of the run
        base = rate * 0.625
        arrival = {"kind": "flash", "base": base, "burst_rate": base * 4,
                   "burst_at_s": duration_s * 0.4,
                   "burst_len_s": duration_s * 0.2}
    else:
        raise ValueError(f"unknown builtin spec {name!r} "
                         "(steady|diurnal|flash)")
    return WorkloadSpec(name=name, arrival=arrival, duration_s=duration_s,
                        tenants=_tiny_tenants(), vocab=1024,
                        max_seq_len=48)


BUILTIN_SPECS = ("steady", "diurnal", "flash")
