"""Traffic lab: deterministic workload generation + the shared load
harness (docs/traffic_lab.md). The capacity model that predicts what
these workloads will observe lives in static/capacity.py."""
from .harness import (HarnessReport, PoolRun, ServeStats, Submission,
                      Window, drive_serve, run_spec, run_worker_pool,
                      submissions_from_events, submissions_from_prompts)
from .workload import (BUILTIN_SPECS, Event, Stream, WorkloadGenerator,
                       WorkloadSpec, builtin_spec, schedule,
                       schedule_digest)

__all__ = ["Stream", "WorkloadSpec", "WorkloadGenerator", "Event",
           "schedule", "schedule_digest", "builtin_spec", "BUILTIN_SPECS",
           "Submission", "ServeStats", "drive_serve", "run_worker_pool",
           "PoolRun", "Window", "run_spec", "HarnessReport",
           "submissions_from_prompts", "submissions_from_events"]
