"""Higher-order autodiff over Tensor-level functions.

The eager tape (core/tape.py) is first-order by design — create_graph-style
double backward would need grad-of-grad graphs the reference builds with
nested GradOpDescMakers. TPU-natively that's just functional transform
composition: lift a Tensor function to raw arrays once, then let jax.grad /
jacfwd / jacrev / hessian stack arbitrarily (promised by
paddle_tpu.autograd.grad's error message, core/tape.py).
"""
from __future__ import annotations

import jax

from ..core import tape as _tape
from ..core.tensor import Tensor

__all__ = ["as_raw_fn", "grad", "value_and_grad", "jacobian", "hessian",
           "vjp", "jvp"]


def as_raw_fn(fn):
    """Lift a Tensor->Tensor function to a pure jax-array function (scalars
    pass through). The body runs eager-over-trace with the tape off, so it
    composes under any jax transform."""
    def raw(*args):
        with _tape.no_grad():
            t_args = [Tensor(a, _internal=True) for a in args]
            out = fn(*t_args)
        is_t = lambda x: isinstance(x, Tensor)  # noqa: E731
        return jax.tree_util.tree_map(
            lambda t: t._value if is_t(t) else t, out, is_leaf=is_t)
    return raw


def _unwrap(a):
    return a._value if isinstance(a, Tensor) else a


def _wrap(v):
    return jax.tree_util.tree_map(lambda x: Tensor(x, _internal=True), v)


def grad(fn, argnums=0):
    """d(scalar fn)/d(args). Composable: grad(grad(f)) is double backward."""
    g = jax.grad(as_raw_fn(fn), argnums=argnums)
    return lambda *args: _wrap(g(*[_unwrap(a) for a in args]))


def value_and_grad(fn, argnums=0):
    vg = jax.value_and_grad(as_raw_fn(fn), argnums=argnums)
    return lambda *args: _wrap(vg(*[_unwrap(a) for a in args]))


def jacobian(fn, argnums=0, mode="rev"):
    jac = (jax.jacrev if mode == "rev" else jax.jacfwd)(
        as_raw_fn(fn), argnums=argnums)
    return lambda *args: _wrap(jac(*[_unwrap(a) for a in args]))


def hessian(fn, argnums=0):
    h = jax.hessian(as_raw_fn(fn), argnums=argnums)
    return lambda *args: _wrap(h(*[_unwrap(a) for a in args]))


def vjp(fn, *primals):
    out, pullback = jax.vjp(as_raw_fn(fn), *[_unwrap(p) for p in primals])
    return _wrap(out), lambda ct: _wrap(pullback(_unwrap(ct)))


def jvp(fn, primals, tangents):
    out, tan = jax.jvp(as_raw_fn(fn),
                       tuple(_unwrap(p) for p in primals),
                       tuple(_unwrap(t) for t in tangents))
    return _wrap(out), _wrap(tan)
