"""paddle.incubate — graduated-experimental APIs (reference
python/paddle/fluid/incubate/: auto-checkpoint, fleet utils, ...).

Here: `incubate.functional` (higher-order autodiff over Tensor functions)
and `incubate.checkpoint` (preemption-safe training checkpoints, the
reference fluid/incubate/checkpoint/auto_checkpoint.py analog).
"""
import importlib as _importlib

_SUBMODULES = ("functional", "checkpoint", "optimizer")


def __getattr__(name):
    if name in _SUBMODULES:
        mod = _importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(
        f"module 'paddle_tpu.incubate' has no attribute {name!r}")
