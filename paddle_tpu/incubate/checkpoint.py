"""Preemption-safe training checkpoints (orbax-backed), with a
verification tier that makes trainer death a non-event.

Analog of the reference auto-checkpoint stack:
- fluid/incubate/checkpoint/auto_checkpoint.py:71 (`AutoCheckpointChecker`,
  `train_epoch_range` epoch-resume) — here `TrainingCheckpoint` +
  `train_epoch_range`;
- operators/save_op.cc / framework/save_load_util.cc tensor serialization —
  here orbax's step-atomic directory commits;
- the reference saved to HDFS from the trainer; on TPU preemptions are
  routine (SURVEY.md §5.3 "needed from day one"), so saves are ASYNC
  (training continues while the previous step's state writes out) with
  keep-latest-k retention.

State captured per step: parameters+buffers, full optimizer state (slots,
step count, LR schedule), AMP loss-scaler state, the ambient PRNG chain
head, the data-pipeline position (epoch, next-batch cursor, shuffle RNG
state — DataLoader.state_dict), and (epoch, step, global_step) counters —
everything needed for a bit-identical training continuation after SIGKILL.

Integrity tier (docs/fault_tolerance.md "Trainer recovery"): every save
writes a sidecar manifest — per-leaf sha256 over the exact bytes handed
to orbax plus a tree schema of shapes/dtypes — committed atomically next
to orbax's own atomic step-directory rename. Restore re-hashes what it
read; a corrupt, partial, or schema-mismatched step raises a structured
`CheckpointCorruptError` naming the first bad leaf, and the default
latest-restore QUARANTINES the bad step (`.quarantine/` + the
`ckpt.corrupt_skipped` counter + a flight-recorder note) and walks back
to the newest checkpoint that verifies — a torn write costs one
checkpoint interval, never the job.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Optional

import numpy as np

__all__ = ["TrainingCheckpoint", "train_epoch_range", "PreemptionGuard",
           "CheckpointCorruptError"]

MANIFEST_VERSION = 1


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed verification. `step` is the checkpoint step,
    `leaf` the first offending tree path ("<unreadable>" when the store
    itself could not be read), `reason` what mismatched."""

    def __init__(self, step, leaf, reason):
        self.step = int(step)
        self.leaf = leaf
        self.reason = reason
        super().__init__(
            f"checkpoint step {step} is corrupt at leaf {leaf!r}: {reason}")


def _np_tree(obj):
    """Tensor/jax leaves -> numpy (orbax-serializable)."""
    from ..core.tensor import Tensor
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _np_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_np_tree(v) for v in obj]
    return obj


def _flat_leaves(tree, prefix=""):
    """Deterministic (path, leaf) walk: dicts by sorted key, lists by
    index — the manifest's leaf namespace."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _flat_leaves(tree[k], f"{prefix}/{k}" if prefix
                                    else str(k))
        return
    if isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _flat_leaves(v, f"{prefix}/{i}" if prefix
                                    else str(i))
        return
    yield prefix, tree


def _leaf_record(leaf):
    """(shape, dtype, sha256) of one leaf, over the canonical numpy
    form — symmetric between save time and restore time, so a bit flip
    anywhere in the stored bytes surfaces as a hash mismatch."""
    arr = np.asarray(leaf)
    return {"shape": list(arr.shape), "dtype": str(arr.dtype),
            "sha256": hashlib.sha256(
                np.ascontiguousarray(arr).tobytes()).hexdigest()}


def build_manifest(step, state):
    return {"manifest_version": MANIFEST_VERSION, "step": int(step),
            "time": time.time(),
            "leaves": {path: _leaf_record(leaf)
                       for path, leaf in _flat_leaves(state)}}


def verify_manifest(step, state, manifest):
    """Raise CheckpointCorruptError naming the first bad leaf if `state`
    does not match `manifest` (missing/extra leaves, shape/dtype drift,
    hash mismatch)."""
    want = manifest.get("leaves", {})
    got = {path: leaf for path, leaf in _flat_leaves(state)}
    for path in sorted(want):
        if path not in got:
            raise CheckpointCorruptError(step, path,
                                         "leaf missing from restored tree")
    for path in sorted(got):
        if path not in want:
            raise CheckpointCorruptError(step, path,
                                         "leaf absent from manifest")
        rec = _leaf_record(got[path])
        ref = want[path]
        for field in ("shape", "dtype"):
            if rec[field] != ref[field]:
                raise CheckpointCorruptError(
                    step, path, f"{field} mismatch: manifest "
                    f"{ref[field]!r}, restored {rec[field]!r}")
        if rec["sha256"] != ref["sha256"]:
            raise CheckpointCorruptError(step, path, "sha256 mismatch")


class TrainingCheckpoint:
    """Async step-atomic training checkpoints with keep-latest-k and
    manifest verification."""

    def __init__(self, directory, keep=3, save_interval_steps=50,
                 async_save=True):
        import orbax.checkpoint as ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ocp = ocp
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                enable_async_checkpointing=async_save))
        self.save_interval_steps = int(save_interval_steps)
        self._emergency_handle = None
        self._emergency_fired = False
        self._in_save = False   # re-entrancy guard for signal-time saves

    # -- manifest plumbing ---------------------------------------------------
    def _manifest_path(self, step):
        return os.path.join(self.directory, f"manifest_{int(step)}.json")

    def _write_manifest(self, step, state):
        path = self._manifest_path(step)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(build_manifest(step, state), f)
        os.replace(tmp, path)

    def _read_manifest(self, step):
        try:
            with open(self._manifest_path(step)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _gc_manifests(self, protect=()):
        """Drop manifests whose step orbax already retired (keep-latest-k)
        or that was quarantined; best-effort. `protect` shields steps
        whose async commit may still be in flight."""
        try:
            live = set(int(s) for s in self._mngr.all_steps())
        except Exception:
            return
        live |= {int(s) for s in protect}
        try:
            for name in os.listdir(self.directory):
                if not (name.startswith("manifest_")
                        and name.endswith(".json")):
                    continue
                try:
                    step = int(name[len("manifest_"):-len(".json")])
                except ValueError:
                    continue
                if step not in live:
                    os.unlink(os.path.join(self.directory, name))
        except OSError:
            pass

    def _quarantine(self, step, exc):
        """Move a corrupt step out of the manager's sight so the restore
        walk-back (and every later restart) lands on a verified step, and
        leave the evidence on disk for post-mortem."""
        from ..core import flight_recorder as _fr
        from ..core import monitor as _monitor
        qdir = os.path.join(self.directory, ".quarantine")
        os.makedirs(qdir, exist_ok=True)
        src = os.path.join(self.directory, str(int(step)))
        dst = os.path.join(qdir, f"{int(step)}_{int(time.time())}")
        try:
            if os.path.isdir(src):
                os.replace(src, dst)
            mpath = self._manifest_path(step)
            if os.path.exists(mpath):
                shutil.move(mpath, dst + ".manifest.json")
        except OSError:
            pass
        _monitor.stat_add("ckpt.corrupt_skipped")
        _fr.dump("ckpt_corrupt", exc,
                 extra={"step": int(step), "directory": self.directory,
                        "leaf": getattr(exc, "leaf", None),
                        "quarantined_to": dst})
        if hasattr(self._mngr, "reload"):
            try:  # forget the cached step list
                self._mngr.reload()
            except Exception:
                pass

    # -- low-level ----------------------------------------------------------
    def save(self, step: int, state: dict, force=False):
        state = _np_tree(state)
        # manifest first: it hashes the exact tree handed to orbax. The
        # COMMIT marker stays orbax's atomic step-dir rename — a SIGKILL
        # between the two leaves a manifest without a step (harmless,
        # GC'd) never a committed step whose manifest lies.
        self._in_save = True
        try:
            self._write_manifest(step, state)
            self._mngr.save(int(step),
                            args=self._ocp.args.StandardSave(state),
                            force=force)
            self._gc_manifests(protect=(int(step),))
        finally:
            self._in_save = False

    def emergency_save(self, step: int, state: dict):
        """Synchronous forced save for failure paths (SIGTERM grace,
        PipelineStepError): returns only once the step is durable."""
        self.save(int(step), state, force=True)
        self.wait()

    def install_emergency_save(self, capture_fn,
                               reasons=("pipeline_step_error",
                                        "signal_SIGTERM")):
        """Join the flight-recorder trigger points: when a dump fires for
        one of `reasons`, run one synchronous emergency save of
        capture_fn() -> (step, state). Fires at most once per process —
        a failure storm must not re-enter the save path."""
        from ..core import flight_recorder as _fr

        def hook(reason, exc):
            # _in_save: the signal landed INSIDE a checkpoint save on
            # this very manager (hooks run on the interrupted main
            # thread) — re-entering orbax mid-mutation could deadlock
            # past the eviction deadline or tear the step being
            # written; die on the last committed step instead
            if self._emergency_fired or self._in_save:
                return
            self._emergency_fired = True
            step, state = capture_fn()
            self.emergency_save(step, state)

        self._emergency_handle = _fr.register_emergency_hook(hook, reasons)
        return self._emergency_handle

    def uninstall_emergency_save(self):
        if self._emergency_handle is not None:
            from ..core import flight_recorder as _fr
            _fr.unregister_emergency_hook(self._emergency_handle)
            self._emergency_handle = None

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self):
        return sorted(int(s) for s in self._mngr.all_steps())

    def _restore_verified(self, step):
        """Load one step and verify it against its manifest. Raises
        CheckpointCorruptError (corrupt/mismatched), FileNotFoundError
        (no such step)."""
        from ..core import flags as _flags
        from ..core import monitor as _monitor
        try:
            state = self._mngr.restore(
                int(step), args=self._ocp.args.StandardRestore())
        except FileNotFoundError:
            raise
        except Exception as e:
            # torn/partial step directory: orbax could not even read it
            raise CheckpointCorruptError(step, "<unreadable>",
                                         f"{type(e).__name__}: {e}")
        manifest = self._read_manifest(step)
        if manifest is None:
            # pre-manifest (legacy) checkpoint: loadable, not provable
            _monitor.stat_add("ckpt.unverified_loads")
            return state
        if _flags.flag("PADDLE_CKPT_VERIFY"):
            verify_manifest(step, state, manifest)
            _monitor.stat_set("ckpt.last_verified_step", int(step))
        return state

    def restore(self, step: Optional[int] = None) -> Optional[dict]:
        """Restore a verified checkpoint. With an explicit `step`:
        returns None if the step is gone (GC'd), raises
        CheckpointCorruptError if it exists but fails verification.
        With step=None: walks newest -> oldest, quarantining every
        corrupt step, and returns the newest state that verifies (None
        when nothing restorable exists)."""
        if step is not None:
            try:
                return self._restore_verified(step)
            except FileNotFoundError:
                return None  # e.g. a step already GC'd by keep-latest-k
        for s in sorted(self.all_steps(), reverse=True):
            try:
                return self._restore_verified(s)
            except CheckpointCorruptError as e:
                self._quarantine(s, e)
            except FileNotFoundError:
                continue
        return None

    def wait(self):
        self._mngr.wait_until_finished()

    def close(self):
        self.uninstall_emergency_save()
        self._mngr.close()

    # -- Model.fit integration ---------------------------------------------
    def capture(self, model, epoch, step, global_step,
                data_state=None, ps_state=None) -> dict:
        from ..core import rng as _rng
        state = {
            "model": {k: v for k, v in _np_tree(
                dict(model.network.state_dict())).items()},
            "optimizer": _np_tree(model._optimizer.state_dict()),
            "rng_key": np.asarray(_rng.default_generator()._key),
            "counters": {"epoch": int(epoch), "step": int(step),
                         "global_step": int(global_step)},
        }
        amp_cfg = getattr(model, "_amp_configs", None)
        scaler = amp_cfg.get("scaler") if amp_cfg else None
        if scaler is not None:
            state["scaler"] = _np_tree(scaler.scale_state())
        if data_state is not None:
            state["data"] = _np_tree(data_state)
        if ps_state is not None:
            state["ps"] = _np_tree(ps_state)
        return state

    def maybe_save(self, model, epoch, step, global_step, force=False,
                   data_state=None, ps_state=None):
        if force or (global_step % self.save_interval_steps == 0
                     and global_step > 0):
            self.save(global_step,
                      self.capture(model, epoch, step, global_step,
                                   data_state=data_state,
                                   ps_state=ps_state),
                      force=force)
            return True
        return False

    def restore_into(self, model, data_loader=None) -> Optional[dict]:
        """Restore the latest verified checkpoint into
        model/optimizer/rng (and, when `data_loader` supports
        load_state_dict and the checkpoint carries a `data` section, the
        data-pipeline position); returns the counters dict (or None if
        no checkpoint exists). Parameter-shape drift between the
        checkpoint and the live model raises a per-param ValueError
        instead of a broadcast crash deep in set_state_dict."""
        state = self.restore()
        if state is None:
            return None
        from ..core import rng as _rng
        import jax.numpy as jnp
        live = dict(model.network.state_dict())
        for name, saved in state["model"].items():
            cur = live.get(name)
            if cur is None:
                continue  # set_state_dict owns unknown-key policy
            saved_shape = tuple(np.asarray(saved).shape)
            cur_shape = tuple(np.asarray(
                cur._value if hasattr(cur, "_value") else cur).shape)
            if saved_shape != cur_shape:
                raise ValueError(
                    f"checkpoint/model shape mismatch for parameter "
                    f"{name!r}: checkpoint has {list(saved_shape)}, model "
                    f"has {list(cur_shape)} — the model architecture "
                    "changed since this checkpoint was written; restore "
                    "it into the original architecture or start fresh")
        model.network.set_state_dict(state["model"])
        model._optimizer.set_state_dict(state["optimizer"])
        if "scaler" in state:
            amp_cfg = getattr(model, "_amp_configs", None)
            scaler = amp_cfg.get("scaler") if amp_cfg else None
            if scaler is not None:
                scaler.load_scale_state(state["scaler"])
        key = state["rng_key"]
        _rng.default_generator().seat(jnp.asarray(
            np.asarray(key, dtype=np.uint32)))
        counters = dict(state["counters"])
        counters = {k: int(v) for k, v in counters.items()}
        if data_loader is not None and "data" in state \
                and hasattr(data_loader, "load_state_dict"):
            data_loader.load_state_dict(state["data"])
            counters["data_resumed"] = True
        if "ps" in state:
            counters["ps_state"] = state["ps"]
        return counters


class PreemptionGuard:
    """SIGTERM-grace checkpointing (SURVEY §5.3: TPU preemptions send
    SIGTERM before eviction; the reference's analog is the launcher's
    watch loop + auto-checkpoint). While installed, SIGTERM triggers one
    forced synchronous checkpoint before the default handler runs, so a
    preempted job resumes from its exact step instead of the last
    periodic save. With `runner` (a PipelineRunner), the capture is
    preceded by `runner.sync()` — in-flight steps drain and the
    device-resident carry writes back, so the saved step count matches
    the applied optimizer state with nothing lost or double-run."""

    def __init__(self, ckpt: TrainingCheckpoint, capture_fn, runner=None):
        """capture_fn() -> (step, state_dict) captured at signal time."""
        self._ckpt = ckpt
        self._capture = capture_fn
        self._runner = runner
        self._prev = None
        self.fired = False

    def _grace_save(self):
        if getattr(self._ckpt, "_in_save", False):
            # SIGTERM landed inside a periodic save on this manager
            # (the handler runs on the interrupted main thread):
            # re-entering orbax could deadlock past the eviction
            # deadline — recovery falls back to the last committed step
            return
        if self._runner is not None:
            try:
                self._runner.sync()
            except Exception:
                pass  # a poisoned pipeline: save what the carry left
        step, state = self._capture()
        self._ckpt.save(step, state, force=True)
        self._ckpt.wait()

    def __enter__(self):
        import signal

        def handler(signum, frame):
            self.fired = True
            try:
                self._grace_save()
            finally:
                if callable(self._prev):
                    self._prev(signum, frame)
                elif self._prev != signal.SIG_IGN:
                    # grace save done: die by SIGTERM as the default
                    # disposition would have, so the launcher sees the
                    # true wait status
                    import os
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

        self._prev = signal.signal(signal.SIGTERM, handler)
        return self

    def __exit__(self, *exc):
        import signal
        signal.signal(signal.SIGTERM, self._prev or signal.SIG_DFL)
        return False


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None,
                      directory=None):
    """reference auto_checkpoint.py `train_epoch_range`: a resumable epoch
    iterator. The epoch counter persists under `directory` (or
    $PADDLE_TPU_CHECKPOINT_DIR / ./paddle_tpu_auto_checkpoint); on restart
    iteration continues from the last completed epoch. An epoch COMMITS
    only when the loop body finishes AND the iterator is resumed — a
    trainer killed between the yield and the post-epoch save redoes that
    epoch rather than skipping it (exactly-once would need the body's
    side effects to be transactional; redo keeps the at-least-once
    contract the reference chose)."""
    directory = directory or os.environ.get(
        "PADDLE_TPU_CHECKPOINT_DIR", "./paddle_tpu_auto_checkpoint")
    ckpt = TrainingCheckpoint(directory, keep=2, async_save=False)
    try:
        last = ckpt.restore()
        start = int(last["epoch"]) + 1 if last is not None else 0
        for epoch in range(start, max_epoch_num):
            yield epoch
            ckpt.save(epoch, {"epoch": epoch}, force=True)
            ckpt.wait()
    finally:
        # finished OR abandoned (GeneratorExit lands here): release the
        # orbax CheckpointManager and its worker thread — one leaked
        # manager per training loop otherwise
        ckpt.close()
