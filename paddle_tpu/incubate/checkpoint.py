"""Preemption-safe training checkpoints (orbax-backed).

Analog of the reference auto-checkpoint stack:
- fluid/incubate/checkpoint/auto_checkpoint.py:71 (`AutoCheckpointChecker`,
  `train_epoch_range` epoch-resume) — here `TrainingCheckpoint` +
  `train_epoch_range`;
- operators/save_op.cc / framework/save_load_util.cc tensor serialization —
  here orbax's step-atomic directory commits;
- the reference saved to HDFS from the trainer; on TPU preemptions are
  routine (SURVEY.md §5.3 "needed from day one"), so saves are ASYNC
  (training continues while the previous step's state writes out) with
  keep-latest-k retention.

State captured per step: parameters+buffers, full optimizer state (slots,
step count, LR schedule), AMP loss-scaler state, the ambient PRNG chain
head, and (epoch, step, global_step) counters — everything needed for a
bit-identical training continuation after SIGKILL.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

__all__ = ["TrainingCheckpoint", "train_epoch_range", "PreemptionGuard"]


def _np_tree(obj):
    """Tensor/jax leaves -> numpy (orbax-serializable)."""
    from ..core.tensor import Tensor
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _np_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_np_tree(v) for v in obj]
    return obj


class TrainingCheckpoint:
    """Async step-atomic training checkpoints with keep-latest-k."""

    def __init__(self, directory, keep=3, save_interval_steps=50,
                 async_save=True):
        import orbax.checkpoint as ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ocp = ocp
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                enable_async_checkpointing=async_save))
        self.save_interval_steps = int(save_interval_steps)

    # -- low-level ----------------------------------------------------------
    def save(self, step: int, state: dict, force=False):
        self._mngr.save(int(step), args=self._ocp.args.StandardSave(
            _np_tree(state)), force=force)

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, step: Optional[int] = None) -> Optional[dict]:
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        try:
            return self._mngr.restore(
                step, args=self._ocp.args.StandardRestore())
        except FileNotFoundError:
            return None  # e.g. a step already GC'd by keep-latest-k

    def wait(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()

    # -- Model.fit integration ---------------------------------------------
    def capture(self, model, epoch, step, global_step) -> dict:
        from ..core import rng as _rng
        state = {
            "model": {k: v for k, v in _np_tree(
                dict(model.network.state_dict())).items()},
            "optimizer": _np_tree(model._optimizer.state_dict()),
            "rng_key": np.asarray(_rng.default_generator()._key),
            "counters": {"epoch": int(epoch), "step": int(step),
                         "global_step": int(global_step)},
        }
        amp_cfg = getattr(model, "_amp_configs", None)
        scaler = amp_cfg.get("scaler") if amp_cfg else None
        if scaler is not None:
            state["scaler"] = _np_tree(scaler.scale_state())
        return state

    def maybe_save(self, model, epoch, step, global_step, force=False):
        if force or (global_step % self.save_interval_steps == 0
                     and global_step > 0):
            self.save(global_step,
                      self.capture(model, epoch, step, global_step),
                      force=force)
            return True
        return False

    def restore_into(self, model) -> Optional[dict]:
        """Restore the latest checkpoint into model/optimizer/rng; returns
        the counters dict (or None if no checkpoint exists)."""
        state = self.restore()
        if state is None:
            return None
        from ..core import rng as _rng
        import jax.numpy as jnp
        model.network.set_state_dict(state["model"])
        model._optimizer.set_state_dict(state["optimizer"])
        if "scaler" in state:
            amp_cfg = getattr(model, "_amp_configs", None)
            scaler = amp_cfg.get("scaler") if amp_cfg else None
            if scaler is not None:
                scaler.load_scale_state(state["scaler"])
        key = state["rng_key"]
        _rng.default_generator().seat(jnp.asarray(
            np.asarray(key, dtype=np.uint32)))
        return dict(state["counters"])


class PreemptionGuard:
    """SIGTERM-grace checkpointing (SURVEY §5.3: TPU preemptions send
    SIGTERM before eviction; the reference's analog is the launcher's
    watch loop + auto-checkpoint). While installed, SIGTERM triggers one
    forced synchronous checkpoint before the default handler runs, so a
    preempted job resumes from its exact step instead of the last
    periodic save."""

    def __init__(self, ckpt: TrainingCheckpoint, capture_fn):
        """capture_fn() -> (step, state_dict) captured at signal time."""
        self._ckpt = ckpt
        self._capture = capture_fn
        self._prev = None
        self.fired = False

    def __enter__(self):
        import signal

        def handler(signum, frame):
            self.fired = True
            try:
                step, state = self._capture()
                self._ckpt.save(step, state, force=True)
                self._ckpt.wait()
            finally:
                if callable(self._prev):
                    self._prev(signum, frame)
                elif self._prev != signal.SIG_IGN:
                    # grace save done: die by SIGTERM as the default
                    # disposition would have, so the launcher sees the
                    # true wait status
                    import os
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

        self._prev = signal.signal(signal.SIGTERM, handler)
        return self

    def __exit__(self, *exc):
        import signal
        signal.signal(signal.SIGTERM, self._prev or signal.SIG_DFL)
        return False


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None,
                      directory=None):
    """reference auto_checkpoint.py `train_epoch_range`: a resumable epoch
    iterator. The epoch counter persists under `directory` (or
    $PADDLE_TPU_CHECKPOINT_DIR / ./paddle_tpu_auto_checkpoint); on restart
    iteration continues from the last completed epoch."""
    directory = directory or os.environ.get(
        "PADDLE_TPU_CHECKPOINT_DIR", "./paddle_tpu_auto_checkpoint")
    ckpt = TrainingCheckpoint(directory, keep=2, async_save=False)
    try:
        last = ckpt.restore()
        start = int(last["epoch"]) + 1 if last is not None else 0
        for epoch in range(start, max_epoch_num):
            yield epoch
            ckpt.save(epoch, {"epoch": epoch}, force=True)
            ckpt.wait()
    finally:
        # finished OR abandoned (GeneratorExit lands here): release the
        # orbax CheckpointManager and its worker thread — one leaked
        # manager per training loop otherwise
        ckpt.close()
