"""paddle.incubate.optimizer — reference incubate optimizer homes
(python/paddle/incubate/optimizer/lookahead.py, modelaverage.py). The
implementations live in paddle_tpu.optimizer.averaging; this module is
the API-parity mount point."""
from ..optimizer.averaging import (ExponentialMovingAverage,  # noqa: F401
                                   LookAhead, ModelAverage)

__all__ = ["LookAhead", "ModelAverage", "ExponentialMovingAverage"]
