"""Device / place API.

Analog of reference paddle/fluid/platform/place.h (Place variant) and
platform/device_context.* (DeviceContextPool). On TPU, XLA/PJRT owns device
contexts and streams, so a Place is a thin handle over a jax.Device; the
DeviceContextPool's job (one context+stream per device) is done by PJRT.
"""
from __future__ import annotations

import jax

__all__ = ["CPUPlace", "CUDAPlace", "TPUPlace", "XPUPlace", "CUDAPinnedPlace",
           "set_device", "get_device", "get_all_devices", "device_count",
           "is_compiled_with_cuda", "is_compiled_with_xpu",
           "is_compiled_with_tpu"]


class Place:
    _kind = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __eq__(self, other):
        return (isinstance(other, Place) and self._kind == other._kind
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self._kind, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"


class CPUPlace(Place):
    _kind = "cpu"


class CUDAPlace(Place):
    # Accepted for API parity; maps to the default accelerator.
    _kind = "gpu"


class CUDAPinnedPlace(Place):
    _kind = "pinned"


class XPUPlace(Place):
    _kind = "xpu"


class TPUPlace(Place):
    _kind = "tpu"


_current = None


def _platform():
    return jax.devices()[0].platform


def set_device(device: str):
    """paddle.set_device — accepted for parity. XLA owns placement; sharding
    (paddle_tpu.distributed) is the multi-device mechanism."""
    global _current
    _current = device
    return device


def get_device() -> str:
    if _current is not None:
        return _current
    p = _platform()
    return f"{p}:0"


def get_all_devices():
    return [f"{d.platform}:{i}" for i, d in enumerate(jax.devices())]


def device_count() -> int:
    return len(jax.devices())


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True
