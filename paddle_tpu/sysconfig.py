"""paddle.sysconfig — installation introspection (reference
python/paddle/sysconfig.py: get_include/get_lib for building C++ extensions
against the framework)."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory of C headers (reference sysconfig.get_include). The
    TPU-native runtime's native pieces live under _native/include."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(pkg, "_native", "include")


def get_lib():
    """Directory of shared libraries."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(pkg, "_native", "lib")
