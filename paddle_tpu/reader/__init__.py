"""paddle.reader — fluid-era reader decorators.

Analog of reference python/paddle/reader/decorator.py: a *reader creator*
is a zero-arg callable returning a generator of samples; these combinators
wrap creators. Kept for v1 compat — the 2.x path is paddle.io.DataLoader
(io/dataloader.py), which the hapi engine uses.
"""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "cache", "multiprocess_reader",
           "ComposeNotAligned", "batch"]


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (reference
    python/paddle/batch.py:18 — exposed at the paddle root as
    paddle.batch)."""
    batch_size = int(batch_size)
    if batch_size <= 0:
        raise ValueError(
            "batch_size should be a positive integer value, "
            f"but got batch_size={batch_size}")

    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    """reader of func(*samples) over zipped readers (decorator.py
    map_readers)."""
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle (decorator.py shuffle)."""
    def new_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return new_reader


def chain(*readers):
    """Concatenate readers (decorator.py chain)."""
    def reader():
        return itertools.chain(*[r() for r in readers])
    return reader


def compose(*readers, **kwargs):
    """Zip readers into flat tuples (decorator.py compose).
    check_alignment=True raises ComposeNotAligned on length mismatch."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
            return
        while True:
            outputs = []
            done = 0
            for r in rs:
                try:
                    outputs.append(next(r))
                except StopIteration:
                    done += 1
            if done == len(rs):
                return
            if done:
                raise ComposeNotAligned(
                    "readers have different lengths")
            yield sum((make_tuple(o) for o in outputs), ())
    return reader


def buffered(reader, size):
    """Prefetch into a bounded queue on a thread (decorator.py
    buffered)."""
    END = object()

    def new_reader():
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for e in reader():
                    q.put(e)
            finally:
                q.put(END)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is END:
                return
            yield e
    return new_reader


def firstn(reader, n):
    def new_reader():
        return itertools.islice(reader(), n)
    return new_reader


def cache(reader):
    """Materialize once, replay from memory (decorator.py cache)."""
    all_data = []
    filled = [False]

    def new_reader():
        if not filled[0]:
            all_data.extend(reader())
            filled[0] = True
        yield from all_data
    return new_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (decorator.py
    xmap_readers). order=True preserves input order."""
    END = object()

    def new_reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(END)

        def work():
            while True:
                item = in_q.get()
                if item is END:
                    out_q.put(END)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if not order:
            while finished < process_num:
                item = out_q.get()
                if item is END:
                    finished += 1
                    continue
                yield item[1]
            return
        pending = {}
        nxt = 0
        while finished < process_num or pending:
            if nxt in pending:
                yield pending.pop(nxt)
                nxt += 1
                continue
            item = out_q.get()
            if item is END:
                finished += 1
                continue
            pending[item[0]] = item[1]
        while nxt in pending:
            yield pending.pop(nxt)
            nxt += 1
    return new_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers concurrently (decorator.py
    multiprocess_reader; worker THREADS here — the samples come from
    in-process synthetic datasets, so process isolation buys nothing)."""
    END = object()

    def new_reader():
        q = queue.Queue(queue_size)

        def run(r):
            try:
                for e in r():
                    q.put(e)
            finally:
                q.put(END)

        for r in readers:
            threading.Thread(target=run, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            e = q.get()
            if e is END:
                finished += 1
                continue
            yield e
    return new_reader
