"""paddle.onnx — model-export compat surface.

Analog of reference python/paddle/onnx/export.py (which shells into
paddle2onnx to emit an ONNX protobuf). Design delta: the TPU-native
interchange artifact is serialized StableHLO via jax.export — the same
role ONNX plays for the reference (a framework-neutral deployment graph),
but directly consumable by XLA on TPU/CPU/GPU with no converter in the
loop. `export` therefore produces the StableHLO artifact set
({path}.stablehlo + {path}.pdinfer.json + {path}.pdmodel/.pdiparams),
loadable by paddle_tpu.inference.Predictor and the C/Go clients.

Emitting an ONNX *protobuf* additionally requires the `onnx` package,
which is not part of this environment; when importable, `export` also
writes {path}.onnx via the generic StableHLO->ONNX single-node wrapper
(function body carried as the serialized StableHLO, mirroring how
paddle2onnx carries custom ops).
"""
from __future__ import annotations

import warnings

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=11, **configs):
    """paddle.onnx.export(layer, path, input_spec) — see module docstring.

    Returns the artifact prefix. The reference writes {path}.onnx; here
    the deployment artifact is {path}.stablehlo (+ metadata); a true
    .onnx protobuf is written only when the optional `onnx` package is
    importable.
    """
    from .. import jit
    prefix = path[:-5] if path.endswith(".onnx") else path
    jit.save(layer, prefix, input_spec=input_spec)
    try:
        import onnx  # noqa: F401
    except ImportError:
        warnings.warn(
            "paddle_tpu.onnx.export wrote the StableHLO deployment "
            f"artifact ({prefix}.stablehlo); writing an ONNX protobuf "
            "additionally requires the optional `onnx` package. The "
            "StableHLO artifact is the TPU-native interchange format — "
            "load it with paddle_tpu.inference.Predictor or the C/Go "
            "clients.")
        return prefix
    _write_onnx_wrapper(prefix, opset_version)
    return prefix


def _write_onnx_wrapper(prefix, opset_version):
    import json

    import onnx
    from onnx import TensorProto, helper

    meta = json.load(open(prefix + ".pdinfer.json"))
    blob = open(prefix + ".stablehlo", "rb").read()
    dt_map = {"float32": TensorProto.FLOAT, "int32": TensorProto.INT32,
              "int64": TensorProto.INT64, "bool": TensorProto.BOOL,
              "float16": TensorProto.FLOAT16}
    ins = [helper.make_tensor_value_info(n, dt_map.get(d, TensorProto.FLOAT),
                                         None)
           for n, d in zip(meta["input_names"], meta["input_dtypes"])]
    outs = [helper.make_tensor_value_info(n, TensorProto.FLOAT, s)
            for n, s in zip(meta["output_names"], meta["output_shapes"])]
    node = helper.make_node(
        "StablehloCall", [i.name for i in ins], [o.name for o in outs],
        domain="org.stablehlo",
        module=blob)
    graph = helper.make_graph([node], "paddle_tpu_export", ins, outs)
    model = helper.make_model(
        graph, opset_imports=[helper.make_opsetid("", opset_version),
                              helper.make_opsetid("org.stablehlo", 1)])
    onnx.save(model, prefix + ".onnx")
