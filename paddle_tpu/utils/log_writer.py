"""Scalar/metric log writer — the VisualDL analog.

The reference streams training metrics to VisualDL through a hapi
callback (reference hapi/callbacks.py VisualDL writer; python/paddle
visualdl integration). Zero-egress equivalent: JSON-lines scalar logs
(one record per add_scalar) that any dashboard can tail, plus a reader
for tests/tools. Used by hapi via VisualDLCallback.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogWriter", "read_scalars"]


class LogWriter:
    def __init__(self, logdir, filename="scalars.jsonl"):
        os.makedirs(logdir, exist_ok=True)
        self._path = os.path.join(logdir, filename)
        self._f = open(self._path, "a", buffering=1)

    @property
    def path(self):
        return self._path

    def add_scalar(self, tag, value, step):
        self._f.write(json.dumps({
            "tag": tag, "value": float(value), "step": int(step),
            "wall_time": time.time()}) + "\n")

    def add_scalars(self, main_tag, tag_value_dict, step):
        for k, v in tag_value_dict.items():
            self.add_scalar(f"{main_tag}/{k}", v, step)

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def read_scalars(logdir, filename="scalars.jsonl", tag=None):
    path = os.path.join(logdir, filename)
    out = []
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if tag is None or rec["tag"] == tag:
                out.append(rec)
    return out
