"""paddle.utils.download (reference python/paddle/utils/download.py
get_weights_path_from_url). Zero-egress delta: nothing is fetched —
weights resolve from the local cache dir (PADDLE_TPU_WEIGHTS_DIR or
~/.cache/paddle_tpu/weights); a missing file raises with the exact path
to drop it at instead of silently downloading."""
from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url", "weights_cache_dir"]


def weights_cache_dir():
    d = os.environ.get("PADDLE_TPU_WEIGHTS_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu", "weights")
    os.makedirs(d, exist_ok=True)
    return d


def get_weights_path_from_url(url, md5sum=None):
    fname = url.rsplit("/", 1)[-1]
    path = os.path.join(weights_cache_dir(), fname)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"pretrained weights {fname!r} not found. paddle_tpu runs "
            f"zero-egress: fetch {url} yourself and place it at {path} "
            "(or set PADDLE_TPU_WEIGHTS_DIR)")
    if md5sum:
        import hashlib
        with open(path, "rb") as f:
            got = hashlib.md5(f.read()).hexdigest()  # noqa: S324
        if got != md5sum:
            raise ValueError(f"{path}: md5 mismatch ({got} != {md5sum})")
    return path
