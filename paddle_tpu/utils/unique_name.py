"""Unique name generator.

Analog of reference python/paddle/fluid/unique_name.py (UniqueNameGenerator
used by LayerHelper for parameter/var naming).
"""
from __future__ import annotations

import contextlib
import threading
from collections import defaultdict

_lock = threading.Lock()
_counters = defaultdict(int)


def generate(key: str) -> str:
    with _lock:
        n = _counters[key]
        _counters[key] += 1
    return f"{key}_{n}"


@contextlib.contextmanager
def guard(prefix: str = ""):
    global _counters
    with _lock:
        saved = _counters
        _counters = defaultdict(int)
    try:
        yield
    finally:
        with _lock:
            _counters = saved


def switch():
    global _counters
    with _lock:
        _counters = defaultdict(int)
