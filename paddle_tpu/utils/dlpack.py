"""paddle.utils.dlpack — zero-copy tensor interchange (reference
python/paddle/utils/dlpack.py to_dlpack/from_dlpack over the DLPack
protocol). jax arrays speak __dlpack__ natively, so interop with torch/
numpy/cupy is direct."""
from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack capsule."""
    from ..core.tensor import Tensor
    v = x._value if isinstance(x, Tensor) else x
    return v.__dlpack__()


def from_dlpack(capsule_or_tensor):
    """DLPack capsule (or any object with __dlpack__, e.g. a torch
    tensor) -> Tensor."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    return Tensor(jnp.from_dlpack(capsule_or_tensor), _internal=True)
