from . import unique_name  # noqa: F401
from .log_writer import LogWriter, read_scalars  # noqa: F401
