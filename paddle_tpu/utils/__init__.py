from . import unique_name  # noqa: F401
from .log_writer import LogWriter, read_scalars  # noqa: F401


def run_check():
    """Install sanity check (reference paddle.utils.run_check /
    fluid/install_check.py: trains a tiny model, reports the device
    story). Runs one regression step on the default backend and a
    dp-sharded step over all local devices."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer

    devs = jax.devices()
    print(f"paddle_tpu is installed; backend={devs[0].platform} "
          f"device_count={len(devs)}")

    paddle.seed(0)
    lin = nn.Linear(4, 1)
    opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).rand(8, 4)
                         .astype("float32"))
    y = paddle.to_tensor(np.random.RandomState(1).rand(8, 1)
                         .astype("float32"))
    before = float(((lin(x) - y) ** 2).mean().numpy())
    for _ in range(5):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    after = float(((lin(x) - y) ** 2).mean().numpy())
    assert after < before, (before, after)
    print("single-device train step: OK")

    if len(devs) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.distributed import mesh as mesh_mod
        mesh = mesh_mod.init_mesh({"dp": len(devs)})
        import jax.numpy as jnp
        w = jax.device_put(jnp.zeros((4,)), NamedSharding(mesh, P()))
        xb = jax.device_put(jnp.ones((len(devs) * 2, 4)),
                            NamedSharding(mesh, P("dp")))
        step = jax.jit(lambda w, x: w + x.mean(0),
                       in_shardings=(NamedSharding(mesh, P()),
                                     NamedSharding(mesh, P("dp"))),
                       out_shardings=NamedSharding(mesh, P()))
        np.testing.assert_allclose(np.asarray(step(w, xb)), np.ones(4))
        print(f"{len(devs)}-device dp-sharded step: OK")
    print("paddle_tpu run_check passed.")
