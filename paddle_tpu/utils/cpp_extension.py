"""Custom-op / C++ extension story (reference
python/paddle/utils/cpp_extension/: CppExtension + load() JIT-compiling
user kernels, and the PD_BUILD_OP custom operator registration).

TPU design delta: DEVICE custom ops here are Python — `@defop` +
`jax.custom_vjp` (or a Pallas kernel) IS the custom-op API, and
`register_custom_op` below wires such a function into OP_REGISTRY so it
dispatches, records into static Programs, and differentiates like any
built-in. `load()` keeps the reference's host-side C++ JIT path for what
native code is still for on a TPU host — parsers, samplers, feature
extractors (the _native tier) — compiling sources with g++ and returning
a ctypes library.
"""
from __future__ import annotations

import os
import subprocess
import threading

__all__ = ["load", "CppExtension", "register_custom_op"]

_lock = threading.Lock()


def _build_dir():
    d = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """Build spec (reference cpp_extension.CppExtension)."""

    def __init__(self, sources, extra_compile_args=None,
                 include_dirs=None, name=None):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])
        self.include_dirs = list(include_dirs or [])
        self.name = name


def load(name, sources=None, extra_cxx_cflags=None, include_dirs=None,
         verbose=False, build_directory=None):
    """JIT-compile C++ sources into {build_dir}/lib{name}.so and load it
    with ctypes (reference cpp_extension.load, minus pybind: the returned
    handle is a ctypes.CDLL — declare argtypes/restype and call; ctypes
    calls release the GIL like the _native tier)."""
    import ctypes

    if isinstance(name, CppExtension):
        ext = name
        name = ext.name or "ext"
        sources = ext.sources
        extra_cxx_cflags = ext.extra_compile_args
        include_dirs = ext.include_dirs
    if not sources:
        raise ValueError("load() needs C++ sources")
    out_dir = build_directory or _build_dir()
    so = os.path.join(out_dir, f"lib{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    with _lock:
        stale = (not os.path.exists(so)
                 or any(os.path.getmtime(so) < os.path.getmtime(s)
                        for s in srcs))
        if stale:
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                   *srcs, "-o", so + ".tmp"]
            for inc in include_dirs or []:
                cmd.append(f"-I{inc}")
            cmd += list(extra_cxx_cflags or [])
            if verbose:
                print("[cpp_extension]", " ".join(cmd))
            subprocess.run(cmd, check=True, capture_output=not verbose)
            os.replace(so + ".tmp", so)
    return ctypes.CDLL(so)


def register_custom_op(name=None, vjp=None):
    """Register a Python/Pallas function as a first-class operator
    (reference PD_BUILD_OP + custom_operator.cc load_op_library): the
    function lands in OP_REGISTRY, dispatches over Tensors, records into
    static Programs, and — when `vjp` is given — differentiates through
    the tape via jax.custom_vjp.

        @register_custom_op(vjp=(fwd_res_fn, bwd_fn))
        def my_op(x, alpha=1.0): ...

    vjp: (fwd, bwd) pair with jax.custom_vjp semantics; omit for ops
    differentiable by tracing."""
    import functools

    from ..ops._dispatch import defop

    def deco(fn):
        raw = fn
        if vjp is not None:
            import jax
            fwd, bwd = vjp
            wrapped = jax.custom_vjp(fn)
            wrapped.defvjp(fwd, bwd)
            raw = functools.wraps(fn)(wrapped)
        return defop(raw, name=name)

    return deco
