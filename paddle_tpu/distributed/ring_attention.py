"""Ring attention — sequence/context parallelism for long sequences.

The reference has NO long-context subsystem (SURVEY.md §5.7: exhaustive
grep confirms no ring attention / sequence parallel / Ulysses; its only
tools are recompute + pipeline micro-batching). This module is the designed-
from-scratch capability: Q/K/V are sharded along the sequence axis over the
'sp' mesh dimension; K/V blocks rotate around the ring via collective-
permute while each device accumulates its queries' attention with an
online-softmax (flash-attention-style log-sum-exp carry), so peak memory is
O(seq_local^2) and communication rides the ICI ring.

Also provides `ulysses_attention`: the all-to-all alternative (seq-shard ->
head-shard re-partition), preferable when head_count >= sp_degree.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops._dispatch import defop
from . import mesh as mesh_mod

__all__ = ["ring_attention", "ulysses_attention", "sequence_parallel_attention"]


def _flash_ring_eligible(q, k):
    from ..core import flags as _flags
    if not _flags.flag("FLAGS_use_flash_attention"):
        return False
    if jax.default_backend() != "tpu" \
            and not _flags.flag("FLAGS_pallas_interpret"):
        return False
    from ..ops.pallas.flash_attention import supported
    return supported(tuple(q.shape), tuple(k.shape), tuple(k.shape))


def _ring_attention_flash(q, k, v, axis, causal, scale):
    """Ring attention with the Pallas flash kernel computing each KV
    block: the kernel returns (out, logsumexp) per block and blocks merge
    exactly in log-space. Causality per ring step resolves to one of three
    static cases — full (kv from an earlier rank), diagonal (own kv,
    causal mask), skip (future kv) — selected by lax.cond on the traced
    source rank, so each device compiles one program with an HLO
    conditional and never materializes masked work."""
    import jax.numpy as jnp
    from ..ops.pallas.flash_attention import flash_attention

    n = mesh_mod.mesh_axis_size(axis)
    my = lax.axis_index(axis)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, h, s, d = q.shape
    NEG = -1e9

    def merge(carry, o_i, lse_i):
        num, m, l = carry                       # [b,h,s,d] f32, [b,h,s] x2
        m_new = jnp.maximum(m, lse_i)
        sc_old = jnp.exp(m - m_new)
        sc_new = jnp.exp(lse_i - m_new)
        num = num * sc_old[..., None] + o_i.astype(jnp.float32) \
            * sc_new[..., None]
        return num, m_new, l * sc_old + sc_new

    def step(i, carry):
        k_cur, v_cur, num, m, l = carry
        src = (my - i) % n

        def full(_):
            return flash_attention(q, k_cur, v_cur, causal=False,
                                   scale=scale, return_lse=True)

        def diag(_):
            return flash_attention(q, k_cur, v_cur, causal=True,
                                   scale=scale, return_lse=True)

        def skip(_):
            return (jnp.zeros((b, h, s, d), q.dtype),
                    jnp.full((b, h, s), NEG, jnp.float32))

        if causal:
            o_i, lse_i = lax.cond(
                src < my, full,
                lambda op: lax.cond(src == my, diag, skip, op), None)
        else:
            o_i, lse_i = full(None)
        num, m, l = merge((num, m, l), o_i, lse_i)
        perm = [(j, (j + 1) % n) for j in range(n)]
        return (lax.ppermute(k_cur, axis, perm),
                lax.ppermute(v_cur, axis, perm), num, m, l)

    num0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s), NEG, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    carry = (k, v, num0, m0, l0)
    for i in range(n):  # unrolled: ppermute of i+1 overlaps compute of i
        carry = step(i, carry)
    _, _, num, m, l = carry
    out = num / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _ring_attention_raw(q, k, v, axis, causal, scale):
    """q,k,v: [batch, heads, seq_local, dim] per device; seq sharded on
    `axis`. Online-softmax accumulation over ring steps."""
    if _flash_ring_eligible(q, k):
        return _ring_attention_flash(q, k, v, axis, causal, scale)
    n = mesh_mod.mesh_axis_size(axis)
    my = lax.axis_index(axis)
    s_local = q.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    q_scaled = q * scale

    # global query positions for causal masking
    q_pos = my * s_local + jnp.arange(s_local)  # [s_local]

    def step(i, carry):
        k_cur, v_cur, o, m, l = carry
        # kv block i came from rank (my - i) mod n
        src = (my - i) % n
        k_pos = src * s_local + jnp.arange(s_local)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q_scaled, k_cur,
                            preferred_element_type=jnp.float32)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, -jnp.inf)
        blk_max = jnp.max(logits, axis=-1)                      # [b,h,q]
        m_new = jnp.maximum(m, blk_max)
        # guard fully-masked blocks (all -inf)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        correction = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * correction + jnp.sum(p, axis=-1)
        o_new = (o * correction[..., None]
                 + jnp.einsum("bhqk,bhkd->bhqd", p,
                              v_cur.astype(p.dtype)))
        # rotate kv to the next rank (ring over ICI)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_next = lax.ppermute(k_cur, axis, perm)
        v_next = lax.ppermute(v_cur, axis, perm)
        return (k_next, v_next, o_new, m_new, l_new)

    b, h, s, d = q.shape
    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    carry = (k, v, o0, m0, l0)
    # unrolled python loop: n is small (mesh dim); lets XLA overlap the
    # ppermute of step i+1 with the matmuls of step i
    for i in range(n):
        carry = step(i, carry)
    _, _, o, m, l = carry
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


@defop(name="ring_attention")
def _ring_attention_op(q, k, v, axis, causal, scale):
    return _ring_attention_raw(q, k, v, axis, causal, scale)


def ring_attention(q, k, v, axis="sp", causal=False, scale=None):
    """Per-device attention over ring-rotated KV. Call inside a shard_map
    region with the sequence axis sharded on `axis`; outside an SPMD region
    falls back to exact single-device attention."""
    if not mesh_mod.in_spmd_region(axis):
        from ..nn.functional import scaled_dot_product_attention
        return scaled_dot_product_attention(q, k, v, is_causal=causal,
                                            scale=scale, training=False)
    return _ring_attention_op(q, k, v, axis=axis, causal=causal, scale=scale)


def _ulysses_raw(q, k, v, axis, causal, scale):
    """All-to-all: [b, h, s/n, d] -> [b, h/n, s, d], full attention locally,
    then back (DeepSpeed-Ulysses style)."""
    n = mesh_mod.mesh_axis_size(axis)
    h = q.shape[1]
    assert h % n == 0, f"heads {h} not divisible by sp degree {n}"

    def seq_to_head(x):
        # split heads across ranks, gather sequence
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def head_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    sc = scale if scale is not None else q.shape[-1] ** -0.5
    if _flash_ring_eligible(qh, kh):
        # full-sequence local attention on the MXU via the flash kernel
        from ..ops.pallas.flash_attention import flash_attention
        out = flash_attention(qh, kh, vh, causal=causal, scale=sc)
        return head_to_seq(out)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh * sc, kh,
                        preferred_element_type=jnp.float32)
    if causal:
        s = logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1).astype(vh.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    return head_to_seq(out)


@defop(name="ulysses_attention")
def _ulysses_op(q, k, v, axis, causal, scale):
    return _ulysses_raw(q, k, v, axis, causal, scale)


def ulysses_attention(q, k, v, axis="sp", causal=False, scale=None):
    if not mesh_mod.in_spmd_region(axis):
        from ..nn.functional import scaled_dot_product_attention
        return scaled_dot_product_attention(q, k, v, is_causal=causal,
                                            scale=scale, training=False)
    return _ulysses_op(q, k, v, axis=axis, causal=causal, scale=scale)


def sequence_parallel_attention(q, k, v, mesh=None, axis="sp", causal=False,
                                scale=None, mode="ring"):
    """Convenience wrapper: shard full [b,h,s,d] arrays on the sequence axis
    and run ring/ulysses attention under shard_map."""
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from ..core.tensor import Tensor

    mesh = mesh or mesh_mod.auto_mesh()
    raw = [x._value if isinstance(x, Tensor) else x for x in (q, k, v)]
    spec = P(None, None, axis, None)
    fn = _ring_attention_raw if mode == "ring" else _ulysses_raw

    def local(ql, kl, vl):
        return fn(ql, kl, vl, axis, causal, scale)

    out = mesh_mod.shard_map(local, mesh=mesh,
                             in_specs=(spec, spec, spec),
                             out_specs=spec)(*raw)
    if isinstance(q, Tensor):
        return Tensor(out, stop_gradient=True, _internal=True)
    return out
