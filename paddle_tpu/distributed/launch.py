"""Process launcher: `python -m paddle_tpu.distributed.launch train.py`.

Analog of reference python/paddle/distributed/launch.py + utils.py
(get_cluster :297, start_local_trainers :424 setting the PADDLE_* env
contract and watching children). On TPU, one process per HOST (not per
chip): jax's single-controller runtime drives all local chips, so
single-host launches collapse to exec'ing the script with rank 0 env.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _build_env(rank, nranks, endpoints):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nranks),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "PADDLE_RANK_IN_NODE": str(rank),
        "FLAGS_selected_tpus": str(rank),
    })
    return env


def launch(script, script_args=(), nproc_per_node=1, host="127.0.0.1",
           start_port=6170):
    endpoints = [f"{host}:{start_port + i}" for i in range(nproc_per_node)]
    procs = []
    for rank in range(nproc_per_node):
        cmd = [sys.executable, script, *script_args]
        p = subprocess.Popen(cmd, env=_build_env(rank, nproc_per_node,
                                                 endpoints))
        procs.append(p)
    # watch loop (reference utils.py watch of child trainers)
    try:
        while procs:
            for p in list(procs):
                ret = p.poll()
                if ret is None:
                    continue
                procs.remove(p)
                if ret != 0:
                    for q in procs:
                        q.send_signal(signal.SIGTERM)
                    raise SystemExit(ret)
            time.sleep(0.5)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        raise
    return 0


def main():
    import argparse
    ap = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--started_port", type=int, default=6170)
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    return launch(args.script, args.script_args, args.nproc_per_node,
                  start_port=args.started_port)


if __name__ == "__main__":
    sys.exit(main())
