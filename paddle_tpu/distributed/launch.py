"""Process launcher: `python -m paddle_tpu.distributed.launch train.py`.

Analog of reference python/paddle/distributed/launch.py + utils.py
(get_cluster :297, start_local_trainers :424 setting the PADDLE_* env
contract and watching children). On TPU, one process per HOST (not per
chip): jax's single-controller runtime drives all local chips, so
single-host launches collapse to exec'ing the script with rank 0 env.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time

from .elastic import Supervisor, _reap

__all__ = ["launch", "launch_elastic", "launch_ps", "main"]


def _build_env(rank, nranks, endpoints):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nranks),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "PADDLE_RANK_IN_NODE": str(rank),
        "FLAGS_selected_tpus": str(rank),
    })
    return env


def launch(script, script_args=(), nproc_per_node=1, host="127.0.0.1",
           start_port=6170, elastic_retries=0):
    """Start one process per rank and watch them (reference
    utils.py:424 start_local_trainers + watch loop). With
    elastic_retries > 0, a failed job RESTARTS as a whole up to that many
    times — trainers resume from auto-checkpoint (incubate/checkpoint.py),
    the reference's elastic knob made concrete (its snapshot stubs it,
    distributed_strategy.py:1160; collective jobs can't hot-swap a rank
    mid-step, so whole-job restart from the latest step is the recovery
    unit)."""
    endpoints = [f"{host}:{start_port + i}" for i in range(nproc_per_node)]

    def start_all():
        return [subprocess.Popen([sys.executable, script, *script_args],
                                 env=_build_env(rank, nproc_per_node,
                                                endpoints))
                for rank in range(nproc_per_node)]

    attempt = 0
    while True:
        procs = start_all()
        failed_ret = None
        try:
            while procs:
                for p in list(procs):
                    ret = p.poll()
                    if ret is None:
                        continue
                    procs.remove(p)
                    if ret != 0:
                        # teardown must not hang on (or leak) a wedged
                        # sibling: TERM, bounded wait, escalate to KILL
                        _reap(procs)
                        procs.clear()
                        failed_ret = ret
                        # the snapshot is stale now — every sibling was
                        # just reaped; iterating on would re-remove them
                        break
                time.sleep(0.5)
        except KeyboardInterrupt:
            _reap(procs)
            raise
        if failed_ret is None:
            return 0
        attempt += 1
        if attempt > elastic_retries:
            raise SystemExit(failed_ret)
        print(f"[paddle_tpu.launch] job failed (rc={failed_ret}); elastic "
              f"restart {attempt}/{elastic_retries}", flush=True)


def launch_elastic(script, script_args=(), nproc_per_node=1,
                   host="127.0.0.1", start_port=6170, heartbeat_dir=None,
                   max_restarts=None, stall_timeout_s=None,
                   heartbeat_timeout_s=None, backoff_s=None):
    """Detection-driven elastic launch (`--elastic`): instead of the
    blind whole-job restart of `launch(elastic_retries=...)`, a
    `Supervisor` (distributed/elastic.py) watches each trainer's exit
    status AND its heartbeat file, and kills+restarts INDIVIDUAL
    trainers on death, heartbeat silence, or stalled step progress —
    with linear backoff and a PADDLE_ELASTIC_MAX_RESTARTS budget per
    rank. Trainers see the heartbeat directory as
    $PADDLE_ELASTIC_HEARTBEAT_DIR and should run a
    `Heartbeat(dir, step_fn=...)` + auto-checkpoint; restart recovery is
    exact via the verified checkpoint tier."""
    endpoints = [f"{host}:{start_port + i}" for i in range(nproc_per_node)]
    heartbeat_dir = heartbeat_dir or os.environ.get(
        "PADDLE_ELASTIC_HEARTBEAT_DIR")

    def start_rank(rank):
        env = _build_env(rank, nproc_per_node, endpoints)
        if heartbeat_dir:
            env["PADDLE_ELASTIC_HEARTBEAT_DIR"] = heartbeat_dir
        return subprocess.Popen([sys.executable, script, *script_args],
                                env=env)

    return Supervisor(start_rank, nranks=nproc_per_node,
                      heartbeat_dir=heartbeat_dir,
                      max_restarts=max_restarts,
                      stall_timeout_s=stall_timeout_s,
                      heartbeat_timeout_s=heartbeat_timeout_s,
                      backoff_s=backoff_s).run()


def launch_ps(script, script_args=(), server_num=1, worker_num=2,
              host="127.0.0.1", start_port=6270, elastic_retries=0):
    """PS-mode launcher (reference fleet launch --server_num/--worker_num,
    python/paddle/distributed/fleet/launch.py): starts server processes
    (TRAINING_ROLE=PSERVER) and worker processes (TRAINING_ROLE=TRAINER)
    with the PADDLE_PSERVERS_IP_PORT_LIST contract. The job succeeds when
    every WORKER exits 0 (servers are then terminated); a worker failure
    kills the job and, with elastic_retries > 0, restarts servers AND
    workers — scripts recover table state via PSClient.load_snapshot
    (large_scale_kv checkpointing analog)."""
    eps = [f"{host}:{start_port + i}" for i in range(server_num)]

    def start_all(attempt):
        base = dict(os.environ)
        base["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(eps)
        base["PADDLE_TRAINERS_NUM"] = str(worker_num)
        base["PADDLE_LAUNCH_ATTEMPT"] = str(attempt)
        servers = []
        for i in range(server_num):
            env = dict(base)
            env.update({"TRAINING_ROLE": "PSERVER",
                        "PADDLE_PSERVER_ID": str(i),
                        "PADDLE_PORT": eps[i].rsplit(":", 1)[1]})
            servers.append(subprocess.Popen(
                [sys.executable, script, *script_args], env=env))
        workers = []
        for i in range(worker_num):
            env = dict(base)
            env.update({"TRAINING_ROLE": "TRAINER",
                        "PADDLE_TRAINER_ID": str(i)})
            workers.append(subprocess.Popen(
                [sys.executable, script, *script_args], env=env))
        return servers, workers

    attempt = 0
    while True:
        servers, workers = start_all(attempt)
        failed_ret = None
        live = list(workers)
        try:
            while live and failed_ret is None:
                for p in list(live):
                    ret = p.poll()
                    if ret is None:
                        continue
                    live.remove(p)
                    if ret != 0:
                        failed_ret = ret
                for s in servers:          # a dead server fails the job
                    ret = s.poll()
                    if ret is not None and ret != 0 and failed_ret is None:
                        failed_ret = ret
                time.sleep(0.3)
        finally:
            # bounded reap with KILL escalation for EVERY child: a hung
            # server must neither raise TimeoutExpired through this
            # teardown nor leak the rest of the fleet
            _reap(live + servers, grace_s=30.0)
        if failed_ret is None:
            return 0
        attempt += 1
        if attempt > elastic_retries:
            raise SystemExit(failed_ret)
        print(f"[paddle_tpu.launch] ps job failed (rc={failed_ret}); "
              f"elastic restart {attempt}/{elastic_retries}", flush=True)


def main():
    import argparse
    ap = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--started_port", type=int, default=6170)
    ap.add_argument("--elastic_retries", type=int, default=0)
    ap.add_argument("--elastic", action="store_true",
                    help="supervisor-driven per-trainer restart "
                         "(heartbeat/stall detection, "
                         "PADDLE_ELASTIC_* knobs) instead of the "
                         "whole-job elastic_retries loop")
    ap.add_argument("--heartbeat_dir", default=None,
                    help="heartbeat directory for --elastic "
                         "(default $PADDLE_ELASTIC_HEARTBEAT_DIR)")
    ap.add_argument("--server_num", type=int, default=0)
    ap.add_argument("--worker_num", type=int, default=0)
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if args.server_num or args.worker_num:
        return launch_ps(args.script, args.script_args,
                         server_num=max(args.server_num, 1),
                         worker_num=max(args.worker_num, 1),
                         start_port=args.started_port,
                         elastic_retries=args.elastic_retries)
    if args.elastic:
        return launch_elastic(args.script, args.script_args,
                              args.nproc_per_node,
                              start_port=args.started_port,
                              heartbeat_dir=args.heartbeat_dir)
    return launch(args.script, args.script_args, args.nproc_per_node,
                  start_port=args.started_port,
                  elastic_retries=args.elastic_retries)


if __name__ == "__main__":
    sys.exit(main())
