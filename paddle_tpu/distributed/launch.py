"""Process launcher: `python -m paddle_tpu.distributed.launch train.py`.

Analog of reference python/paddle/distributed/launch.py + utils.py
(get_cluster :297, start_local_trainers :424 setting the PADDLE_* env
contract and watching children). On TPU, one process per HOST (not per
chip): jax's single-controller runtime drives all local chips, so
single-host launches collapse to exec'ing the script with rank 0 env.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _build_env(rank, nranks, endpoints):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nranks),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "PADDLE_RANK_IN_NODE": str(rank),
        "FLAGS_selected_tpus": str(rank),
    })
    return env


def launch(script, script_args=(), nproc_per_node=1, host="127.0.0.1",
           start_port=6170, elastic_retries=0):
    """Start one process per rank and watch them (reference
    utils.py:424 start_local_trainers + watch loop). With
    elastic_retries > 0, a failed job RESTARTS as a whole up to that many
    times — trainers resume from auto-checkpoint (incubate/checkpoint.py),
    the reference's elastic knob made concrete (its snapshot stubs it,
    distributed_strategy.py:1160; collective jobs can't hot-swap a rank
    mid-step, so whole-job restart from the latest step is the recovery
    unit)."""
    endpoints = [f"{host}:{start_port + i}" for i in range(nproc_per_node)]

    def start_all():
        return [subprocess.Popen([sys.executable, script, *script_args],
                                 env=_build_env(rank, nproc_per_node,
                                                endpoints))
                for rank in range(nproc_per_node)]

    attempt = 0
    while True:
        procs = start_all()
        failed_ret = None
        try:
            while procs:
                for p in list(procs):
                    ret = p.poll()
                    if ret is None:
                        continue
                    procs.remove(p)
                    if ret != 0:
                        for q in procs:
                            q.send_signal(signal.SIGTERM)
                        for q in procs:
                            q.wait()
                        procs.clear()
                        failed_ret = ret
                time.sleep(0.5)
        except KeyboardInterrupt:
            for p in procs:
                p.send_signal(signal.SIGTERM)
            raise
        if failed_ret is None:
            return 0
        attempt += 1
        if attempt > elastic_retries:
            raise SystemExit(failed_ret)
        print(f"[paddle_tpu.launch] job failed (rc={failed_ret}); elastic "
              f"restart {attempt}/{elastic_retries}", flush=True)


def launch_ps(script, script_args=(), server_num=1, worker_num=2,
              host="127.0.0.1", start_port=6270, elastic_retries=0):
    """PS-mode launcher (reference fleet launch --server_num/--worker_num,
    python/paddle/distributed/fleet/launch.py): starts server processes
    (TRAINING_ROLE=PSERVER) and worker processes (TRAINING_ROLE=TRAINER)
    with the PADDLE_PSERVERS_IP_PORT_LIST contract. The job succeeds when
    every WORKER exits 0 (servers are then terminated); a worker failure
    kills the job and, with elastic_retries > 0, restarts servers AND
    workers — scripts recover table state via PSClient.load_snapshot
    (large_scale_kv checkpointing analog)."""
    eps = [f"{host}:{start_port + i}" for i in range(server_num)]

    def start_all(attempt):
        base = dict(os.environ)
        base["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(eps)
        base["PADDLE_TRAINERS_NUM"] = str(worker_num)
        base["PADDLE_LAUNCH_ATTEMPT"] = str(attempt)
        servers = []
        for i in range(server_num):
            env = dict(base)
            env.update({"TRAINING_ROLE": "PSERVER",
                        "PADDLE_PSERVER_ID": str(i),
                        "PADDLE_PORT": eps[i].rsplit(":", 1)[1]})
            servers.append(subprocess.Popen(
                [sys.executable, script, *script_args], env=env))
        workers = []
        for i in range(worker_num):
            env = dict(base)
            env.update({"TRAINING_ROLE": "TRAINER",
                        "PADDLE_TRAINER_ID": str(i)})
            workers.append(subprocess.Popen(
                [sys.executable, script, *script_args], env=env))
        return servers, workers

    attempt = 0
    while True:
        servers, workers = start_all(attempt)
        failed_ret = None
        live = list(workers)
        try:
            while live and failed_ret is None:
                for p in list(live):
                    ret = p.poll()
                    if ret is None:
                        continue
                    live.remove(p)
                    if ret != 0:
                        failed_ret = ret
                for s in servers:          # a dead server fails the job
                    ret = s.poll()
                    if ret is not None and ret != 0 and failed_ret is None:
                        failed_ret = ret
                time.sleep(0.3)
        finally:
            for p in live + servers:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in live + servers:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
        if failed_ret is None:
            return 0
        attempt += 1
        if attempt > elastic_retries:
            raise SystemExit(failed_ret)
        print(f"[paddle_tpu.launch] ps job failed (rc={failed_ret}); "
              f"elastic restart {attempt}/{elastic_retries}", flush=True)


def main():
    import argparse
    ap = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--started_port", type=int, default=6170)
    ap.add_argument("--elastic_retries", type=int, default=0)
    ap.add_argument("--server_num", type=int, default=0)
    ap.add_argument("--worker_num", type=int, default=0)
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if args.server_num or args.worker_num:
        return launch_ps(args.script, args.script_args,
                         server_num=max(args.server_num, 1),
                         worker_num=max(args.worker_num, 1),
                         start_port=args.started_port,
                         elastic_retries=args.elastic_retries)
    return launch(args.script, args.script_args, args.nproc_per_node,
                  start_port=args.started_port,
                  elastic_retries=args.elastic_retries)


if __name__ == "__main__":
    sys.exit(main())
