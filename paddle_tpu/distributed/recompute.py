"""Recompute (activation checkpointing).

Analog of the reference RecomputeOptimizer (fluid/optimizer.py:4526) and
`_append_backward_ops_with_checkpoints_` (fluid/backward.py:701): segments
between checkpoints are re-run in the backward pass instead of storing
their activations.

TPU-native design delta: the reference rewrites the Program, duplicating
forward ops into the backward block. Here rematerialization is a property
of the *trace* — `jax.checkpoint` marks a function so XLA drops its
residuals and recomputes them when the cotangents arrive. Three surfaces:

- `recompute(fn, *args)` — manual wrapper (reference
  paddle.distributed.fleet.utils.recompute);
- `Layer.enable_recompute()` — per-layer marker consumed by Layer.__call__;
- `DistributedStrategy.recompute` — strategy knob applied by the hapi
  engine (transformer blocks by default / name patterns) and by the static
  Program lowering (op-list segments split at
  recompute_configs["checkpoints"] variables, executor.py).

Policies map to jax.checkpoint_policies: "nothing" (save nothing, full
recompute — the reference's semantics) and "dots" (save MXU matmul
results, recompute the cheap elementwise chains — usually the best
flops/memory trade on TPU).
"""
from __future__ import annotations

import jax

__all__ = ["recompute", "checkpoint_policy"]


def checkpoint_policy(name):
    if name in (None, "nothing", "nothing_saveable"):
        return None  # jax.checkpoint default: save nothing
    if name in ("dots", "dots_saveable"):
        return jax.checkpoint_policies.dots_saveable
    if name in ("dots_no_batch", "dots_with_no_batch_dims_saveable"):
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"unknown recompute policy {name!r}")


def recompute(function, *args, policy="nothing", **kwargs):
    """Run `function(*args, **kwargs)` so its activations are rematerialized
    in the backward pass rather than stored.

    Effective inside a compiled step (hapi engine, static Executor,
    jit-traced user steps) where jax.grad differentiates the whole trace.
    In eager implicit-graph mode the per-op tape already owns residuals —
    there is no XLA program to rematerialize — so this is a passthrough,
    like the reference's recompute with no backward pass requested.
    """
    from ..core import tape as _tape
    from ..core.tensor import Tensor

    if _tape.is_grad_enabled():
        return function(*args, **kwargs)

    is_t = lambda x: isinstance(x, Tensor)  # noqa: E731
    raw = [a._value if is_t(a) else a for a in args]
    # A checkpointed function must be pure: the backward replay re-runs it,
    # so stochastic ops (dropout) must draw the SAME keys both times. Pull
    # one key from the ambient chain (advancing it exactly once) and re-seat
    # the chain on it inside — replay then reproduces the forward stream.
    from ..core import rng as _rng
    key = _rng.next_key()

    def raw_fn(key, *vals):
        targs = [Tensor(v, _internal=True) if is_t(a) else v
                 for a, v in zip(args, vals)]
        with _rng.rng_state(key):
            out = function(*targs, **kwargs)
        return jax.tree_util.tree_map(
            lambda t: t._value if is_t(t) else t, out, is_leaf=is_t)

    pol = checkpoint_policy(policy)
    ck = jax.checkpoint(raw_fn, policy=pol)
    out_vals = ck(key, *raw)
    return jax.tree_util.tree_map(
        lambda v: Tensor(v, _internal=True), out_vals)
