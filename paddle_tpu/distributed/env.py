"""Distributed environment contract.

Analog of the reference's PADDLE_* env protocol
(reference: python/paddle/distributed/utils.py:406-409 sets
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS;
parallel.py:138-141 ParallelEnv reads them). On TPU the same variables
select the jax process (multi-host) and data-parallel rank.
"""
from __future__ import annotations

import os

__all__ = ["ParallelEnv", "get_rank", "get_world_size"]


def get_rank() -> int:
    v = os.environ.get("PADDLE_TRAINER_ID")
    if v is not None:
        return int(v)
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    v = os.environ.get("PADDLE_TRAINERS_NUM")
    if v is not None:
        return int(v)
    try:
        import jax
        return jax.process_count()
    except Exception:
        return 1


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_RANK_IN_NODE", get_rank()))

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else [self.current_endpoint]
