"""Failure detection and elastic restart.

Analog of the reference's liveness machinery: HeartBeatMonitor
(operators/distributed/heart_beat_monitor.cc — tracks worker heartbeats,
completes barriers when workers die), the launcher watch loop
(distributed/utils.py:424), and the `DistributedStrategy.elastic` knob
(a stub in the reference snapshot, fleet/base/distributed_strategy.py:1160).

TPU-native scoping (SURVEY §5.3): collective jobs can't paper over a lost
process mid-step — recovery is restart-from-checkpoint, which
incubate/checkpoint.py makes exact. What belongs HERE is detection and
supervision: a heartbeat any watcher can read, a stall monitor that fires
a callback when training stops progressing (hung collective, dead input
pipeline), and launcher-side restart of failed trainers
(distributed/launch.py --elastic), which resume via auto-checkpoint.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

__all__ = ["Heartbeat", "StallMonitor"]


class Heartbeat:
    """Periodic liveness file: {dir}/heartbeat_{rank}.json holding rank,
    step, timestamp (the HeartBeatMonitor's UPDATE side; any supervisor —
    the launcher, an operator, a dashboard — is the CHECK side)."""

    def __init__(self, directory, rank=None, interval_s=10.0):
        from .env import get_rank
        os.makedirs(directory, exist_ok=True)
        self.rank = get_rank() if rank is None else rank
        self.path = os.path.join(directory, f"heartbeat_{self.rank}.json")
        self.interval_s = interval_s
        self._step = 0
        self._stop = threading.Event()
        self._thread = None

    def update(self, step=None):
        if step is not None:
            self._step = int(step)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"rank": self.rank, "step": self._step,
                       "time": time.time()}, f)
        os.replace(tmp, self.path)

    def start(self):
        def beat():
            while not self._stop.wait(self.interval_s):
                self.update()
        self.update()
        self._thread = threading.Thread(target=beat, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    @staticmethod
    def check(directory, timeout_s=60.0):
        """Supervisor side: ranks whose heartbeat is stale (dead/hung).

        Never raises on bad beat files: the supervisor is the one process
        that must outlive everything else, and a trainer dying mid-write
        (or a vanished file, or a corrupted disk) is exactly the moment
        it's needed. A heartbeat that can't be read or parsed counts as
        STALE — liveness must be proven, not assumed."""
        now = time.time()
        stale = []
        try:
            names = sorted(os.listdir(directory))
        except OSError:
            return []   # directory gone: nothing provably alive OR dead
        for name in names:
            # only committed beat files; skips the atomic-write .tmp twin
            if not (name.startswith("heartbeat_")
                    and name.endswith(".json")):
                continue
            try:
                rank = int(name[len("heartbeat_"):-len(".json")])
            except ValueError:
                continue
            try:
                with open(os.path.join(directory, name)) as f:
                    rec = json.load(f)
                beat_time = float(rec["time"])
                rank = int(rec.get("rank", rank))
            except (OSError, ValueError, KeyError, TypeError):
                # corrupt / partial / vanished mid-check → stale rank
                stale.append(rank)
                continue
            if now - beat_time > timeout_s:
                stale.append(rank)
        return stale


class StallMonitor:
    """Fires `on_stall` when no step completes for `timeout_s` — a hung
    collective or dead input pipeline looks exactly like this (the
    reference's heartbeat CHECK loop, heart_beat_monitor.cc:?? applied to
    single-controller training)."""

    def __init__(self, timeout_s=300.0,
                 on_stall: Optional[Callable[[float], None]] = None):
        self.timeout_s = timeout_s
        self.on_stall = on_stall or (lambda dt: print(
            f"[paddle_tpu] WARNING: no training step for {dt:.0f}s — "
            "hung collective or starved input pipeline?", flush=True))
        self._last = time.time()
        self._stop = threading.Event()
        self._thread = None
        self.stalled = False

    def step_done(self):
        self._last = time.time()
        self.stalled = False

    def start(self):
        def watch():
            while not self._stop.wait(min(self.timeout_s / 4, 30.0)):
                dt = time.time() - self._last
                if dt > self.timeout_s and not self.stalled:
                    self.stalled = True
                    self.on_stall(dt)
        self._thread = threading.Thread(target=watch, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
