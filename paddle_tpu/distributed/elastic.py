"""Failure detection and elastic restart.

Analog of the reference's liveness machinery: HeartBeatMonitor
(operators/distributed/heart_beat_monitor.cc — tracks worker heartbeats,
completes barriers when workers die), the launcher watch loop
(distributed/utils.py:424), and the `DistributedStrategy.elastic` knob
(a stub in the reference snapshot, fleet/base/distributed_strategy.py:1160).

TPU-native scoping (SURVEY §5.3): collective jobs can't paper over a lost
process mid-step — recovery is restart-from-checkpoint, which
incubate/checkpoint.py makes exact. What belongs HERE is detection and
supervision: a heartbeat any watcher can read, a stall monitor that fires
a callback when training stops progressing (hung collective, dead input
pipeline), and the `Supervisor` — the launcher-side loop
(distributed/launch.py --elastic) that kills and restarts individual
trainers on death, heartbeat loss, or stalled progress, with backoff and
a PADDLE_ELASTIC_MAX_RESTARTS budget. Restarted trainers resume exactly
via the verified auto-checkpoint tier.

The training loops (hapi fit, Executor.train_from_dataset, the
PipelineRunner hot loop) call `notify_step()` once per completed step;
every started StallMonitor and Heartbeat registers itself as a listener,
so liveness reflects REAL progress instead of a stale counter.
"""
from __future__ import annotations

import json
import os
import subprocess
import threading
import time
from typing import Callable, Optional

__all__ = ["Heartbeat", "StallMonitor", "Supervisor", "notify_step"]

# Started StallMonitor/Heartbeat instances; notify_step() fans the
# training loops' per-step pulse out to them. A plain set + lock — the
# pulse is one dict lookup when nothing is registered.
_listeners_lock = threading.Lock()
_listeners: set = set()


def notify_step(step=None):
    """One completed training step: refresh every active StallMonitor /
    Heartbeat. Called by the training hot loops (hapi fit,
    Executor.train_from_dataset, PipelineRunner.submit*)."""
    if not _listeners:
        return
    with _listeners_lock:
        targets = list(_listeners)
    for t in targets:
        try:
            t.step_done(step)
        except Exception:
            pass


def _register(listener):
    with _listeners_lock:
        _listeners.add(listener)


def _unregister(listener):
    with _listeners_lock:
        _listeners.discard(listener)


class Heartbeat:
    """Periodic liveness file: {dir}/heartbeat_{rank}.json holding rank,
    step, timestamp (the HeartBeatMonitor's UPDATE side; any supervisor —
    the launcher, an operator, a dashboard — is the CHECK side).

    The beat thread writes the LIVE step: `step_fn` (a callable returning
    the current global step) wins, else the shared counter refreshed by
    `notify_step()` / `update(step=...)` — a beat between update() calls
    no longer re-writes a stale step."""

    def __init__(self, directory, rank=None, interval_s=10.0,
                 step_fn: Optional[Callable[[], int]] = None):
        from .env import get_rank
        os.makedirs(directory, exist_ok=True)
        self.rank = get_rank() if rank is None else rank
        self.path = os.path.join(directory, f"heartbeat_{self.rank}.json")
        self.interval_s = interval_s
        self._step = 0
        self._step_fn = step_fn
        self._stop = threading.Event()
        self._thread = None

    def step_done(self, step=None):
        """notify_step() listener: training advanced one step."""
        if step is not None:
            self._step = int(step)
        else:
            self._step += 1

    def update(self, step=None):
        if step is not None:
            self._step = int(step)
        if self._step_fn is not None:
            try:
                self._step = int(self._step_fn())
            except Exception:
                pass
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"rank": self.rank, "step": self._step,
                       "time": time.time()}, f)
        os.replace(tmp, self.path)

    def start(self):
        def beat():
            while not self._stop.wait(self.interval_s):
                self.update()
        self.update()
        self._thread = threading.Thread(target=beat, daemon=True)
        self._thread.start()
        _register(self)
        return self

    def stop(self):
        self._stop.set()
        _unregister(self)

    @staticmethod
    def read(directory):
        """Supervisor side: {rank: {"step", "time"}} for every readable
        committed beat file; unreadable/corrupt files map to None."""
        out = {}
        try:
            names = sorted(os.listdir(directory))
        except OSError:
            return out
        for name in names:
            if not (name.startswith("heartbeat_")
                    and name.endswith(".json")):
                continue
            try:
                rank = int(name[len("heartbeat_"):-len(".json")])
            except ValueError:
                continue
            try:
                with open(os.path.join(directory, name)) as f:
                    rec = json.load(f)
                out[int(rec.get("rank", rank))] = {
                    "step": int(rec.get("step", 0)),
                    "time": float(rec["time"])}
            except (OSError, ValueError, KeyError, TypeError):
                out[rank] = None
        return out

    @staticmethod
    def check(directory, timeout_s=60.0):
        """Supervisor side: ranks whose heartbeat is stale (dead/hung).

        Never raises on bad beat files: the supervisor is the one process
        that must outlive everything else, and a trainer dying mid-write
        (or a vanished file, or a corrupted disk) is exactly the moment
        it's needed. A heartbeat that can't be read or parsed counts as
        STALE — liveness must be proven, not assumed. Publishes the
        oldest readable beat's age as the `elastic.heartbeat_age_s`
        gauge."""
        from ..core import monitor as _monitor
        now = time.time()
        stale, ages = [], []
        for rank, rec in Heartbeat.read(directory).items():
            if rec is None:
                # corrupt / partial / vanished mid-check → stale rank
                stale.append(rank)
                continue
            age = now - rec["time"]
            ages.append(age)
            if age > timeout_s:
                stale.append(rank)
        if ages:
            _monitor.stat_set("elastic.heartbeat_age_s", max(ages))
        return stale


def _default_on_stall(dt):
    """A stall is a failure in progress: count it, flight-record the
    span/metric history (the stall's only timeline — no exception will
    ever carry it), and warn."""
    from ..core import flight_recorder as _fr
    from ..core import monitor as _monitor
    _monitor.stat_add("elastic.stalls")
    _fr.dump("stall", extra={"stalled_s": dt})
    print(f"[paddle_tpu] WARNING: no training step for {dt:.0f}s — "
          "hung collective or starved input pipeline?", flush=True)


class StallMonitor:
    """Fires `on_stall` when no step completes for `timeout_s` — a hung
    collective or dead input pipeline looks exactly like this (the
    reference's heartbeat CHECK loop, heart_beat_monitor.cc applied to
    single-controller training). Started monitors register as
    `notify_step()` listeners, so the training loops feed them without
    holding a reference. The default `on_stall` bumps `elastic.stalls`
    and writes a flight-recorder dump (reason=stall)."""

    def __init__(self, timeout_s=300.0,
                 on_stall: Optional[Callable[[float], None]] = None):
        self.timeout_s = timeout_s
        self.on_stall = on_stall or _default_on_stall
        self._last = time.time()
        self._stop = threading.Event()
        self._thread = None
        self.stalled = False

    def step_done(self, step=None):
        self._last = time.time()
        self.stalled = False

    def start(self):
        def watch():
            while not self._stop.wait(min(self.timeout_s / 4, 30.0)):
                dt = time.time() - self._last
                if dt > self.timeout_s and not self.stalled:
                    self.stalled = True
                    self.on_stall(dt)
        self._thread = threading.Thread(target=watch, daemon=True)
        self._thread.start()
        _register(self)
        return self

    def stop(self):
        self._stop.set()
        _unregister(self)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def _reap(procs, grace_s=5.0, term_first=True):
    """Terminate a set of child processes WITHOUT ever hanging the
    supervisor: TERM (optional grace), then KILL on timeout, and keep
    iterating — one wedged child must not leak its siblings."""
    import signal as _signal
    procs = [p for p in procs if p is not None]
    if term_first:
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(_signal.SIGTERM)
                except OSError:
                    pass
    for p in procs:
        try:
            p.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            try:
                p.kill()
            except OSError:
                pass
            try:
                p.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                pass  # unreapable (kernel-stuck); nothing more to do
        except OSError:
            pass


class Supervisor:
    """Per-trainer kill+restart supervision (the launcher watch loop
    grown into detection-driven recovery; distributed/launch.py
    --elastic builds one).

    `start_fn(rank) -> subprocess.Popen` launches one trainer; the
    supervisor then watches for three failure shapes:

    - DEATH: the child exited non-zero (SIGKILL'd counts) — restart it;
    - SILENCE: its heartbeat file is older than heartbeat_timeout_s (or
      unreadable) — kill + restart;
    - STALL: the heartbeat keeps beating but its step counter hasn't
      advanced for stall_timeout_s — flight-record, kill + restart.

    Each restart backs off linearly (backoff_s x restarts) and burns one
    unit of that rank's PADDLE_ELASTIC_MAX_RESTARTS budget; an exhausted
    budget tears the whole job down and raises SystemExit with the
    child's status. Ranks that exit 0 are done. Restarted trainers
    recover exactly via the verified checkpoint tier
    (incubate/checkpoint.py) — supervision is only safe because resume
    is exact."""

    def __init__(self, start_fn, nranks=1, heartbeat_dir=None,
                 max_restarts=None, backoff_s=None,
                 heartbeat_timeout_s=None, stall_timeout_s=None,
                 poll_s=0.2):
        from ..core.flags import flag as _flag
        self._start = start_fn
        self.nranks = int(nranks)
        self.heartbeat_dir = heartbeat_dir
        self.max_restarts = int(_flag("PADDLE_ELASTIC_MAX_RESTARTS")
                                if max_restarts is None else max_restarts)
        self.backoff_s = float(_flag("PADDLE_ELASTIC_RESTART_BACKOFF_S")
                               if backoff_s is None else backoff_s)
        self.heartbeat_timeout_s = float(
            _flag("PADDLE_ELASTIC_HEARTBEAT_TIMEOUT_S")
            if heartbeat_timeout_s is None else heartbeat_timeout_s)
        self.stall_timeout_s = float(
            _flag("PADDLE_ELASTIC_STALL_TIMEOUT_S")
            if stall_timeout_s is None else stall_timeout_s)
        self.poll_s = float(poll_s)
        self.restarts = {r: 0 for r in range(self.nranks)}
        self.events: list = []   # (time, rank, reason) timeline

    # -- internals -----------------------------------------------------------
    def _note(self, rank, reason):
        from ..core import monitor as _monitor
        self.events.append((time.time(), rank, reason))
        _monitor.stat_add("elastic.restarts")
        print(f"[paddle_tpu.elastic] rank {rank}: {reason}; restart "
              f"{self.restarts[rank]}/{self.max_restarts}", flush=True)

    def _restart(self, procs, rank, reason, rc=None):
        from ..core import flight_recorder as _fr
        self.restarts[rank] += 1
        if self.restarts[rank] > self.max_restarts:
            _fr.dump("elastic_budget_exhausted",
                     extra={"rank": rank, "reason": reason,
                           "restarts": self.restarts[rank] - 1})
            _reap(list(procs.values()))
            raise SystemExit(rc if rc not in (None, 0) else 1)
        self._note(rank, reason)
        _fr.dump("elastic_restart", extra={"rank": rank, "reason": reason,
                                           "restart": self.restarts[rank]})
        _reap([procs[rank]])
        time.sleep(self.backoff_s * self.restarts[rank])
        procs[rank] = self._start(rank)

    def run(self):
        procs = {rank: self._start(rank) for rank in range(self.nranks)}
        done: set = set()
        # per-rank progress tracking for stall detection, plus the
        # current attempt's start time: a beat file written BEFORE it
        # belongs to a previous incarnation (or a previous job in the
        # same heartbeat dir) and proves neither liveness nor death —
        # without this, one silence restart storms (the stale file
        # outlives the kill, so every poll of the restarting child
        # burns another restart until the budget fails the job)
        last_step = {r: None for r in range(self.nranks)}
        step_time = {r: time.time() for r in range(self.nranks)}
        started = {r: time.time() for r in range(self.nranks)}

        def _reset(rank):
            started[rank] = step_time[rank] = time.time()
            last_step[rank] = None

        try:
            while len(done) < self.nranks:
                beats = (Heartbeat.read(self.heartbeat_dir)
                         if self.heartbeat_dir else {})
                now = time.time()
                for rank in range(self.nranks):
                    if rank in done:
                        continue
                    p = procs[rank]
                    rc = p.poll()
                    if rc == 0:
                        done.add(rank)
                        continue
                    if rc is not None:
                        self._restart(procs, rank, f"exited rc={rc}",
                                      rc=rc)
                        _reset(rank)
                        continue
                    if not self.heartbeat_dir:
                        continue
                    rec = beats.get(rank)
                    if rec is not None and rec["time"] < started[rank]:
                        rec = None   # a previous incarnation's beat
                    if rec is None:
                        # no beat from THIS attempt yet: grant the
                        # startup window before declaring silence
                        if now - started[rank] > self.heartbeat_timeout_s:
                            self._restart(procs, rank,
                                          "heartbeat missing/unreadable")
                            _reset(rank)
                        continue
                    if now - rec["time"] > self.heartbeat_timeout_s:
                        self._restart(procs, rank,
                                      f"heartbeat stale "
                                      f"({now - rec['time']:.1f}s)")
                        _reset(rank)
                        continue
                    if rec["step"] != last_step[rank]:
                        last_step[rank] = rec["step"]
                        step_time[rank] = now
                    elif now - step_time[rank] > self.stall_timeout_s:
                        from ..core import monitor as _monitor
                        _monitor.stat_add("elastic.stalls")
                        self._restart(
                            procs, rank,
                            f"stalled at step {rec['step']} for "
                            f"{now - step_time[rank]:.1f}s")
                        _reset(rank)
                time.sleep(self.poll_s)
        except KeyboardInterrupt:
            _reap(list(procs.values()))
            raise
        except SystemExit:
            raise
        except BaseException:
            _reap(list(procs.values()))
            raise
        return 0
