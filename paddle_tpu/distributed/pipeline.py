"""Pipeline parallelism.

Analog of reference PipelineOptimizer + PipelineTrainer/SectionWorker
(python/paddle/fluid/optimizer.py:3695 program splitter;
framework/section_worker.cc:61-117 — per-microbatch forward for all, then
backward for all, optimizer once: GPipe F-then-B).

TPU design delta (SURVEY.md §2.2 "PP"): no per-stage programs or section
threads. All pp ranks run ONE SPMD program under shard_map: stage 0 injects
a fresh microbatch each tick, activations hop to the next stage via
collective-permute, and the last stage emits finished microbatches. The
backward schedule is jax AD of this loop — F-then-B falls out of
differentiating it; XLA overlaps each tick's ppermute with the next tick's
stage matmuls on ICI.

Stages must be homogeneous (hidden -> hidden, same shape/dtype): apply the
embedding before entering the pipeline and the head after, as in standard
SPMD pipelining. PipelineLayer (fleet.meta_parallel) produces the per-rank
stage function.
"""
from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax

from . import mesh as mesh_mod

__all__ = ["micro_batch", "gpipe", "interleaved", "pipeline_loss",
           "bubble_fraction", "schedule_ticks", "schedule_collectives"]


def micro_batch(x, num_micro):
    """[B, ...] -> [num_micro, B/num_micro, ...]"""
    b = x.shape[0]
    assert b % num_micro == 0, (b, num_micro)
    return x.reshape((num_micro, b // num_micro) + x.shape[1:])


def gpipe(stage_fn: Callable, x_micro, axis: str = "pp", schedule="gpipe"):
    """Pipelined forward inside shard_map.

    stage_fn(h) -> h: THIS rank's stage (closed over its local params),
    hidden-shaped in and out. x_micro: [M, mb, ...] hidden-shaped
    microbatches (only stage 0 actually consumes them).
    Returns [M, mb, ...]; entries are the completed pipeline outputs on the
    LAST stage (garbage elsewhere — mask by rank).

    schedule:
      - "gpipe": plain F-then-B under AD (reference section_worker.cc
        :61-117 semantics) — residuals for all M microbatches live at once.
      - "1f1b": each tick is wrapped in jax.checkpoint, so AD stores only
        the tick-boundary hidden states (O(M+n) hiddens) and recomputes
        intra-stage activations when that microbatch's backward fires —
        the activation-stash bound that motivates the classic 1F1B
        schedule (Megatron PipeDream-flush), expressed the SPMD way.

    Design note: under single-program SPMD all ranks trace ONE program, so
    a literally rank-divergent 1F1B tick order (warmup depth n-1-r) can't
    be expressed — ranks would need different collective sequences. What
    the schedule buys — bounded activation memory and back-pressure — is
    what "1f1b" provides via per-tick remat; the compute-skip of idle
    ticks remains masked, exactly as the reference's bubble ticks idle.
    """
    n = mesh_mod.mesh_axis_size(axis)
    rank = lax.axis_index(axis)
    M = x_micro.shape[0]
    ticks = M + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]
    is_first = (rank == 0)

    tick_fn = stage_fn
    if schedule == "1f1b":
        import jax
        tick_fn = jax.checkpoint(stage_fn)
    elif schedule != "gpipe":
        raise ValueError(f"unknown pipeline schedule {schedule!r}")

    carry = jnp.zeros_like(x_micro[0])
    outs = jnp.zeros_like(x_micro)
    for t in range(ticks):
        inject = x_micro[min(t, M - 1)]
        h = jnp.where(is_first, inject, carry)
        h_out = tick_fn(h)
        mb_done = t - (n - 1)
        if 0 <= mb_done < M:
            outs = outs.at[mb_done].set(h_out)
        carry = lax.ppermute(h_out, axis, perm)
    return outs


def interleaved(chunk_fns, x_micro, axis: str = "pp", remat=True):
    """Interleaved virtual-stage pipeline (Megatron interleaved 1F1B,
    expressed as one SPMD program): each rank holds v chunks; global stage
    c*n + r is chunk c on rank r. Microbatches circulate the ring v times,
    in groups of n; each tick every rank runs ONE chunk, selected by
    lax.switch on ((t - rank) // n) mod v — the switch is the
    SPMD-expressible form of the rank-divergent interleaved tick order.

    Ticks = v*M + n - 1, vs gpipe's (M + n - 1) ticks of v-chunk-deep
    compute = v*(M + n - 1) chunk-times: the bubble shrinks from
    (n-1)/(M+n-1) to (n-1)/(v*M+n-1) of the schedule (reference analog:
    section_worker.cc has no interleaving; this is the new-capability
    half of VERDICT r04 item 7).

    chunk_fns: list of v hidden->hidden fns (this rank's chunks, shallow
    to deep). M must be a multiple of n (inject in groups of n).
    Returns [M, mb, ...] finished outputs, real on the LAST stage.
    """
    import jax
    n = mesh_mod.mesh_axis_size(axis)
    v = len(chunk_fns)
    rank = lax.axis_index(axis)
    M = x_micro.shape[0]
    if M % n != 0:
        raise ValueError(
            f"interleaved schedule needs num_micro ({M}) divisible by the "
            f"pp size ({n}) — microbatches inject in groups of n")
    ticks = v * M + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]
    is_first = (rank == 0)
    fns = [jax.checkpoint(f) if remat else f for f in chunk_fns]

    carry = jnp.zeros_like(x_micro[0])
    outs = jnp.zeros_like(x_micro)
    for t in range(ticks):
        # this rank's chunk for this tick (traced in rank, static in t)
        kidx = jnp.mod(jnp.floor_divide(jnp.maximum(t - rank, 0), n),
                       v).astype(jnp.int32)
        # rank 0's chunk index is static: injection ticks are known
        inj_group = t // (v * n)
        injecting = ((t // n) % v == 0) and (inj_group * n + t % n) < M
        if injecting:
            m_inj = inj_group * n + t % n
            h = jnp.where(is_first, x_micro[m_inj], carry)
        else:
            h = carry
        h_out = lax.switch(kidx, fns, h)
        # completion at the last rank is equally static per tick
        tb = t - (n - 1)
        if tb >= 0 and (tb // n) % v == v - 1:
            m_done = (tb // (v * n)) * n + tb % n
            if m_done < M:
                outs = outs.at[m_done].set(h_out)
        carry = lax.ppermute(h_out, axis, perm)
    return outs


def schedule_ticks(num_micro: int, num_stages: int, schedule: str = "gpipe",
                   num_virtual: int = 1) -> int:
    """Chunk-time ticks a schedule takes (the step-time accounting the
    reference leaves implicit in SectionWorker): gpipe/1f1b run M+n-1
    ticks of full per-rank depth (= v chunk-times each); interleaved runs
    v*M + n - 1 single-chunk ticks.

    Degenerate shapes price sanely instead of going negative: a
    single-stage "pipeline" (n=1) is just M serial microbatches (v*M
    ticks), and M < n still runs M+n-1 ticks (mostly bubble — the cost
    model must SEE that, not crash)."""
    num_micro = max(int(num_micro), 0)
    num_stages = max(int(num_stages), 1)
    num_virtual = max(int(num_virtual), 1)
    if num_micro == 0:
        return 0
    if schedule == "interleaved":
        return num_virtual * num_micro + num_stages - 1
    return num_virtual * (num_micro + num_stages - 1)


def pipeline_loss(stage_fn, loss_fn, x_micro, labels_micro, axis="pp",
                  schedule="gpipe"):
    """Mean microbatch loss of the pipelined stack; identical scalar on all
    ranks (each rank's grads flow only to its own stage params through the
    permutes — the SectionWorker F-then-B equivalent under AD). Pass
    schedule="1f1b" for the bounded-activation-memory variant, or
    schedule="interleaved" with stage_fn as a LIST of per-rank chunk fns
    for the virtual-stage schedule."""
    n = mesh_mod.mesh_axis_size(axis)
    rank = lax.axis_index(axis)
    if schedule == "interleaved":
        outs = interleaved(list(stage_fn), x_micro, axis)
    else:
        outs = gpipe(stage_fn, x_micro, axis, schedule=schedule)
    M = x_micro.shape[0]
    total = jnp.zeros((), jnp.float32)
    on_last = (rank == n - 1).astype(jnp.float32)
    for m in range(M):
        total = total + loss_fn(outs[m], labels_micro[m]).astype(jnp.float32) \
            * on_last
    return lax.psum(total, axis) / M


def bubble_fraction(num_micro: int, num_stages: int,
                    schedule: str = "gpipe", num_virtual: int = 1) -> float:
    """Pipeline bubble overhead (n-1)/(M+n-1) — the schedule-quality
    accounting the reference leaves implicit in SectionWorker. The
    interleaved schedule's finer chunks shrink it to (n-1)/(vM+n-1).

    Degenerate pipelines price as ZERO bubble: one stage never idles,
    and zero microbatches have no schedule to be idle in (guards the
    divide-by-zero a naive (n-1)/(M+n-1) hits at M=0, n=1)."""
    num_micro = max(int(num_micro), 0)
    num_stages = max(int(num_stages), 1)
    num_virtual = max(int(num_virtual), 1)
    if num_stages <= 1 or num_micro == 0:
        return 0.0
    if schedule == "interleaved":
        return (num_stages - 1) / (num_virtual * num_micro
                                   + num_stages - 1)
    return (num_stages - 1) / (num_micro + num_stages - 1)


def schedule_collectives(num_micro: int, num_stages: int,
                         hidden_bytes: int, schedule: str = "gpipe",
                         num_virtual: int = 1, axis: str = "pp",
                         tiers=None) -> dict:
    """The pipeline's implied collective set, in the static analyzer's
    terms (static/spmd_analyzer.py): every schedule above emits ONE
    lax.ppermute of the hidden microbatch per tick, so the 'pp' wire
    cost of a step is ticks x hidden_bytes — the quantity the analyzer's
    collective table and tools/spmd_lint.py report next to the
    matmul-implied all-reduces. (The forward numbers; AD mirrors each
    ppermute in reverse, doubling the wire bytes for training.)

    A single-stage pipeline has no ring to permute around — it prices
    as ZERO ppermutes, not `ticks` no-op sends.

    `tiers` ({axis: {"tier", "gbps"}}, the mesh.axis_tiers form) adds
    `tier`/`cost_us` keys pricing the wire against the stage axis's
    link — a pipeline axis left on the slow DCN tier shows its cost
    here before a single microbatch moves."""
    if max(int(num_stages), 1) <= 1:
        out = {"kind": "ppermute", "axis": axis, "count": 0,
               "bytes_per_tick": int(hidden_bytes), "total_bytes": 0}
    else:
        ticks = schedule_ticks(num_micro, num_stages, schedule,
                               num_virtual)
        out = {"kind": "ppermute", "axis": axis, "count": ticks,
               "bytes_per_tick": int(hidden_bytes),
               "total_bytes": ticks * int(hidden_bytes)}
    if tiers and axis in tiers:
        m = tiers[axis]
        g = float(m.get("gbps", 0.0))
        out["tier"] = str(m.get("tier", "ici"))
        out["cost_us"] = round(out["total_bytes"] / (g * 1e3), 3) \
            if g > 0 else 0.0
    return out
