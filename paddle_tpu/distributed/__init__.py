"""paddle.distributed — mesh-first distributed training.

TPU-native re-design of the reference's distributed stack (SURVEY.md §2.2,
§2.3): NCCL ring_id registries + program-rewriting meta-optimizers become
named mesh axes + sharding rules + XLA-inserted ICI collectives.
"""
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
from . import mesh  # noqa: F401
from .mesh import (get_mesh, init_hybrid_mesh, init_mesh,  # noqa: F401
                   mesh_axis_size, in_spmd_region, reset_mesh)

import importlib as _importlib

_LAZY_MODULES = ("fleet", "sharding", "pipeline", "launch", "spawn", "moe",
                 "collective", "parallel", "ring_attention", "bootstrap",
                 "elastic", "ps", "localsgd")
_LAZY_NAMES = {
    "recompute": "recompute", "checkpoint_policy": "recompute",
    "all_gather": "collective", "all_reduce": "collective",
    "alltoall": "collective", "barrier": "collective",
    "broadcast": "collective", "recv": "collective", "reduce": "collective",
    "reduce_scatter": "collective", "scatter": "collective",
    "hierarchical_all_reduce": "collective",
    "send": "collective", "ReduceOp": "collective", "split": "collective",
    "DataParallel": "parallel", "init_parallel_env": "parallel",
    "ring_attention_fn": "ring_attention",
}


# Lazily-injected non-module names; enumerated so the API.spec snapshot is
# deterministic regardless of import order (see tools/gen_api_spec.py).
__all_lazy__ = tuple(_LAZY_NAMES) + (
    "InMemoryDataset", "QueueDataset", "DatasetFactory")


def __getattr__(name):
    if name in ("InMemoryDataset", "QueueDataset", "DatasetFactory"):
        # 2.0 API location: paddle.distributed.InMemoryDataset
        from ..io import fleet_dataset as _fd
        val = getattr(_fd, name)
        globals()[name] = val
        return val
    if name in _LAZY_MODULES:
        mod = _importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in _LAZY_NAMES:
        mod = _importlib.import_module(f".{_LAZY_NAMES[name]}", __name__)
        # Importing a submodule binds it as a package attribute; when a
        # public function shares its module's name (recompute), that binding
        # would shadow the function for every later lookup. Materialize all
        # names backed by this module now, overwriting any module binding.
        for n, m in _LAZY_NAMES.items():
            if m == _LAZY_NAMES[name]:
                globals()[n] = getattr(
                    mod, n if n != "ring_attention_fn" else "ring_attention")
        return globals()[name]
    raise AttributeError(
        f"module 'paddle_tpu.distributed' has no attribute {name!r}")
