"""Mixture-of-Experts with expert parallelism.

Absent in the reference snapshot (SURVEY.md §2.2 "EP / MoE: build fresh").
Design: experts are sharded over the 'ep' mesh axis; tokens are routed by a
top-k softmax gate with capacity, dispatched to expert shards via all-to-all
on ICI, processed batched on the MXU, and combined back with a second
all-to-all (the standard Switch/GShard formulation).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn, ops
from ..ops._dispatch import defop
from . import mesh as mesh_mod

__all__ = ["MoELayer", "switch_route"]


def switch_route(gate_logits, num_experts, capacity, k=1):
    """Top-1 routing with capacity: returns (dispatch, combine).

    dispatch: [tokens, experts, capacity] one-hot
    combine:  [tokens, experts, capacity] gate-weighted

    Tokens routed past an expert's `capacity` are DROPPED (their
    dispatch row is all-zero — the standard Switch overflow semantics).
    That used to be silent; outside a jit trace the drop count now bumps
    the `moe.dropped_tokens` monitor counter, so a mis-sized
    capacity_factor shows up on the dashboard instead of as a quiet
    quality regression. (Eager-mode calls pay one host sync for the
    count; traced/jitted calls pay nothing — the accounting is skipped
    entirely under tracing.)"""
    probs = jax.nn.softmax(gate_logits, axis=-1)            # [T, E]
    expert = jnp.argmax(probs, axis=-1)                     # [T]
    gate = jnp.max(probs, axis=-1)                          # [T]
    onehot = jax.nn.one_hot(expert, num_experts)            # [T, E]
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0         # [T, E]
    keep = (pos < capacity) & (onehot > 0)
    if not isinstance(keep, jax.core.Tracer):
        n = int(jnp.sum(onehot > 0) - jnp.sum(keep))
        if n:
            from ..core import monitor
            monitor.stat_add("moe.dropped_tokens", n)
    pos_cap = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    dispatch = keep[..., None] & (jax.nn.one_hot(pos_cap, capacity) > 0)
    combine = dispatch.astype(probs.dtype) * gate[:, None, None]
    return dispatch.astype(probs.dtype), combine


class MoELayer(nn.Layer):
    """Expert-parallel FFN block.

    Outside an SPMD region all experts run locally (dense fallback);
    inside shard_map over 'ep', each rank holds num_experts/ep experts and
    tokens move via all-to-all.
    """

    def __init__(self, d_model, d_hidden, num_experts, capacity_factor=1.25,
                 axis="ep", activation="gelu", k=1):
        super().__init__()
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.axis = axis
        ep = mesh_mod.mesh_axis_size(axis)
        assert num_experts % ep == 0, (num_experts, ep)
        self.experts_per_rank = num_experts // ep
        self.gate = nn.Linear(d_model, num_experts, bias_attr=False)
        # expert weights stacked: [E_local, d_model, d_hidden]
        from ..nn import initializer as I
        self.w_up = self.create_parameter(
            [self.experts_per_rank, d_model, d_hidden],
            default_initializer=I.XavierUniform())
        self.b_up = self.create_parameter([self.experts_per_rank, d_hidden],
                                          is_bias=True)
        self.w_down = self.create_parameter(
            [self.experts_per_rank, d_hidden, d_model],
            default_initializer=I.XavierUniform())
        self.b_down = self.create_parameter([self.experts_per_rank, d_model],
                                            is_bias=True)
        self.act = getattr(nn.functional, activation)

    def _expert_ffn(self, x, w_up, b_up, w_down, b_down):
        # x: [E, cap, d] batched expert matmuls on the MXU
        h = jnp.einsum("ecd,edh->ech", x, w_up) + b_up[:, None, :]
        h = jax.nn.gelu(h)
        return jnp.einsum("ech,ehd->ecd", h, w_down) + b_down[:, None, :]

    def forward(self, x):
        @defop(name="moe_layer")
        def run(xv, gate_w, w_up, b_up, w_down, b_down, axis, e_total,
                e_local, cap_factor):
            b, s, d = xv.shape
            tokens = xv.reshape(b * s, d)
            T = tokens.shape[0]
            in_region = mesh_mod.in_spmd_region(axis)
            ep = mesh_mod.mesh_axis_size(axis) if in_region else 1
            capacity = int(cap_factor * T / e_total) + 1
            logits = tokens @ gate_w                       # [T, E]
            dispatch, combine = switch_route(logits, e_total, capacity)
            # [T,E,C] x [T,d] -> [E, C, d]
            xin = jnp.einsum("tec,td->ecd", dispatch, tokens)
            if in_region:
                # all-to-all: experts dim -> local experts, tokens from all
                # ranks concatenated on capacity dim
                xin = lax.all_to_all(xin, axis, split_axis=0, concat_axis=1,
                                     tiled=True)           # [E/ep, C*ep, d]
            out = self._expert_ffn(xin, w_up, b_up, w_down, b_down)
            if in_region:
                out = lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                                     tiled=True)           # [E, C, d]
            y = jnp.einsum("tec,ecd->td", combine, out)
            return y.reshape(b, s, d)

        ep = mesh_mod.mesh_axis_size(self.axis) \
            if mesh_mod.in_spmd_region(self.axis) else 1
        if ep == 1 and self.experts_per_rank != self.num_experts:
            raise RuntimeError("MoELayer built for ep>1 used outside SPMD")
        return run(x, self.gate.weight, self.w_up, self.b_up, self.w_down,
                   self.b_down, axis=self.axis, e_total=self.num_experts,
                   e_local=self.experts_per_rank,
                   cap_factor=self.capacity_factor)
