"""Sharding rules — the TP/ZeRO presets.

TPU-native replacement for the reference's program-surgery parallelism
(reference: fleet/meta_optimizers/sharding_optimizer.py:33 ShardingOptimizer
— splits params/grads/opt-states and inserts broadcast/reduce ops; and the
manual Megatron-style c_allgather/c_reducescatter assembly, SURVEY.md §2.2
"TP"). Design delta: parallelism is declared as PartitionSpecs per parameter
NAME PATTERN; GSPMD partitions the jitted step and inserts the ICI
collectives the reference wrote by hand.

Conventions (our Linear weight is [in, out]):
  column-parallel (shard output dim):  qkv/q/k/v projections, ffn up-proj
  row-parallel   (shard input dim):    attention out-proj, ffn down-proj
  vocab-parallel (shard rows):         word embeddings / tied LM head
ZeRO-style sharded-DP shards every remaining (replicated) param and its
optimizer slots along 'dp' on dim 0 when enabled.
"""
from __future__ import annotations

import re
from typing import Dict, Optional

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_mod

__all__ = ["param_spec_for", "build_param_shardings", "COLUMN_PARALLEL",
           "ROW_PARALLEL", "VOCAB_PARALLEL", "add_tp_rule",
           "remove_tp_rule", "shard_optimizer_state",
           "group_sharded_parallel", "named_param_specs", "mesh_like"]

COLUMN_PARALLEL = [
    r"qkv_proj\.weight$", r"q_proj\.weight$", r"k_proj\.weight$",
    r"v_proj\.weight$", r"linear1\.weight$", r"fc1\.weight$",
    r"mlm_transform\.weight$",
]
COLUMN_PARALLEL_BIAS = [
    r"qkv_proj\.bias$", r"q_proj\.bias$", r"k_proj\.bias$",
    r"v_proj\.bias$", r"linear1\.bias$", r"fc1\.bias$",
    r"mlm_transform\.bias$",
]
ROW_PARALLEL = [
    r"out_proj\.weight$", r"linear2\.weight$", r"fc2\.weight$",
]
VOCAB_PARALLEL = [
    r"word_embeddings\.weight$", r"wte\.weight$",
]

_extra_rules = []  # (regex, P | spec_builder(ndim) -> P)


def add_tp_rule(pattern: str, spec):
    """Register a custom tensor-parallel rule (most-specific wins last).

    `spec` is either a fixed PartitionSpec or a callable `(ndim) -> P`
    so one rule can serve params of different ranks (e.g. weight+bias
    under one name template). Fixed specs are rank-checked when the rule
    MATCHES — a 2-entry spec on a 1-D param raises here, naming the rule,
    instead of surfacing as a spec-rank crash deep in the partitioner."""
    _extra_rules.append((re.compile(pattern), spec))


def remove_tp_rule(pattern: str) -> int:
    """Unregister every rule added for `pattern`; returns how many."""
    before = len(_extra_rules)
    _extra_rules[:] = [(rx, sp) for rx, sp in _extra_rules
                       if rx.pattern != pattern]
    return before - len(_extra_rules)


def _resolve_rule_spec(rx, spec, name, ndim) -> P:
    spec = spec(ndim) if callable(spec) else spec
    if spec is None:
        spec = P()
    if len(tuple(spec)) > ndim:
        raise ValueError(
            f"tp rule {rx.pattern!r} produced PartitionSpec {spec} with "
            f"{len(tuple(spec))} entries for rank-{ndim} param {name!r} — "
            "register a callable spec builder (ndim -> P) or scope the "
            "pattern to params of the right rank")
    return spec


def _match(name, patterns):
    return any(re.search(p, name) for p in patterns)


def param_spec_for(name: str, ndim: int, mesh: Optional[Mesh] = None,
                   zero_dp: bool = False) -> P:
    """PartitionSpec for a parameter by name pattern."""
    m = mesh or mesh_mod.get_mesh()
    axes = set(m.axis_names) if m is not None else set()
    has_tp = "tp" in axes

    for rx, spec in reversed(_extra_rules):
        if rx.search(name):
            return _resolve_rule_spec(rx, spec, name, ndim)
    if has_tp and ndim >= 2:
        if _match(name, COLUMN_PARALLEL):
            return P(*([None] * (ndim - 1) + ["tp"]))
        if _match(name, ROW_PARALLEL):
            return P(*(["tp"] + [None] * (ndim - 1)))
        if _match(name, VOCAB_PARALLEL):
            return P(*(["tp"] + [None] * (ndim - 1)))
    if has_tp and ndim == 1 and _match(name, COLUMN_PARALLEL_BIAS):
        return P("tp")
    if zero_dp and "dp" in axes and ndim >= 1:
        # ZeRO-3-style: shard dim 0 of everything not already tp-sharded
        return P(*(["dp"] + [None] * (ndim - 1)))
    return P()


def build_param_shardings(params: Dict[str, "jax.Array"],
                          mesh: Optional[Mesh] = None,
                          zero_dp: bool = False) -> Dict[str, NamedSharding]:
    m = mesh or mesh_mod.auto_mesh()
    out = {}
    for name, v in params.items():
        spec = param_spec_for(name, v.ndim, m, zero_dp=zero_dp)
        spec = _validate_divisible(spec, v.shape, m, name=name)
        out[name] = NamedSharding(m, spec)
    return out


def _validate_divisible(spec: P, shape, mesh: Mesh, name: str = None) -> P:
    """Drop axis shardings that don't divide the dim (falls back to
    replication for that dim, like GSPMD would pad — we prefer explicit).

    The fallback is no longer silent: each dropped axis bumps the
    `sharding.nondivisible_fallback` monitor counter (the static
    analyzer reports the same condition as a `non-divisible` diagnostic
    before compilation). A spec with MORE entries than the tensor has
    dims is a caller bug and raises — trailing axes used to be
    zip-truncated without complaint."""
    entries = tuple(spec)
    if len(entries) > len(shape):
        raise ValueError(
            f"PartitionSpec {spec} has {len(entries)} entries but "
            f"{'param ' + repr(name) + ' ' if name else ''}shape "
            f"{tuple(shape)} has only {len(shape)} dims — trailing axes "
            "would be silently dropped")
    new = []
    for dim, ax in zip(shape,
                       entries + (None,) * (len(shape) - len(entries))):
        if ax is None:
            new.append(None)
        else:
            axes = (ax,) if isinstance(ax, str) else tuple(ax)
            size = 1
            for a in axes:
                size *= mesh.shape[a] if a in mesh.axis_names else 1
            if dim % size == 0:
                new.append(ax)
            else:
                from ..core import monitor as _monitor
                _monitor.stat_add("sharding.nondivisible_fallback")
                new.append(None)
    return P(*new)


def mesh_like(mesh):
    """Normalize a mesh argument for spec derivation: a real Mesh passes
    through, an {axis: size} dict becomes a duck-typed stand-in with
    .axis_names/.shape (no devices needed — the static analyzer and spec
    helpers only read the axis layout), None resolves the registered
    default."""
    if mesh is None:
        return mesh_mod.get_mesh()
    if isinstance(mesh, dict):
        from types import SimpleNamespace
        # axis_sizes flattens the topology grammar ({axis: {"size": n,
        # "tier": ...}}) down to plain int sizes for spec derivation
        return SimpleNamespace(axis_names=tuple(mesh),
                               shape=mesh_mod.axis_sizes(mesh))
    return mesh


def named_param_specs(layer, mesh=None, zero_dp=False, by="storage"):
    """PartitionSpecs for a Layer's parameters, keyed for downstream use.

    The TP rules above match DOTTED module paths ('blocks.0.fc2.weight'),
    but a static Program stores persistables under their scope names and
    dygraph params under their tensor names — this walks
    `layer.named_parameters()` once and returns {storage_name: spec}
    (by="storage", feeds `Program.spmd_param_specs` / analyze_program) or
    {dotted_name: spec} (by="dotted", feeds analyze_params).

    mesh may be a Mesh, an {axis: size} dict (no devices needed), or
    None for the registered default.
    """
    mesh = mesh_like(mesh)
    out = {}
    for dotted, p in layer.named_parameters():
        spec = param_spec_for(dotted, len(p.shape), mesh, zero_dp=zero_dp)
        key = dotted if by == "dotted" else (
            getattr(p, "scope_name", None) or getattr(p, "name", dotted))
        out[key] = spec
    return out


def shard_optimizer_state(slot_tree: Dict[str, Dict[str, "jax.Array"]],
                          param_shardings: Dict[str, NamedSharding]):
    """Optimizer slots inherit their parameter's sharding (the
    ShardingOptimizer §2.2 'shard opt states' half)."""
    return {k: {s: param_shardings[k] for s in slots}
            for k, slots in slot_tree.items()}


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None):
    """API parity with paddle.distributed.sharding.group_sharded_parallel:
    marks the model/optimizer for ZeRO-style sharded data parallel. The
    actual partitioning happens in the compiled step via
    build_param_shardings(zero_dp=True)."""
    model._zero_dp = True
    if optimizer is not None:
        optimizer._zero_dp = True
    return model, optimizer, scaler
