"""paddle.distributed.spawn (reference python/paddle/distributed/spawn.py):
fork worker processes running `func(rank, *args)` with the PADDLE_* env."""
from __future__ import annotations

import multiprocessing as mp
import os

__all__ = ["spawn"]


def _worker(rank, nprocs, func, args):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    func(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker, args=(rank, nprocs, func, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode]
        if bad:
            raise RuntimeError(f"spawned workers failed with codes {bad}")
    return procs
