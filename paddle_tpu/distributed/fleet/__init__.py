"""Fleet — the distributed-training user API.

Analog of reference python/paddle/distributed/fleet/ (fleet.init
fleet_base.py:130, distributed_optimizer :593, DistributedStrategy
base/distributed_strategy.py:101 over framework/distributed_strategy.proto,
RoleMaker base/role_maker.py, 16 meta-optimizers under meta_optimizers/).

Design delta (SURVEY.md §3.3): meta-optimizers rewrote the Program op-by-op
(insert c_allreduce/c_broadcast, split params, prune). Here
DistributedStrategy maps to *declarative* execution config: a mesh shape +
sharding rules + step-wrapping transforms (amp/recompute/gradient merge)
that the compiled step consumes — StrategyCompiler composition collapses
into picking those settings.
"""
from __future__ import annotations

import os
from typing import Optional

from .. import mesh as mesh_mod
from ..env import ParallelEnv, get_rank, get_world_size
from .strategy import DistributedStrategy  # noqa: F401
from .role_maker import (PaddleCloudRoleMaker, Role, UserDefinedRoleMaker)  # noqa: F401
from . import meta_parallel  # noqa: F401

__all__ = ["init", "DistributedStrategy", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "distributed_optimizer", "worker_index",
           "worker_num", "is_first_worker", "is_worker", "is_server",
           "worker_endpoints", "barrier_worker", "init_worker",
           "stop_worker", "init_server", "run_server", "ps_client",
           "ps_communicator", "DistributedOptimizer",
           "get_hybrid_communicate_group", "spmd_report"]

_fleet_state = {
    "initialized": False,
    "role_maker": None,
    "strategy": None,
    "is_collective": True,
    "hcg": None,
}


def init(role_maker=None, is_collective=True, strategy=None):
    """reference fleet_base.py:130. Declares the mesh from the strategy's
    hybrid degrees (replacing Gloo rendezvous + NCCL ring init).

    With is_collective=False the job is parameter-server mode (reference
    fleet/runtime/the_one_ps.py): no mesh, no jax bootstrap — server
    processes are host-only; workers talk to servers through
    paddle.distributed.ps (PADDLE_PSERVERS_IP_PORT_LIST env contract,
    reference distributed/utils.py:406-409)."""
    strategy = strategy or DistributedStrategy()
    _fleet_state.update(initialized=True, role_maker=role_maker,
                        strategy=strategy, is_collective=is_collective)
    if not is_collective:
        if role_maker is None:
            _fleet_state["role_maker"] = PaddleCloudRoleMaker(
                is_collective=False)
        return _FleetFacade()
    from ..bootstrap import maybe_initialize_distributed
    maybe_initialize_distributed()
    import jax
    n = len(jax.devices())  # global across hosts once bootstrapped
    degrees = strategy.hybrid_configs
    dp = degrees.get("dp_degree", -1)
    mp = degrees.get("mp_degree", 1)
    pp = degrees.get("pp_degree", 1)
    sp = degrees.get("sep_degree", degrees.get("sp_degree", 1))
    ep = degrees.get("ep_degree", 1)
    fixed = mp * pp * sp * ep
    if dp == -1:
        dp = max(n // max(fixed, 1), 1)
    shape = {}
    if dp > 1 or fixed == 1:
        shape["dp"] = dp
    if mp > 1:
        shape["tp"] = mp
    if pp > 1:
        shape["pp"] = pp
    if sp > 1:
        shape["sp"] = sp
    if ep > 1:
        shape["ep"] = ep
    if not shape:
        shape = {"dp": n}
    total = 1
    for v in shape.values():
        total *= v
    if total != n:
        raise ValueError(
            f"hybrid parallel degrees {dict(degrees)} imply mesh {shape} "
            f"({total} devices) but {n} devices are available; degrees must "
            f"factor the device count exactly (the reference likewise "
            f"rejects bad strategy configs rather than silently rewriting "
            f"the user's parallelism)")
    mesh_mod.init_mesh(shape)
    _fleet_state["hcg"] = HybridCommunicateGroup(shape)
    return _FleetFacade()


def spmd_report(program=None, layer=None, mesh=None, data_specs=None,
                tokens_per_step=None, zero_dp=False):
    """Run the static SPMD sharding analyzer against the fleet mesh
    (static/spmd_analyzer.py): resolved PartitionSpecs, the implied
    collective set with per-device payload bytes, a per-device peak-HBM
    estimate, and the sharding diagnostic catalogue — all before jit.

    Pass a static `program` (optionally with a `layer` so the TP name
    patterns see dotted parameter paths), or just a `layer`/param tree
    for the dygraph/hapi path. `mesh` defaults to the fleet-declared
    mesh; an {axis: size} dict also works (no devices needed — lint a
    pod layout from a dev box)."""
    from ...static import spmd_analyzer as spmd
    from .. import sharding as sharding_mod
    mesh = mesh if mesh is not None else mesh_mod.get_mesh()
    if program is not None:
        param_specs = getattr(program, "spmd_param_specs", None)
        if param_specs is None and layer is not None:
            param_specs = sharding_mod.named_param_specs(
                layer, mesh, zero_dp=zero_dp)
        if data_specs is None:  # same defaulting as the VERIFY_SPMD hook
            data_specs = getattr(program, "spmd_data_specs", None)
        return spmd.analyze_program(program, mesh=mesh,
                                    param_specs=param_specs,
                                    data_specs=data_specs)
    if layer is None:
        raise ValueError("spmd_report needs a program or a layer")
    params = dict(layer.named_parameters()) if hasattr(
        layer, "named_parameters") else dict(layer)
    return spmd.analyze_params(params, mesh=mesh,
                               tokens_per_step=tokens_per_step,
                               zero_dp=zero_dp)


class HybridCommunicateGroup:
    """Topology info (reference fleet/base/topology.py
    HybridCommunicateGroup). Ranks are REAL mesh coordinates: this
    process's position along each axis, found by locating one of its
    devices in the active mesh (multi-process SPMD), falling back to a
    row-major decomposition of the process rank over the axis sizes."""

    def __init__(self, shape):
        self.shape = dict(shape)

    def _coords(self):
        axes = list(self.shape.keys())
        from .. import mesh as mesh_mod
        mesh = mesh_mod.get_mesh()
        try:
            import jax
            pid = jax.process_index()
            if mesh is not None and set(axes) <= set(mesh.axis_names):
                import numpy as np
                dev = mesh.devices
                for idx in np.ndindex(dev.shape):
                    if dev[idx].process_index == pid:
                        return dict(zip(mesh.axis_names, idx))
        except Exception:
            pass
        r = get_rank()
        coords = {}
        for ax in reversed(axes):           # row-major, last axis fastest
            coords[ax] = r % self.shape[ax]
            r //= self.shape[ax]
        return coords

    def _rank(self, axis):
        return int(self._coords().get(axis, 0))

    def get_data_parallel_world_size(self):
        return self.shape.get("dp", 1)

    def get_model_parallel_world_size(self):
        return self.shape.get("tp", 1)

    def get_pipe_parallel_world_size(self):
        return self.shape.get("pp", 1)

    def get_sep_parallel_world_size(self):
        return self.shape.get("sp", 1)

    def get_expert_parallel_world_size(self):
        return self.shape.get("ep", 1)

    def get_data_parallel_rank(self):
        return self._rank("dp")

    def get_model_parallel_rank(self):
        return self._rank("tp")

    def get_stage_id(self):
        return self._rank("pp")

    def get_sep_parallel_rank(self):
        return self._rank("sp")

    def get_expert_parallel_rank(self):
        return self._rank("ep")


def get_hybrid_communicate_group():
    return _fleet_state["hcg"]


def worker_index():
    rm = _fleet_state.get("role_maker")
    return rm.worker_index() if rm is not None else get_rank()


def worker_num():
    rm = _fleet_state.get("role_maker")
    return rm.worker_num() if rm is not None else get_world_size()


def is_first_worker():
    rm = _fleet_state.get("role_maker")
    return rm.is_first_worker() if rm is not None else get_rank() == 0


def is_worker():
    rm = _fleet_state.get("role_maker")
    return rm.is_worker() if rm is not None else True


def is_server():
    rm = _fleet_state.get("role_maker")
    return rm.is_server() if rm is not None else False


def worker_endpoints(to_string=False):
    eps = ParallelEnv().trainer_endpoints
    return ",".join(eps) if to_string else eps


def barrier_worker():
    from ..collective import barrier
    barrier()


def init_worker():
    """PS mode: connect a PSClient to all servers; strategy.a_sync adds
    the background Communicator (reference fleet_base.py init_worker ->
    the_one_ps._init_worker + communicator start)."""
    if _fleet_state["is_collective"]:
        return
    from ..ps import Communicator, PSClient
    rm = _fleet_state.get("role_maker")
    eps = rm.get_pserver_endpoints() if rm is not None else []
    if not eps:
        eps = [e for e in os.environ.get(
            "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
    if not eps:
        raise RuntimeError(
            "PS mode needs server endpoints: pass them to the role maker "
            "(UserDefinedRoleMaker(server_endpoints=[...])) or set "
            "PADDLE_PSERVERS_IP_PORT_LIST (comma-separated host:port list)")
    client = PSClient(eps)
    _fleet_state["ps_client"] = client
    strategy = _fleet_state["strategy"]
    if strategy is not None and strategy.a_sync:
        cfg = strategy.a_sync_configs or {}
        _fleet_state["ps_communicator"] = Communicator(
            client, send_every=cfg.get("send_queue_size", 4))


def ps_client():
    c = _fleet_state.get("ps_client")
    if c is None:
        raise RuntimeError("call fleet.init_worker() first")
    return c


def ps_communicator():
    return _fleet_state.get("ps_communicator")


def stop_worker():
    """Drain the communicator, rendezvous ALL workers at the server-side
    stop barrier (so no server dies under a still-training peer), then
    the first worker shuts the servers down (reference: trainers
    deregister before pserver exit, heart_beat_monitor.cc)."""
    if _fleet_state["is_collective"]:
        return
    comm = _fleet_state.pop("ps_communicator", None)
    if comm is not None:
        comm.flush()
        comm.stop()
    client = _fleet_state.pop("ps_client", None)
    if client is not None:
        try:
            client.barrier(_STOP_BARRIER, worker_index())
        except (RuntimeError, ConnectionError, OSError):
            # pre-ps-stack server config without the barrier table, or
            # servers already gone/unreachable — teardown must still
            # proceed to close() so the worker exits cleanly
            pass
        if is_first_worker():
            try:
                client.stop_servers()
            except (ConnectionError, OSError):
                pass  # servers already dead is a successful stop
        client.close()


_STOP_BARRIER = "_fleet_stop_barrier"


def init_server(tables=None, endpoint=None):
    """Build this process's PSServer from table specs (reference
    fleet.init_server building tables out of ps.proto TableParameters;
    here specs are explicit dicts — see distributed.ps.make_table). A
    stop barrier sized to the trainer count is provisioned automatically
    so stop_worker can rendezvous before servers exit.

    With PADDLE_PS_REPLICA_BACKUPS > 0 and a full endpoint list in
    PADDLE_PSERVERS_IP_PORT_LIST, the server joins the replicated
    storage tier: every server derives the SAME initial shard map from
    the endpoint list (chained primary/backup layout), so no bootstrap
    rendezvous is needed — promotions and rejoins evolve the map from
    there (distributed/ps/replica.py)."""
    from ...core.flags import flag as _flag
    from ..ps import PSServer, ShardMap
    eps = [e for e in os.environ.get(
        "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
    if endpoint is None:
        idx = int(os.environ.get("PADDLE_PSERVER_ID", "0"))
        endpoint = eps[idx] if eps else "127.0.0.1:0"
    tables = dict(tables or {})
    tables.setdefault(_STOP_BARRIER, {
        "type": "barrier",
        "trainer_num": int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))})
    n_backups = int(_flag("PADDLE_PS_REPLICA_BACKUPS"))
    replica = None
    if n_backups > 0 and len(eps) > 1 and ":0" not in endpoint:
        replica = {"shard_map": ShardMap.create(eps, n_backups),
                   "peers": eps, "n_backups": n_backups}
    server = PSServer(endpoint, tables, replica=replica)
    _fleet_state["ps_server"] = server
    server.start()
    return server


def run_server():
    """Blocks serving pull/push until a worker sends stop (reference
    pscore/listen_and_serv_op.cc server loop)."""
    server = _fleet_state.get("ps_server")
    if server is None:
        raise RuntimeError("call fleet.init_server() first")
    server.run()


class DistributedOptimizer:
    """Strategy-composing optimizer wrapper (reference fleet_base.py:593 +
    StrategyCompiler). Effects are declarative: the strategy's knobs are
    consumed by the compiled train step (hapi engine / static Executor)."""

    def __init__(self, optimizer, strategy: DistributedStrategy):
        self.inner_opt = optimizer
        self.user_defined_strategy = strategy
        optimizer._dist_strategy = strategy  # engine reads these
        if strategy.sharding:
            optimizer._zero_dp = True
        if strategy.amp:
            # O2/pure-bf16 keeps f32 master weights in the optimizer (the
            # reference amp meta-optimizer's rewrite, declaratively)
            level = strategy.amp_configs.get("level", "O1")
            if level == "O2" or strategy.amp_configs.get("use_pure_bf16"):
                optimizer._multi_precision = True

    def __getattr__(self, item):
        return getattr(self.inner_opt, item)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        strategy = self.user_defined_strategy
        if getattr(strategy, "auto_shard", False) \
                and hasattr(loss, "program"):
            # tag the Program; the Executor's compile path resolves the
            # plan (static/spmd_planner.resolve_auto_shard) against the
            # mesh live at compile time, then the VERIFY_SPMD hook and
            # FLAGS_log_spmd_estimate read the resolved specs
            from ...static.program import default_main_program
            program = loss.program or default_main_program()
            program._auto_shard = dict(
                getattr(strategy, "auto_shard_configs", None) or {})
        if strategy.recompute and hasattr(loss, "program"):
            # static graph: tag the Program; the Executor lowering splits
            # the op list at these variables and wraps each segment in
            # jax.checkpoint (static/executor.py; reference
            # RecomputeOptimizer fluid/optimizer.py:4526)
            from ...static.program import default_main_program
            program = loss.program or default_main_program()
            cfg = strategy.recompute_configs or {}
            program.recompute_checkpoints = tuple(
                v.name if hasattr(v, "name") else str(v)
                for v in cfg.get("checkpoints", ()))
            program.recompute_policy = cfg.get("policy", "nothing")
        if strategy.amp and hasattr(loss, "program"):
            # static graph: tag the Program so the Executor applies the
            # per-op cast policy (static/amp.py)
            import jax.numpy as jnp
            from ...static.program import default_main_program
            program = loss.program or default_main_program()
            cfg = strategy.amp_configs
            program.amp_level = "O2" if cfg.get("use_pure_bf16") \
                else cfg.get("level", "O1")
            program.amp_dtype = jnp.float16 \
                if str(cfg.get("dtype", "bfloat16")) in ("float16", "fp16") \
                else jnp.bfloat16
            if cfg.get("custom_white_list") or cfg.get("custom_black_list"):
                from ... import amp as amp_mod
                white = amp_mod.white_list() \
                    | set(cfg.get("custom_white_list") or ())
                black = (amp_mod.black_list()
                         | set(cfg.get("custom_black_list") or ())) \
                    - set(cfg.get("custom_white_list") or ())
                program.amp_lists = (frozenset(white), frozenset(black))
        return self.inner_opt.minimize(loss, startup_program, parameters,
                                       no_grad_set)

    def step(self):
        return self.inner_opt.step()

    def clear_grad(self):
        return self.inner_opt.clear_grad()

    def state_dict(self):
        return self.inner_opt.state_dict()

    def set_state_dict(self, state):
        return self.inner_opt.set_state_dict(state)


def distributed_optimizer(optimizer, strategy=None):
    strategy = strategy or _fleet_state.get("strategy") or DistributedStrategy()
    return DistributedOptimizer(optimizer, strategy)


def distributed_model(model):
    """reference fleet.distributed_model — wraps for data parallelism."""
    from ..parallel import DataParallel
    return DataParallel(model)


class _FleetFacade:
    """Object returned by fleet.init supporting the fluent API."""

    distributed_optimizer = staticmethod(distributed_optimizer)
    distributed_model = staticmethod(distributed_model)
    worker_index = staticmethod(worker_index)
    worker_num = staticmethod(worker_num)
    is_first_worker = staticmethod(is_first_worker)
    barrier_worker = staticmethod(barrier_worker)

    @property
    def util(self):
        from .util import UtilBase
        return UtilBase()
