"""Role makers (reference fleet/base/role_maker.py:33-128: parse PADDLE_*
env contract; Gloo rendezvous becomes the jax distributed runtime)."""
from __future__ import annotations

import os

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.worker_index() == 0

    def worker_index(self):
        from ..env import get_rank
        return get_rank()

    def worker_num(self):
        from ..env import get_world_size
        return get_world_size()

    def server_num(self):
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        return len(eps.split(",")) if eps else 0

    def get_pserver_endpoints(self):
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        return eps.split(",") if eps else []

    def get_trainer_endpoints(self):
        from ..env import ParallelEnv
        return ParallelEnv().trainer_endpoints


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=True, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, **kwargs):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_endpoints = list(server_endpoints or [])

    def worker_index(self):
        return self._current_id

    def worker_num(self):
        return self._worker_num

    def server_num(self):
        return len(self._server_endpoints) or super().server_num()

    def get_pserver_endpoints(self):
        return self._server_endpoints or super().get_pserver_endpoints()
