"""DistributedStrategy.

Analog of reference framework/distributed_strategy.proto (:115, sub-messages
:25-113) + python fleet/base/distributed_strategy.py:101. Same knob surface;
instead of selecting program-rewriting meta-optimizers, the knobs configure
the compiled step: mesh degrees, sharding rules, amp/recompute/gradient-
merge wrappers.

Knobs that are deliberately inert here, with the reasoning:
- `dgc` (deep gradient compression) and `localsgd`/`adaptive_localsgd`:
  both exist to cheapen the gradient exchange between DIVERGENT replicas
  over slow interconnects. Under the single-controller SPMD model there
  are no divergent replicas — parameters are one sharded/replicated
  array, and XLA emits the exact gradient reduction over ICI, whose
  bandwidth is what these tricks trade accuracy to save. SURVEY §2.2
  rates both optional for this reason; accepting the flags keeps
  reference configs loadable.
- `fuse_all_reduce_ops`, `nccl_comm_num`, `fuse_grad_size_in_MB`: XLA
  owns collective fusion and scheduling.

`a_sync` is live: with fleet.init(is_collective=False) it selects the
async Communicator in the PS stack (distributed/ps; reference
communicator.cc AsyncCommunicator).
"""
from __future__ import annotations

__all__ = ["DistributedStrategy"]


class DistributedStrategy:
    def __init__(self):
        # mirroring proto defaults
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "use_pure_bf16": True,
                            "use_dynamic_loss_scaling": True, "level": "O1"}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {"segment_broadcast_MB": 32,
                                 "sharding_degree": 8, "stage": 2}
        # auto_shard: derive PartitionSpecs with the planner
        # (static/spmd_planner.py) at compile instead of the hand-written
        # COLUMN_PARALLEL/ROW_PARALLEL presets. Configs may carry a
        # pre-searched "plan" (ShardingPlan), a "mesh" ({axis: size}
        # dict), "names" (scope->dotted), "data_specs", "zero_dp" and the
        # objective weights; everything defaults from the fleet mesh.
        self.auto_shard = False
        self.auto_shard_configs = {}
        self.pipeline = False
        # The planner writes searched stage assignments into this same
        # knob surface (static/spmd_planner.ShardingPlan.as_strategy
        # when the plan carries pipeline cuts): "num_virtual" (chunks
        # per rank, interleaved 1F1B when > 1), "pp_degree" and
        # "stage_op_ranges" (the planned per-stage op ranges) join the
        # reference keys; the Executor resolves them onto the Program
        # as _pipeline_stages before the VERIFY_SPMD hook runs.
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1,
                                 "schedule_mode": "1F1B", "num_virtual": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lamb_configs = {}
        self.lars = False
        self.lars_configs = {}
        self.dgc = False
        self.dgc_configs = {}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1}
        self.adaptive_localsgd = False
        # hierarchical_allreduce: dp gradient sync as the three-phase
        # pod-aware decomposition (collective.hierarchical_all_reduce:
        # reduce-scatter over inner_axes, all-reduce the shard over
        # outer_axes, all-gather back). Flipped by
        # ShardingPlan.as_strategy() when the planned mesh declares a
        # slow link tier and the cost model recommends it.
        self.hierarchical_allreduce = False
        self.hierarchical_allreduce_configs = {"inner_axes": [],
                                               "outer_axes": []}
        self.a_sync = False
        self.a_sync_configs = {}
        self.elastic = False
        self.nccl_comm_num = 1  # parity no-op: no NCCL comms to count
        self.fuse_all_reduce_ops = True  # XLA fuses; accepted for parity
        self.fuse_grad_size_in_MB = 32
        self.hybrid_configs = {"dp_degree": -1, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "ep_degree": 1}
        self.find_unused_parameters = False
        self.heter_ccl_mode = False

    # dict-style hybrid_configs setter parity
    def __setattr__(self, key, value):
        if key == "hybrid_configs" and isinstance(value, dict) \
                and hasattr(self, "hybrid_configs"):
            merged = dict(self.__dict__.get("hybrid_configs", {}))
            merged.update(value)
            self.__dict__[key] = merged
            return
        self.__dict__[key] = value

    def to_dict(self):
        return {k: v for k, v in self.__dict__.items()}

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on}, hybrid={self.hybrid_configs})"
