"""Fleet utils (reference fleet/utils/fs.py + base/util_factory.py UtilBase:
HDFS helpers, all_reduce on host values)."""
from __future__ import annotations

import os
import shutil

import numpy as np

__all__ = ["UtilBase", "LocalFS"]


class LocalFS:
    """Local filesystem with the reference's FS interface
    (reference fleet/utils/fs.py LocalFS; HDFS shells out in the reference,
    framework/io/fs.cc — cloud FS backends plug in here)."""

    def ls_dir(self, path):
        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name))
             else files).append(name)
        return dirs, files

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)

    def rename(self, src, dst):
        os.replace(src, dst)

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def touch(self, path, exist_ok=True):
        open(path, "a").close()


class UtilBase:
    def __init__(self):
        self._fs = LocalFS()

    def get_file_system(self):
        return self._fs

    def all_reduce(self, input, mode="sum"):  # noqa: A002
        # host-side values; single-controller => identity reduce
        arr = np.asarray(input)
        return arr

    def all_gather(self, input):  # noqa: A002
        return [input]

    def barrier(self):
        from ..collective import barrier
        barrier()

    def print_on_rank(self, message, rank_id=0):
        from ..env import get_rank
        if get_rank() == rank_id:
            print(message)
