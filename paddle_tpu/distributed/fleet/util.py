"""Fleet utils (reference fleet/utils/fs.py + base/util_factory.py UtilBase:
HDFS helpers, all_reduce on host values)."""
from __future__ import annotations

import os
import shutil

import numpy as np

__all__ = ["UtilBase", "LocalFS"]


class LocalFS:
    """Local filesystem with the reference's FS interface
    (reference fleet/utils/fs.py LocalFS; HDFS shells out in the reference,
    framework/io/fs.cc — cloud FS backends plug in here)."""

    def ls_dir(self, path):
        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name))
             else files).append(name)
        return dirs, files

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)

    def rename(self, src, dst):
        os.replace(src, dst)

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def touch(self, path, exist_ok=True):
        open(path, "a").close()


class UtilBase:
    def __init__(self):
        self._fs = LocalFS()

    def get_file_system(self):
        return self._fs

    def all_reduce(self, input, mode="sum"):  # noqa: A002
        # host-side values; single-controller => identity reduce
        arr = np.asarray(input)
        return arr

    def all_gather(self, input):  # noqa: A002
        return [input]

    def barrier(self):
        from ..collective import barrier
        barrier()

    def print_on_rank(self, message, rank_id=0):
        from ..env import get_rank
        if get_rank() == rank_id:
            print(message)


class HDFSClient:
    """HDFS filesystem client (reference fleet/utils/fs.py HDFSClient):
    shells out to `hadoop fs` exactly like the reference — pass
    hadoop_home and the fs.default.name/ugi configs. Zero-egress images
    without a hadoop binary get a clear error at call time, not import
    time."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300):
        import os as _os
        self._hadoop = (_os.path.join(hadoop_home, "bin", "hadoop")
                        if hadoop_home else "hadoop")
        self._configs = dict(configs or {})
        self._timeout = time_out

    def _run(self, *args):
        import subprocess
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=self._timeout)
        except FileNotFoundError as e:
            raise RuntimeError(
                f"HDFSClient: hadoop binary {self._hadoop!r} not found — "
                "set hadoop_home (the reference shells out the same way)"
            ) from e
        return r.returncode, r.stdout, r.stderr

    def is_exist(self, path):
        rc, _, _ = self._run("-test", "-e", path)
        return rc == 0

    def is_dir(self, path):
        rc, _, _ = self._run("-test", "-d", path)
        return rc == 0

    def is_file(self, path):
        return self.is_exist(path) and not self.is_dir(path)

    def ls_dir(self, path):
        rc, out, err = self._run("-ls", path)
        if rc != 0:
            raise RuntimeError(f"hdfs ls failed: {err.strip()}")
        dirs, files = [], []
        for ln in out.splitlines():
            parts = ln.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rsplit("/", 1)[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def upload(self, local_path, fs_path):
        rc, _, err = self._run("-put", "-f", local_path, fs_path)
        if rc != 0:
            raise RuntimeError(f"hdfs put failed: {err.strip()}")

    def download(self, fs_path, local_path):
        rc, _, err = self._run("-get", fs_path, local_path)
        if rc != 0:
            raise RuntimeError(f"hdfs get failed: {err.strip()}")

    def mkdirs(self, path):
        rc, _, err = self._run("-mkdir", "-p", path)
        if rc != 0:
            raise RuntimeError(f"hdfs mkdir failed: {err.strip()}")

    def delete(self, path):
        rc, _, err = self._run("-rm", "-r", "-f", path)
        if rc != 0:
            raise RuntimeError(f"hdfs rm failed: {err.strip()}")

    def mv(self, src, dst):
        rc, _, err = self._run("-mv", src, dst)
        if rc != 0:
            raise RuntimeError(f"hdfs mv failed: {err.strip()}")
