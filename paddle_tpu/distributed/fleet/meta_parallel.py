"""Hybrid-parallel building blocks.

Analog of the reference's manual Megatron-style assembly (SURVEY.md §2.2
"TP": c_allgather/c_reducescatter/send_v2 + split ops composed by hand —
the reference has no general TP engine). Here TP layers are first-class:

- In the default pjit path, tensor parallelism is pure sharding metadata
  (distributed/sharding.py rules on plain nn.Linear weights) and GSPMD
  inserts the collectives.
- The explicit layers below are for shard_map-style code where the user
  writes per-device math: column/row-parallel linears with the classic
  identity/allreduce forward/backward pairs, and a vocab-parallel embedding
  with masked lookup + psum.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ... import nn, ops
from ...ops._dispatch import defop
from .. import mesh as mesh_mod

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "PipelineLayer", "LayerDesc",
           "get_rng_state_tracker"]


@defop(name="mp_allreduce_identity_bwd")
def _allreduce_fwd_identity_bwd(x, axis):
    """f(x)=psum(x); the transpose of psum is identity (g: copy) — exactly
    the RowParallelLinear output reduction."""
    return lax.psum(x, axis)


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _identity_fwd_allreduce_bwd_core(x, axis):
    return x


def _ifab_fwd(x, axis):
    return x, None


def _ifab_bwd(axis, _res, g):
    return (lax.psum(g, axis),)


_identity_fwd_allreduce_bwd_core.defvjp(_ifab_fwd, _ifab_bwd)


@defop(name="mp_identity_allreduce_bwd")
def _identity_fwd_allreduce_bwd(x, axis):
    """f(x)=x with grad psum — the ColumnParallelLinear input copy."""
    return _identity_fwd_allreduce_bwd_core(x, axis)


class ColumnParallelLinear(nn.Layer):
    """Output-dim sharded linear (weight shard [in, out/tp] per device)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, axis="tp", name=None):
        super().__init__()
        self.axis = axis
        tp = mesh_mod.mesh_axis_size(axis)
        assert out_features % tp == 0, (out_features, tp)
        self.out_per_shard = out_features // tp
        self.gather_output = gather_output
        self.inner = nn.Linear(in_features, self.out_per_shard,
                               weight_attr=weight_attr,
                               bias_attr=None if has_bias else False)

    @property
    def weight(self):
        return self.inner.weight

    def forward(self, x):
        if mesh_mod.in_spmd_region(self.axis):
            x = _identity_fwd_allreduce_bwd(x, axis=self.axis)
        out = self.inner(x)
        if self.gather_output and mesh_mod.in_spmd_region(self.axis):
            from ..collective import _allgather_raw
            g = _allgather_raw(out, axis=self.axis)  # [tp, ..., out/tp]
            parts = ops.unbind(g, 0)
            out = ops.concat(parts, axis=-1)
        return out


class RowParallelLinear(nn.Layer):
    """Input-dim sharded linear (weight shard [in/tp, out] per device)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, axis="tp", name=None):
        super().__init__()
        self.axis = axis
        tp = mesh_mod.mesh_axis_size(axis)
        assert in_features % tp == 0, (in_features, tp)
        self.in_per_shard = in_features // tp
        self.input_is_parallel = input_is_parallel
        self.inner = nn.Linear(self.in_per_shard, out_features,
                               weight_attr=weight_attr, bias_attr=False)
        self.bias = self.create_parameter([out_features], is_bias=True) \
            if has_bias else None

    @property
    def weight(self):
        return self.inner.weight

    def forward(self, x):
        if not self.input_is_parallel and mesh_mod.in_spmd_region(self.axis):
            idx = lax.axis_index(self.axis)
            x = lax.dynamic_slice_in_dim(
                x, idx * self.in_per_shard, self.in_per_shard, axis=-1)
        out = self.inner(x)
        if mesh_mod.in_spmd_region(self.axis):
            out = _allreduce_fwd_identity_bwd(out, axis=self.axis)
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(nn.Layer):
    """Vocab-sharded embedding: masked local lookup + psum."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 axis="tp", name=None):
        super().__init__()
        self.axis = axis
        tp = mesh_mod.mesh_axis_size(axis)
        assert num_embeddings % tp == 0
        self.per_shard = num_embeddings // tp
        self.inner = nn.Embedding(self.per_shard, embedding_dim,
                                  weight_attr=weight_attr)

    @property
    def weight(self):
        return self.inner.weight

    def forward(self, ids):
        if not mesh_mod.in_spmd_region(self.axis):
            return self.inner(ids)

        @defop(name="vocab_parallel_lookup")
        def lookup(weight, ids_raw, axis, per_shard):
            rank = lax.axis_index(axis)
            lo = rank * per_shard
            local = ids_raw - lo
            valid = (local >= 0) & (local < per_shard)
            safe = jnp.where(valid, local, 0)
            emb = jnp.take(weight, safe, axis=0)
            emb = jnp.where(valid[..., None], emb, 0.0)
            return lax.psum(emb, axis)

        return lookup(self.inner.weight, ids, axis=self.axis,
                      per_shard=self.per_shard)


class LayerDesc:
    """Deferred layer construction for pipeline stages
    (reference fleet/meta_parallel/parallel_layers/pp_layers.py)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.layer_cls(*self.args, **self.kwargs)


class PipelineLayer(nn.Layer):
    """Stage container: splits a layer list across the 'pp' axis
    (reference pp_layers.py PipelineLayer). The schedule itself lives in
    paddle_tpu.distributed.pipeline."""

    def __init__(self, layers, num_stages=None, loss_fn=None,
                 partition_method="uniform", **kwargs):
        super().__init__()
        self.descs = list(layers)
        self.num_stages = num_stages or mesh_mod.mesh_axis_size("pp")
        self.loss_fn = loss_fn
        n = len(self.descs)
        per = -(-n // self.num_stages)
        self.stage_bounds = [(i * per, min((i + 1) * per, n))
                             for i in range(self.num_stages)]
        built = [d.build() if isinstance(d, LayerDesc) else d
                 for d in self.descs]
        self.stages = nn.LayerList([
            nn.Sequential(*built[lo:hi]) for lo, hi in self.stage_bounds])

    def stage_fn(self, stage_idx):
        return self.stages[stage_idx]

    def forward(self, x):
        # reference single-process fallback: run all stages sequentially
        for s in self.stages:
            x = s(x)
        return x


class _RNGTracker:
    def rng_state(self, name="global_seed"):
        import contextlib
        return contextlib.nullcontext()

    def add(self, name, seed):
        pass


_tracker = _RNGTracker()


def get_rng_state_tracker():
    return _tracker
