"""Named-mesh registry.

TPU-native replacement for the reference's NCCL communicator registry
(reference: paddle/fluid/platform/collective_helper.h:63 NCCLCommContext —
process-global map ring_id→device→NCCLComm, populated by c_gen_nccl_id +
c_comm_init startup ops). Design delta (SURVEY.md §2.3, §5.8): communicators
become mesh AXES declared once; collectives become XLA HLO emitted by the
partitioner over ICI/DCN; there are no comm streams or sync ops to manage.

Axis-name conventions used across the framework:
  dp — data parallel         tp — tensor (model) parallel
  pp — pipeline parallel     sp — sequence/context parallel
  ep — expert parallel (MoE)
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["init_mesh", "init_hybrid_mesh", "get_mesh", "set_mesh",
           "reset_mesh", "mesh_axis_size", "in_spmd_region",
           "named_sharding", "MeshGuard", "auto_mesh", "shard_map",
           "axis_sizes", "axis_tiers", "LINK_TIERS", "DEFAULT_TIER"]

# ---------------------------------------------------------------------------
# two-tier topology grammar. A mesh description's axis value is either a
# plain int size (legacy form, link tier defaults to ICI) or a dict
#   {"size": 2, "tier": "dcn"[, "gbps": 25.0]}
# declaring the link tier the axis crosses: "ici" for intra-pod chip
# links, "dcn" for the inter-pod data-center network, an order of
# magnitude slower (SURVEY §2.3 DCN row; MLPerf TPU-v3 pod scaling).
# Per-device link bandwidths default from FLAGS_topology_{ici,dcn}_gbps
# so the cost model is tunable without touching call sites.
# ---------------------------------------------------------------------------

LINK_TIERS = ("ici", "dcn")
DEFAULT_TIER = "ici"


def _tier_gbps(tier: str) -> float:
    from ..core.flags import flag as _flag
    if tier == "dcn":
        return float(_flag("FLAGS_topology_dcn_gbps"))
    return float(_flag("FLAGS_topology_ici_gbps"))


def _axis_entry(value):
    """(size, tier_meta | None) for one axis value of a mesh description."""
    if isinstance(value, dict):
        size = int(value.get("size", 1))
        tier = str(value.get("tier", DEFAULT_TIER))
        if tier not in LINK_TIERS:
            raise ValueError(
                f"unknown link tier {tier!r} (choose from {LINK_TIERS})")
        gbps = float(value.get("gbps", _tier_gbps(tier)))
        return size, {"tier": tier, "gbps": gbps}
    return int(value), None


def axis_sizes(shape: Dict[str, object]) -> Dict[str, int]:
    """{axis: int} from a mesh description dict, tier grammar accepted."""
    return {str(k): _axis_entry(v)[0] for k, v in shape.items()}


def axis_tiers(mesh_or_shape) -> Dict[str, dict]:
    """{axis: {"tier": str, "gbps": float}} for every axis of a mesh
    description dict or a Mesh. Axes without declared tier metadata get
    the ICI default; a Mesh carries its tiers in `_link_tiers` (attached
    by init_mesh tier grammar / init_hybrid_mesh DCN layering)."""
    out: Dict[str, dict] = {}
    if mesh_or_shape is None:
        return out
    if isinstance(mesh_or_shape, dict):
        for k, v in mesh_or_shape.items():
            _, meta = _axis_entry(v)
            out[str(k)] = meta or {"tier": DEFAULT_TIER,
                                   "gbps": _tier_gbps(DEFAULT_TIER)}
        return out
    declared = dict(getattr(mesh_or_shape, "_link_tiers", {}) or {})
    for name in getattr(mesh_or_shape, "axis_names", ()):
        meta = declared.get(name)
        if isinstance(meta, str):
            meta = {"tier": meta, "gbps": _tier_gbps(meta)}
        out[str(name)] = dict(meta) if meta else \
            {"tier": DEFAULT_TIER, "gbps": _tier_gbps(DEFAULT_TIER)}
    return out


def shard_map(f, mesh=None, in_specs=None, out_specs=None, check=False):
    """Version-portable `shard_map`: `jax.shard_map` where it exists
    (newer jax; `check_vma=`), `jax.experimental.shard_map.shard_map`
    otherwise (`check_rep=`). The replication check defaults OFF — the
    pipeline/MoE SPMD programs here intermix psum/ppermute/all_to_all in
    ways the checker's older releases reject spuriously."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        try:
            return fn(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check)
        except TypeError:
            return fn(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check)
    except TypeError:
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

_lock = threading.Lock()
_meshes: Dict[str, Mesh] = {}
_default_name: Optional[str] = None


def init_mesh(shape: Dict[str, int] = None, name: str = "default",
              devices=None) -> Mesh:
    """Declare a named mesh once (the c_comm_init analog).

    shape: ordered {axis_name: size}; product must equal device count.
    Axis values may use the tier grammar ({"size": 2, "tier": "dcn"}) —
    sizes build the device array, tier metadata rides the Mesh as
    `_link_tiers` for the topology cost model (axis_tiers).
    Defaults to a pure data-parallel mesh over all devices.
    """
    global _default_name
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = {"dp": len(devices)}
    tiers = {k: m for k, m in
             ((k, _axis_entry(v)[1]) for k, v in shape.items()) if m}
    shape = axis_sizes(shape)
    sizes = list(shape.values())
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(sizes)  # sub-mesh allowed
    mesh = Mesh(arr, tuple(shape.keys()))
    # always (re)assign: jax interns equivalent Mesh objects, so a stale
    # _link_tiers from an earlier same-shape mesh must not leak through
    # (object.__setattr__ — jax's Mesh forbids ordinary reassignment)
    object.__setattr__(mesh, "_link_tiers", tiers)
    with _lock:
        _meshes[name] = mesh
        if _default_name is None or name == "default":
            _default_name = name
    return mesh


def init_hybrid_mesh(ici_shape: Dict[str, int],
                     dcn_shape: Dict[str, int] = None,
                     name: str = "default") -> Mesh:
    """Declare a mesh with DCN axes layered over per-slice ICI axes.

    Devices are grouped by slice (TPU `slice_index`; process index under
    the CPU emulation, where each host process stands in for a slice) and
    laid out so DCN axes vary slowest. Collectives over the inner (ICI)
    axes then stay inside a slice and only the outer (DCN) axes cross the
    data-center network — the dp-across-slices x tp-within-slice recipe
    (SURVEY §2.3 DCN row; replaces the reference's per-ring NCCL comm
    bootstrap gen_nccl_id_op_helper.cc:277).

      init_hybrid_mesh({"tp": 4}, {"dp": 2})   # 2 slices x 4 chips
    """
    devices = list(jax.devices())

    # group by TPU slice when the platform reports distinct slices;
    # otherwise by host process (the CPU emulation, where each process
    # stands in for a slice — and single-slice multi-host jobs, where DCN
    # crosses hosts)
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    use_slice = len(slice_ids) > 1 and None not in slice_ids

    def slice_of(d):
        return d.slice_index if use_slice else d.process_index

    groups: Dict[int, list] = {}
    for d in devices:
        groups.setdefault(slice_of(d), []).append(d)
    slices = [groups[k] for k in sorted(groups)]
    n_slices = len(slices)
    per_slice = len(slices[0])
    if any(len(s) != per_slice for s in slices):
        raise ValueError(
            f"uneven slices: {[len(s) for s in slices]} devices per slice")
    if dcn_shape is None:
        dcn_shape = {"dp": n_slices}
    overlap = set(dcn_shape) & set(ici_shape)
    if overlap:
        raise ValueError(
            f"axis name(s) {sorted(overlap)} appear in both dcn_shape and "
            "ici_shape; hybrid axes must be distinct (e.g. dp over DCN, "
            "tp/sp over ICI)")
    need_dcn = int(np.prod(list(dcn_shape.values())))
    need_ici = int(np.prod(list(ici_shape.values())))
    if need_dcn != n_slices:
        raise ValueError(
            f"dcn_shape {dcn_shape} needs {need_dcn} slices, have "
            f"{n_slices}")
    if need_ici != per_slice:
        raise ValueError(
            f"ici_shape {ici_shape} needs {need_ici} devices per slice, "
            f"have {per_slice}")
    arr = np.array([sorted(s, key=lambda d: d.id) for s in slices])
    arr = arr.reshape(list(dcn_shape.values()) + list(ici_shape.values()))
    mesh = Mesh(arr, tuple(dcn_shape.keys()) + tuple(ici_shape.keys()))
    # the DCN axes cross the slow tier by construction — tag them so the
    # topology cost model (axis_tiers / spmd_analyzer) prices them as such
    object.__setattr__(mesh, "_link_tiers", {
        ax: {"tier": "dcn", "gbps": _tier_gbps("dcn")} for ax in dcn_shape})
    return set_mesh(mesh, name)


def set_mesh(mesh: Mesh, name: str = "default"):
    global _default_name
    with _lock:
        _meshes[name] = mesh
        _default_name = name
    return mesh


def reset_mesh(name: str = None):
    """Drop a registered mesh (all of them when name is None). Mainly for
    tests: a leaked dp mesh silently turns every later single-device train
    step into a GSPMD-partitioned one."""
    global _default_name
    with _lock:
        if name is None:
            _meshes.clear()
            _default_name = None
        else:
            _meshes.pop(name, None)
            if _default_name == name:
                _default_name = next(iter(_meshes), None)


def get_mesh(name: str = None) -> Optional[Mesh]:
    with _lock:
        if name is not None:
            return _meshes.get(name)
        if _default_name is not None:
            return _meshes.get(_default_name)
    return None


def auto_mesh() -> Mesh:
    """Get-or-create the default mesh (pure DP over all devices)."""
    m = get_mesh()
    if m is None:
        m = init_mesh()
    return m


def mesh_axis_size(axis: str, name: str = None) -> int:
    """Size of a mesh axis. Inside an SPMD region (shard_map trace)
    the BOUND axis size is authoritative — the registry may hold a
    different default mesh (e.g. a test registered `{"dp": 8}` as
    "default" while the pipeline runs under a named `{"pp": 4}` mesh;
    reading the registry there silently degraded the pipeline to a
    single stage). Falls back to the registered mesh when the axis is
    not bound in the current trace."""
    try:
        from jax._src.core import get_axis_env
        env = get_axis_env()
        if axis in tuple(env.axis_names()):
            return int(env.axis_size(axis))
    except Exception:
        pass  # private accessor moved / axis unbound: registry fallback
    m = get_mesh(name)
    if m is None or axis not in m.axis_names:
        return 1
    return m.shape[axis]


def _axis_env_names():
    """Bound mesh-axis names of the current trace context, via the
    private jax accessor (the fast path; raises ImportError/AttributeError
    on jax versions that moved it — callers must fall back, NOT swallow)."""
    from jax._src.core import get_axis_env
    return tuple(get_axis_env().axis_names())


def _axis_bound_probe(axis: str) -> bool:
    """Public-API fallback: `lax.psum(axis)` is legal exactly when `axis`
    is bound here, and `jax.eval_shape` asks that question abstractly
    (no op enters the enclosing trace). An unbound name raises NameError;
    anything else jax raises for a malformed probe also means 'not a
    bound SPMD axis'."""
    import jax.numpy as jnp
    try:
        jax.eval_shape(lambda: jax.lax.psum(jnp.zeros((), jnp.float32),
                                            axis))
        return True
    except NameError:
        return False
    except Exception:
        return False


def in_spmd_region(axis: str = None) -> bool:
    """True when tracing inside shard_map where `axis` is bound —
    i.e. lax.psum(axis) is legal here.

    Prefers the private jax axis-env accessor; when a jax version moves
    it, degrades to a public-API probe (eval_shape over lax.psum) that
    still answers correctly for named axes. With axis=None the fallback
    probes every registered mesh's axes (plus the conventional five) —
    a correct answer for any axis this framework could have bound."""
    try:
        names = _axis_env_names()
    except (ImportError, AttributeError):
        if axis is not None:
            return _axis_bound_probe(axis)
        with _lock:
            candidates = {a for m in _meshes.values() for a in m.axis_names}
        candidates |= {"dp", "tp", "pp", "sp", "ep"}
        return any(_axis_bound_probe(a) for a in sorted(candidates))
    if axis is None:
        return bool(names)
    return axis in names


def named_sharding(spec: PartitionSpec, name: str = None) -> NamedSharding:
    return NamedSharding(auto_mesh() if name is None else get_mesh(name), spec)


class MeshGuard:
    """`with MeshGuard(mesh):` — scope the jax mesh context manager."""

    def __init__(self, mesh: Mesh = None, name: str = None):
        self.name = name
        self.mesh = mesh or get_mesh(name)

    def __enter__(self):
        if self.mesh is None:
            with _lock:
                have = sorted(_meshes)
            want = self.name if self.name is not None else \
                "<default>"
            raise RuntimeError(
                f"MeshGuard: no mesh named {want!r} in the mesh registry "
                f"(registered: {have or 'none'}). Declare one with "
                "init_mesh({'dp': n, ...}) / init_hybrid_mesh(...) or "
                "pass a Mesh explicitly: MeshGuard(mesh)")
        self._cm = self.mesh
        self._cm.__enter__()
        return self.mesh

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)
