"""LocalSGD — periodic parameter averaging over the dp axis.

Analog of reference meta_optimizers/localsgd_optimizer.py (LocalSGD and
AdaptiveLocalSGD: replicas run k_steps of purely local updates, then
broadcast-average parameters; the adaptive variant grows k as loss
stabilizes, Lin et al. 2018 "Don't Use Large Mini-Batches, Use Local
SGD").

TPU-native form: under the single-controller SPMD model "divergent
replicas" are expressed explicitly — parameters carry a leading replica
axis sharded over dp inside shard_map, each shard steps locally, and the
periodic sync is one lax.cond'ed pmean over the axis. The whole k-step
round stays inside one jitted computation, so XLA schedules the sync
collective on ICI like any other op (no host round-trips between local
steps, unlike the reference's program-rewriting pass).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from . import mesh as mesh_mod

__all__ = ["local_sgd_step", "LocalSGD", "replicate_for_localsgd"]


def local_sgd_step(step_fn, axis="dp", k_steps=4):
    """Wrap a per-replica update into a LocalSGD update.

    step_fn(params, batch) -> (loss, new_params) — a PURE local update
    (its grads/optimizer must NOT do their own cross-replica reduction;
    that is the point of LocalSGD).

    Returns fn(params, counter, batch) -> (loss, new_params, counter+1)
    for use INSIDE shard_map over `axis`: steps locally, and averages
    parameters over `axis` whenever the incoming counter hits a sync
    boundary. Losses are averaged every step (cheap scalar) for logging.
    """
    def wrapped(params, counter, batch):
        loss, new_params = step_fn(params, batch)
        counter = counter + 1

        def sync(p):
            return jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, axis), p)

        new_params = jax.lax.cond(counter % k_steps == 0, sync,
                                  lambda p: p, new_params)
        return jax.lax.pmean(loss, axis), new_params, counter

    return wrapped


def replicate_for_localsgd(params, axis="dp", mesh=None):
    """Tile a pytree of parameters with a leading replica dimension
    sharded over `axis` (each dp shard then owns a private copy inside
    shard_map)."""
    mesh = mesh or mesh_mod.get_mesh()
    n = mesh.shape[axis]
    sh = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            jnp.broadcast_to(x[None], (n,) + x.shape), sh), params)


class LocalSGD:
    """Driver object: owns the replicated params + sync counter and the
    jitted shard_map step.

        trainer = LocalSGD(step_fn, params, k_steps=4)   # under a mesh
        for batch in data:                                # batch: [dp*b, ...]
            loss = trainer.step(batch)
        params = trainer.averaged_params()

    Adaptive variant (reference AdaptiveLocalSGDOptimizer): pass
    init_k_steps and the schedule grows k by +1 every time the synced
    loss improves by < rel_tol (longer local phases once training
    stabilizes, capped at max_k_steps). The k change re-jits — by design,
    it happens a handful of times per run.
    """

    def __init__(self, step_fn, params, axis="dp", k_steps=4, mesh=None,
                 adaptive=False, max_k_steps=16, rel_tol=0.01):
        self.mesh = mesh or mesh_mod.get_mesh()
        self.axis = axis
        self.k_steps = int(k_steps)
        self.adaptive = adaptive
        self.max_k_steps = int(max_k_steps)
        self.rel_tol = float(rel_tol)
        self._step_fn = step_fn
        self.params = replicate_for_localsgd(params, axis, self.mesh)
        self.counter = jax.device_put(
            jnp.zeros((self.mesh.shape[axis],), jnp.int32),
            NamedSharding(self.mesh, P(axis)))
        self._compiled = {}
        self._last_sync_loss = None

    def _build(self, k):
        inner = local_sgd_step(self._step_fn, self.axis, k)

        def spmd(params, counter, batch):
            loss, params, counter = inner(
                jax.tree_util.tree_map(lambda x: x[0], params),
                counter[0], batch)
            return (loss[None],
                    jax.tree_util.tree_map(lambda x: x[None], params),
                    counter[None])

        pspec = jax.tree_util.tree_map(lambda _: P(self.axis), self.params)
        from . import mesh as _mesh_mod
        fn = jax.jit(_mesh_mod.shard_map(
            spmd, mesh=self.mesh,
            in_specs=(pspec, P(self.axis), P(self.axis)),
            out_specs=(P(self.axis), pspec, P(self.axis))))
        return fn

    def step(self, batch):
        """batch: leading dim = dp_degree * per_replica_batch."""
        k = self.k_steps
        if k not in self._compiled:
            self._compiled[k] = self._build(k)
        loss, self.params, self.counter = self._compiled[k](
            self.params, self.counter, batch)
        loss = float(loss[0])
        if self.adaptive and int(self.counter[0]) % k == 0:
            if self._last_sync_loss is not None and \
                    loss > self._last_sync_loss * (1 - self.rel_tol):
                self.k_steps = min(self.k_steps + 1, self.max_k_steps)
            self._last_sync_loss = loss
        return loss

    def averaged_params(self):
        """Final cross-replica average (host-side; used once at the end)."""
        return jax.tree_util.tree_map(
            lambda x: jnp.mean(x.astype(jnp.float32), axis=0).astype(
                x.dtype), self.params)
