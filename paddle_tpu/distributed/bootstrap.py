"""Multi-host bootstrap: the PADDLE_* env contract -> jax.distributed.

Analog of the reference's NCCL-id TCP rendezvous
(operators/collective/gen_nccl_id_op_helper.cc:205,277 — rank 0 listens,
others connect, then c_comm_init builds the rings) and its env protocol
(distributed/utils.py:406-409). TPU-native design: instead of exchanging
communicator ids, processes join JAX's coordination service over DCN —
PADDLE_TRAINER_ENDPOINTS[0] is the coordinator, PADDLE_TRAINER_ID the
process id — after which `jax.devices()` is the *global* device set and
mesh axes span hosts; collectives ride ICI within a slice and DCN across
(SURVEY.md §2.3).
"""
from __future__ import annotations

import os

__all__ = ["maybe_initialize_distributed", "is_initialized"]

_initialized = False


def is_initialized() -> bool:
    return _initialized


def maybe_initialize_distributed(timeout_s: int = 120) -> bool:
    """Join the multi-host coordination service when the PADDLE_* env
    contract declares more than one trainer. Idempotent; single-process
    jobs (or already-initialized runtimes) are a no-op. Returns True if
    this call (or a previous one) initialized multi-host mode."""
    global _initialized
    if _initialized:
        return True
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    endpoints = [e for e in os.environ.get(
        "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]
    if n <= 1 or not endpoints:
        return False
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if len(endpoints) != n:
        raise ValueError(
            f"PADDLE_TRAINER_ENDPOINTS has {len(endpoints)} entries but "
            f"PADDLE_TRAINERS_NUM={n}")

    import jax
    coordinator = endpoints[0]  # rank 0's endpoint doubles as coordinator,
    # exactly like the reference's rank-0 TCP rendezvous server
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=n,
        process_id=rank,
        initialization_timeout=timeout_s)
    _initialized = True
    return True
