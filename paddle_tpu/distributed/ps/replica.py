"""Primary–backup replication manager for the PS storage tier.

PR 2 proved the *transport* exactly-once under chaos; this module makes
the *storage* survive a permanent server death (ROADMAP "extend the PR 2
proof from transport to storage"; the TensorFlow paper treats PS
replication/recovery as table stakes, and the TPU-v3 Pods paper is why
the embedding tier must stay up while the dense step runs). One
``ReplicaManager`` rides inside every ``PSServer`` of a replicated
cluster and owns four protocols:

**Routing** (`check`): every shard-map-routed request carries the
client's map epoch (+ target shard). An epoch mismatch or a write aimed
at a non-primary raises ``ShardMapStale`` carrying the server's current
map — the redirect is never cached in the replay cache (the same replay
id must still run for real on the right server) and costs the client one
round trip.

**Replication** (`record_and_forward`): a primary applies a mutation
locally, stamps it with a per-table sequence number into a bounded
replay-keyed delta log, then *synchronously* forwards it to every live
backup under the ORIGINAL client replay id — so a client retry after the
primary dies dedupes on the backup against the forward that already
landed (the exactly-once keystone of failover), and a forward retry
dedupes against itself via the backup's ReplayCache. Apply+log+forward
run under a per-table gate, which keeps per-table forwards in sequence
order over the serialized per-backup connection. The ack returns to the
client only once the write is durable on the quorum
(``PADDLE_PS_REPLICA_QUORUM``, 0 = every live replica); an unreachable
backup is evicted from the map (epoch bump, broadcast) rather than
wedging writes.

**Failure detection** (`_beat_loop`/`_watch_loop`): every server beats
``replica_beat`` into its peers every ``PADDLE_PS_HEARTBEAT_S``; a
primary whose beats stop for ``PADDLE_PS_HEARTBEAT_TIMEOUT_S`` is
suspected, and the FIRST live backup of each of its shards promotes
itself: installs ``map.without(dead)`` (epoch+1) and broadcasts it.
Epochs resolve races — newer maps win everywhere, and beat replies carry
epochs so a behind server fetches the current map. A deposed primary
that still tries to forward gets a ``ShardMapStale`` from its backups,
adopts the new map, and surfaces the redirect to its client instead of
acking a write that is durable nowhere that serves.

**Rejoin/catch-up** (`rejoin`/`fetch`/`attach`): a restarted (or
falsely-evicted) server pulls each table's full snapshot + sequence
cursor from the new primary (`replica_fetch`), then attaches
(`replica_attach`): the primary — holding every table gate so the cutoff
is exact — adds it to the map as a backup and hands back the delta-log
suffix past the snapshot cursor. The rejoiner applies those deltas
through the replay cache under their original rids while incoming live
forwards PARK on the catch-up event, so deltas and forwards interleave
exactly once and in order. A cursor that has fallen off the bounded log
(``PADDLE_PS_REPLICA_DELTA_LOG``) answers ``restart`` and the rejoiner
re-fetches.

Observability: counters ``ps.replica.{forwards,promotions,catchups,
stale_maps,forward_failures,evictions}`` (stale_maps is bumped by the
client on redirect) and spans ``ps.replica/{forward,promote,catchup}``
cover every hop; all knobs are ``PADDLE_PS_REPLICA_*`` /
``PADDLE_PS_HEARTBEAT_*`` flags.

Scope: single-failure-at-a-time tolerance per shard (classic
primary–backup without consensus — concurrent epoch bumps for the SAME
epoch are resolved arbitrarily by arrival order, which cannot happen in
the chained default layout where each server primaries exactly one
shard). Barrier tables are routed by the map but not replicated (their
state is a transient rendezvous, not training state).
"""
from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from ...core import monitor as _monitor
from ...core import trace as _trace
from ...core.flags import flag as _flag
from .rpc import Connection
from .shard_map import ShardMap, ShardMapStale

__all__ = ["ReplicaManager", "ReplayUncacheable", "REPLICATED_MUTATIONS"]

# table mutations that replicate (barrier excluded by design) — the
# single source of truth; PSServer._handle imports it to decide which
# methods run under the gate+forward path
REPLICATED_MUTATIONS = frozenset({
    "push_dense_grad", "set_dense", "push_sparse_grad",
    "push_sparse_delta"})


class ReplayUncacheable(RuntimeError):
    """A replication error whose reply must NOT be committed to the
    replay cache: the same rid is expected to run for real on a retry
    (rpc._serve_one aborts the rid instead — a cached error would
    replay forever and permanently poison the client's replay key)."""

    replay_uncacheable = True


def _filter_sparse_state(st, shard, n_shards):
    """Restrict a SparseTable state dict to the rows of one shard —
    catch-up transfers one shard at a time, and a primary's table also
    holds rows of OTHER shards it backs (or once served); leaking those
    into a rejoiner could shadow fresher rows it synced elsewhere."""
    ids = np.asarray(st["ids"], np.int64).reshape(-1)
    mask = (ids % np.int64(n_shards)) == shard
    keep = ids[mask]
    values = np.asarray(st["values"], np.float32)
    if len(ids):
        values = values.reshape(len(ids), -1)[mask]
    kept = {int(i) for i in keep}
    slots = {i: s for i, s in (st.get("slots") or {}).items()
             if int(i) in kept}
    return {"ids": keep, "values": values, "lr": st["lr"], "slots": slots}


class ReplicaManager:
    def __init__(self, server, endpoint, shard_map=None, peers=None,
                 n_backups=None, heartbeat_s=None, heartbeat_timeout_s=None,
                 rpc_opts=None, rejoin=True):
        """server: the owning PSServer (started; tables + replay cache
        live there). shard_map: initial ShardMap/dict; a rejoining server
        passes None + `peers` (live endpoints to learn the map from).
        rpc_opts: Connection overrides for forward channels (tests pass
        fast timeouts)."""
        self._server = server
        self.endpoint = endpoint
        self._peers = list(peers or ())
        self._n_backups = int(_flag("PADDLE_PS_REPLICA_BACKUPS")
                              if n_backups is None else n_backups)
        self._hb_s = float(_flag("PADDLE_PS_HEARTBEAT_S")
                           if heartbeat_s is None else heartbeat_s)
        self._hb_timeout = float(_flag("PADDLE_PS_HEARTBEAT_TIMEOUT_S")
                                 if heartbeat_timeout_s is None
                                 else heartbeat_timeout_s)
        self._rpc_opts = dict(rpc_opts or {})
        self._rejoin_enabled = bool(rejoin)

        self._map_lock = threading.RLock()
        if shard_map is None:
            self._map = ShardMap.default([endpoint])
            self._needs_bootstrap = bool(self._peers)
        else:
            self._map = shard_map if isinstance(shard_map, ShardMap) \
                else ShardMap.from_dict(shard_map)
            self._needs_bootstrap = False

        # per-table: apply+log+forward gate, mutation cursor, delta log
        self._gates: dict[str, threading.Lock] = {}
        self._gates_lock = threading.Lock()
        self._seq: dict[str, int] = {}
        self._dlog: dict[str, deque] = {}

        # catch-up parking: forwards for these tables wait until the
        # delta suffix has been applied, preserving sequence order
        self._catching_up: set[str] = set()
        self._catchup_done = threading.Event()
        self._catchup_done.set()

        # membership view
        self._last_beat: dict[str, float] = {}
        self._started_at = time.monotonic()

        # data (forward) and beat connections, separate so a large
        # forward can't delay a heartbeat into a false suspicion
        self._conns_lock = threading.Lock()
        self._data_conns: dict[str, Connection] = {}
        self._beat_conns: dict[str, Connection] = {}

        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._beat_loop, daemon=True,
                             name=f"ps-replica-beat@{endpoint}"),
            threading.Thread(target=self._watch_loop, daemon=True,
                             name=f"ps-replica-watch@{endpoint}"),
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------ helpers
    @property
    def shard_map(self) -> ShardMap:
        return self._map

    def map_dict(self):
        return self._map.to_dict()

    def replicates(self, table_name):
        t = self._server._tables.get(table_name)
        return t is not None and hasattr(t, "state")

    def _replicated_tables(self):
        return sorted(n for n in self._server._tables
                      if self.replicates(n))

    def gate(self, table):
        with self._gates_lock:
            g = self._gates.get(table)
            if g is None:
                g = self._gates[table] = threading.Lock()
            return g

    def _conn(self, pool, ep, **extra):
        with self._conns_lock:
            c = pool.get(ep)
            if c is None:
                opts = dict(self._rpc_opts)
                opts.update(extra)
                c = pool[ep] = Connection(ep, **opts)
            return c

    def _data_conn(self, ep):
        return self._conn(self._data_conns, ep, fail_fast_refused=True)

    def _beat_conn(self, ep):
        return self._conn(self._beat_conns, ep,
                          timeout=min(2.0, self._hb_timeout),
                          max_retries=0, connect_retry_s=0.5,
                          fail_fast_refused=True)

    def _drop_conn(self, ep):
        with self._conns_lock:
            for pool in (self._data_conns, self._beat_conns):
                c = pool.pop(ep, None)
                if c is not None:
                    c.close()

    # --------------------------------------------------------- map install
    def install(self, map_dict, broadcast=False):
        """Adopt a map if it is newer than ours. Returns True on adopt."""
        new = map_dict if isinstance(map_dict, ShardMap) \
            else ShardMap.from_dict(map_dict)
        with self._map_lock:
            if new.epoch <= self._map.epoch:
                return False
            self._map = new
        if broadcast:
            self._broadcast(new)
        return True

    def _install_bumped(self, new: ShardMap):
        with self._map_lock:
            if new.epoch <= self._map.epoch:
                return False
            self._map = new
        self._broadcast(new)
        return True

    def _broadcast(self, new: ShardMap):
        """Best-effort push of a new map to every member + known peer —
        redirects and beat-epoch gossip cover anyone missed here."""
        d = new.to_dict()
        for ep in {*new.servers, *self._peers} - {self.endpoint}:
            try:
                self._beat_conn(ep).call("install_shard_map", shard_map=d)
            except (ConnectionError, OSError):
                pass

    # ------------------------------------------------------- request path
    def check(self, method, req):
        """Routing check, called by PSServer._handle before any apply.
        Pops the routing keys; returns (shard, is_forward). Raises
        ShardMapStale on an epoch/primary mismatch."""
        shard = req.pop("__shard__", None)
        fwd_epoch = req.pop("__fwd__", None)
        epoch = req.pop("__epoch__", None)
        m = self._map
        if fwd_epoch is not None:
            # a forward from a primary. A deposed primary (older epoch)
            # must not smuggle writes past a promotion — teach it.
            if fwd_epoch < m.epoch:
                raise ShardMapStale(m.to_dict(),
                                    "forward from a deposed primary")
            self._park_if_catching_up(req.get("table"))
            return shard, True
        if epoch is None:
            return shard, False        # legacy/unrouted client: no checks
        if epoch != m.epoch:
            raise ShardMapStale(
                m.to_dict(), f"client epoch {epoch} != server {m.epoch}")
        if shard is not None and m.primary(shard) != self.endpoint:
            raise ShardMapStale(
                m.to_dict(), f"{self.endpoint} is not primary of shard "
                             f"{shard}")
        return shard, False

    def _park_if_catching_up(self, table):
        """Forwards for a table mid-catch-up wait until its delta suffix
        has been applied — sequence order is preserved end to end. A
        catch-up that outlasts the park window fails the forward LOUDLY
        (the primary's quorum/eviction path deals with it) instead of
        letting it apply ahead of earlier-sequenced suffix entries."""
        if table in self._catching_up:
            if not self._catchup_done.wait(timeout=30.0):
                raise ReplayUncacheable(
                    f"ps replica: forward for table {table!r} parked "
                    ">30s behind an unfinished catch-up")

    def seen(self, table, rid):
        """Is `rid` already in `table`'s delta log? True means this
        exact mutation was applied+logged here before — a retry of a
        quorum-failed call must re-FORWARD it but never re-APPLY it."""
        if rid is None:
            return False
        log = self._dlog.get(table)
        if not log:
            return False
        rid = tuple(rid)
        return any(e[1] is not None and tuple(e[1]) == rid for e in log)

    def record_and_forward(self, table, shard, method, req, rid,
                           is_forward, log_entry=True):
        """Called under gate(table), AFTER the local apply: stamp the
        mutation into the delta log; when acting as primary, forward it
        to every live backup under the original rid and enforce the
        write quorum. `log_entry=False` skips the apply-side bookkeeping
        for a quorum-failure retry whose mutation is already logged —
        only the forward + quorum check re-run."""
        m = self._map
        if shard is None:
            ids = req.get("ids")
            if ids is not None and np.asarray(ids).size:
                shard = int(np.asarray(ids).reshape(-1)[0]) % m.n_shards
            else:
                shard = m.shard_of_name(table)
        if log_entry:
            seq = self._seq[table] = self._seq.get(table, 0) + 1
            log = self._dlog.get(table)
            if log is None:
                log = self._dlog[table] = deque(
                    maxlen=max(1,
                               int(_flag("PADDLE_PS_REPLICA_DELTA_LOG"))))
            log.append((seq, rid, method, dict(req), int(shard)))
        if is_forward:
            return
        backups = [b for b in m.backups(shard) if b != self.endpoint]
        acked = 1                              # self
        for b in backups:
            with _trace.span("ps.replica/forward", table=table,
                             shard=shard, backup=b, method=method,
                             epoch=m.epoch):
                try:
                    kw = {"_rid": rid} if rid is not None else {}
                    self._data_conn(b).call(
                        method, _mutating=True, __fwd__=m.epoch,
                        table=table, **kw, **req)
                    _monitor.stat_add("ps.replica.forwards")
                    acked += 1
                except ShardMapStale as e:
                    # the backup knows a newer world: we were deposed.
                    # Adopt, and DO NOT ack — re-raise so the client
                    # re-pushes (same rid) to the real primary.
                    _monitor.stat_add("ps.replica.forward_failures")
                    self.install(e.shard_map_dict)
                    raise
                except (ConnectionError, OSError):
                    _monitor.stat_add("ps.replica.forward_failures")
                    self._evict(b)
        quorum = int(_flag("PADDLE_PS_REPLICA_QUORUM"))
        if quorum and acked < quorum:
            # already applied+logged locally, so the rid must stay
            # retryable: ReplayUncacheable makes serve() abort it, and
            # the retry re-enters through seen() — forward-only, no
            # second apply — once a backup rejoins or is evicted
            raise ReplayUncacheable(
                f"ps replica: write quorum not met for {table!r}: "
                f"{acked}/{quorum} replicas acked")

    def _evict(self, ep):
        """Remove an unreachable member from the map (epoch bump +
        broadcast). Its state is NOT lost if it comes back — it rejoins
        through catch-up like any restarted server."""
        with self._map_lock:
            if ep not in self._map.servers:
                return
            new = self._map.without(ep)
            self._map = new
        self._drop_conn(ep)
        _monitor.stat_add("ps.replica.evictions")
        self._broadcast(new)

    # ----------------------------------------------------------- liveness
    def on_beat(self, from_ep, epoch):
        self._last_beat[from_ep] = time.monotonic()
        return {"epoch": self._map.epoch}

    def _beat_loop(self):
        while not self._stop.wait(self._hb_s):
            m = self._map
            mine = m.epoch
            for ep in {*m.servers, *self._peers} - {self.endpoint}:
                try:
                    r = self._beat_conn(ep).call(
                        "replica_beat", **{"from": self.endpoint,
                                           "epoch": mine})
                    peer_epoch = (r or {}).get("epoch", 0)
                    if peer_epoch > mine:
                        md = self._beat_conn(ep).call("get_shard_map")
                        if md:
                            self.install(md)
                    elif peer_epoch < mine:
                        self._beat_conn(ep).call(
                            "install_shard_map", shard_map=m.to_dict())
                except (ConnectionError, OSError):
                    pass
            if self._needs_bootstrap:
                self._bootstrap()

    def _alive(self, ep, now=None):
        if ep == self.endpoint:
            return True
        now = time.monotonic() if now is None else now
        last = self._last_beat.get(ep, self._started_at)
        return (now - last) < self._hb_timeout

    def _watch_loop(self):
        interval = max(0.05, self._hb_timeout / 4.0)
        while not self._stop.wait(interval):
            now = time.monotonic()
            m = self._map
            for shard in range(m.n_shards):
                primary = m.primary(shard)
                if primary == self.endpoint or self._alive(primary, now):
                    continue
                live_backups = [b for b in m.backups(shard)
                                if self._alive(b, now)]
                if live_backups and live_backups[0] == self.endpoint:
                    self._promote(primary)
            if self._rejoin_enabled and not self._needs_bootstrap \
                    and self.endpoint not in m.servers:
                # we were evicted (false suspicion or a lost race) —
                # our state may have diverged; re-enter via catch-up
                try:
                    self.rejoin()
                except (ConnectionError, OSError, RuntimeError):
                    pass

    def _promote(self, dead):
        with self._map_lock:
            if dead not in self._map.servers:
                return
            now = time.monotonic()
            new = self._map.without(dead)
            # a multi-failure window (primary AND its leading backups
            # dead past the deadline) must not install a corpse as
            # primary — without() promotes the first LISTED backup, so
            # sweep every dead member that would end up primarying a
            # shard in the same epoch window. Each pass removes >=1
            # server, so this terminates; tombstoned unrecoverable
            # primaries are already out of `servers` and stay listed.
            while True:
                stale = [ep for ep in new.servers
                         if ep != self.endpoint
                         and not self._alive(ep, now)
                         and new.shards_primaried_by(ep)]
                if not stale:
                    break
                for ep in stale:
                    new = new.without(ep)
            with _trace.span("ps.replica/promote", dead=dead,
                             new_epoch=new.epoch,
                             promoted=self.endpoint):
                self._map = new
        self._drop_conn(dead)
        _monitor.stat_add("ps.replica.promotions")
        self._broadcast(new)

    # ----------------------------------------------------- rejoin/catch-up
    def _bootstrap(self):
        """First map fetch for a server started with peers + no map."""
        best = None
        for ep in self._peers:
            if ep == self.endpoint:
                continue
            try:
                md = self._beat_conn(ep).call("get_shard_map")
            except (ConnectionError, OSError):
                continue
            if md and (best is None or md["epoch"] > best["epoch"]):
                best = md
        if best is None:
            return
        with self._map_lock:
            new = ShardMap.from_dict(best)
            if new.epoch >= self._map.epoch:
                self._map = new
        self._needs_bootstrap = False
        if self._rejoin_enabled and self.endpoint not in self._map.servers:
            try:
                self.rejoin()
            except (ConnectionError, OSError, RuntimeError):
                self._needs_bootstrap = True    # retry on the next beat

    def rejoin(self):
        """Re-enter the map as a backup of every under-replicated shard:
        snapshot + delta-log catch-up from each shard's primary."""
        m = self._map
        shards = [s for s in m.under_replicated(self._n_backups)
                  if m.primary(s) != self.endpoint
                  and self.endpoint not in m.backups(s)]
        if not shards:
            return False
        with _trace.span("ps.replica/catchup", shards=list(shards),
                         endpoint=self.endpoint):
            for shard in shards:
                self._catchup_shard(shard)
        _monitor.stat_add("ps.replica.catchups")
        return True

    def _catchup_shard(self, shard, max_rounds=3):
        primary = self._map.primary(shard)
        conn = self._data_conn(primary)
        tables = None
        for _round in range(max_rounds):
            snap = conn.call("replica_fetch")
            tables = sorted(snap)
            # load snapshots + cursors; park forwards until deltas land
            self._catchup_done.clear()
            self._catching_up.update(tables)
            n_shards = self._map.n_shards
            try:
                for t, entry in snap.items():
                    table = self._server._tables.get(t)
                    if table is None or not hasattr(table, "load_state"):
                        continue
                    st = entry["state"]
                    with self.gate(t):
                        if "ids" in st:        # sparse: merge one shard
                            table.load_state(_filter_sparse_state(
                                st, int(shard), n_shards), merge=True)
                        elif self._map.shard_of_name(t) == int(shard):
                            table.load_state(st)   # dense of this shard
                        else:
                            continue           # dense of another shard
                        self._seq[t] = max(self._seq.get(t, 0),
                                           int(entry["seq"]))
                        self._dlog.pop(t, None)
                        # snapshot-covered rids of THIS shard: a late
                        # forward-retry must replay, not re-apply
                        replay = getattr(self._server, "replay", None)
                        if replay is not None:
                            for rid, rshard in entry.get("rids", ()):
                                if int(rshard) != int(shard):
                                    continue
                                state, _ = replay.begin(tuple(rid))
                                if state == "run":
                                    replay.commit(tuple(rid),
                                                  {"result": True})
                reply = conn.call(
                    "replica_attach", _mutating=True,
                    endpoint=self.endpoint, shard=int(shard),
                    seqs={t: int(snap[t]["seq"]) for t in snap})
                if reply.get("restart"):
                    continue        # cursor fell off the bounded log
                self.install(reply["shard_map"])
                self._apply_deltas(reply.get("deltas", {}))
                return True
            finally:
                self._catching_up.difference_update(tables or ())
                self._catchup_done.set()
        raise RuntimeError(
            f"ps replica: catch-up for shard {shard} kept missing the "
            f"delta log after {max_rounds} rounds "
            "(PADDLE_PS_REPLICA_DELTA_LOG too small for the write rate?)")

    def _apply_deltas(self, deltas):
        """Apply the attach delta suffix through the replay cache under
        each entry's ORIGINAL rid, so live forwards (and client retries)
        arriving later dedupe against it."""
        replay = getattr(self._server, "replay", None)
        for t, entries in deltas.items():
            table = self._server._tables.get(t)
            if table is None:
                continue
            for seq, rid, method, payload in entries:
                run = True
                if rid is not None and replay is not None:
                    state, _payload = replay.begin(tuple(rid))
                    run = state == "run"
                if run:
                    with self.gate(t):
                        self._server._apply_table_op(table, method,
                                                     dict(payload))
                        self._seq[t] = max(self._seq.get(t, 0), int(seq))
                    if rid is not None and replay is not None:
                        replay.commit(tuple(rid), {"result": True})

    # ----------------------------------------------- primary-side handlers
    def fetch(self):
        """replica_fetch: per-table consistent (state, cursor) pairs,
        plus the (rid, shard) pairs currently in the delta log — their
        mutations are reflected in the snapshot, and the rejoiner
        registers them in its replay cache so a late forward-retry of
        one (a quorum-failed call) replays instead of re-applying on
        top of the snapshot."""
        out = {}
        for t in self._replicated_tables():
            table = self._server._tables[t]
            with self.gate(t):
                out[t] = {"state": table.state(),
                          "seq": int(self._seq.get(t, 0)),
                          "rids": [[e[1], e[4]]
                                   for e in self._dlog.get(t, ())
                                   if e[1] is not None]}
        return out

    def attach(self, endpoint, shard, seqs):
        """replica_attach: holding EVERY table gate (so the cutoff is
        exact), add the rejoiner to the map — forwards to it start the
        instant the gates release — and return the delta-log suffix past
        its snapshot cursors."""
        tables = self._replicated_tables()
        gates = [self.gate(t) for t in tables]
        for g in gates:
            g.acquire()
        try:
            shard = int(shard)
            deltas = {}
            for t in tables:
                cutoff = int(seqs.get(t, 0))
                cur = self._seq.get(t, 0)
                if cur <= cutoff:
                    deltas[t] = []
                    continue
                log = self._dlog.get(t, ())
                suffix = [e for e in log if e[0] > cutoff]
                # contiguity on the UNFILTERED log: a gap means the
                # bounded log already dropped entries the cursor needs
                if not suffix or suffix[0][0] != cutoff + 1:
                    return {"restart": True}
                deltas[t] = [(e[0], e[1], e[2], e[3]) for e in suffix
                             if e[4] == shard]
            with self._map_lock:
                new = self._map.with_backup(shard, endpoint)
                self._map = new
        finally:
            for g in gates:
                g.release()
        self._last_beat[endpoint] = time.monotonic()
        self._broadcast(new)
        return {"shard_map": new.to_dict(), "deltas": deltas}

    # -------------------------------------------------------------- admin
    def close(self):
        self._stop.set()
        self._catchup_done.set()
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=2.0)
        with self._conns_lock:
            for pool in (self._data_conns, self._beat_conns):
                for c in pool.values():
                    c.close()
                pool.clear()
