"""HeterPS — tiered accelerator-resident embedding cache over the host PS.

Reference tier: framework/fleet/heter_ps/hashtable.h + heter_comm.h (a
GPU-resident concurrent hashtable caching hot embedding rows, backed by
the CPU parameter server). TPU redesign: the table is a pair of jnp
arrays (open-addressing keys [cap] i64 + values [cap, dim]) living in
HBM, with LOOKUP as a fully vectorized fixed-probe gather that jits into
the training step, and INSERT as a lax.fori_loop of dynamic updates (runs
once per batch on the miss set, off the hot path). No device hashtable
kernels to hand-write — XLA lowers both to gathers/scatters.

The cache is TIERED (HeterPS lineage — tables larger than device memory):

  device tier   hot-id LRU, bounded by PADDLE_PS_HETER_CACHE_ROWS; rows
                past the bound evict oldest-first (`ps.heter.evictions`)
  host tier     evicted rows park in host RAM, bounded by
                PADDLE_PS_HETER_HOST_ROWS; a host hit re-promotes to the
                device tier without a PS round trip (`ps.heter.host_hits`)
  PS tier       authoritative sharded storage; misses in both tiers pull
                through the client's batched deduped cross-shard fan-out

Semantics: read-through cache with push-through writes —
  rows = cache.pull(ids)        # device hits + host hits + PS misses
  ...                           # grads computed on device
  cache.push_grad(ids, grads)   # goes to the PS (server accessor owns
                                # the update rule), cached copies refresh
so the server stays authoritative (same division of labor as the
reference: hashtable.h caches, the DownpourPsClient owns optimizer state).

Coherence across MEMBERSHIP CHANGES: the cache registers a shard-map
listener on its PSClient (`add_map_listener`), so every adoption of a
newer map — stale-epoch redirect, failover promotion, eviction gossip —
invalidates BOTH tiers (`ps.heter.invalidations`): a row cached before a
promotion can never be served after it. A pull that was already in
flight when the epoch moved re-checks the epoch before populating the
tiers and skips the insert, closing the race where pre-change rows
sneak into a post-change cache.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from ...core import monitor as _monitor
from ...core.flags import flag as _flag

__all__ = ["DeviceHashTable", "HeterPSCache"]

_EMPTY = np.int64(-1)


def _mix(h):
    """splitmix64 finalizer — good avalanche for sequential ids."""
    import jax.numpy as jnp
    h = (h ^ (h >> 30)) * jnp.int64(-4658895280553007687)   # 0xbf58476d1ce4e5b9
    h = (h ^ (h >> 27)) * jnp.int64(-7723592293110705685)   # 0x94d049bb133111eb
    return h ^ (h >> 31)


class DeviceHashTable:
    """Fixed-capacity open-addressing (linear probe) id -> row table as a
    functional pytree of device arrays. Supports vectorized remove() so
    an LRU layer above can evict; lookups scan the FULL probe window
    (no early stop at an empty slot), which is what makes removal safe
    under linear probing without tombstones."""

    def __init__(self, capacity, dim, max_probes=16, dtype="float32"):
        import jax.numpy as jnp
        self.capacity = int(capacity)
        self.dim = int(dim)
        self.max_probes = int(max_probes)
        self.keys = jnp.full((self.capacity,), _EMPTY, jnp.int64)
        self.values = jnp.zeros((self.capacity, self.dim), dtype)
        self._count = 0

    # ---- pure kernels ----------------------------------------------------
    def _slots(self, ids):
        """[n, max_probes] candidate slots per query id."""
        import jax.numpy as jnp
        h = _mix(ids.astype(jnp.int64)) % self.capacity
        probe = jnp.arange(self.max_probes, dtype=jnp.int64)
        return (h[:, None] + probe[None, :]) % self.capacity

    def lookup(self, ids):
        """ids [n] -> (rows [n, dim], found [n] bool). Jit-safe: static
        shapes, no host sync; missing ids read zeros."""
        import jax.numpy as jnp
        ids = jnp.asarray(ids, jnp.int64).reshape(-1)
        slots = self._slots(ids)                       # [n, P]
        slot_keys = self.keys[slots]                   # [n, P]
        hit = slot_keys == ids[:, None]
        found = hit.any(axis=1)
        # first hit slot (or slot 0 — masked out below)
        idx = jnp.argmax(hit, axis=1)
        sel = jnp.take_along_axis(slots, idx[:, None], axis=1)[:, 0]
        rows = self.values[sel] * found[:, None].astype(self.values.dtype)
        return rows, found

    def insert(self, ids, rows, best_effort=False):
        """Functional batch insert (linear probing; existing keys are
        overwritten). A row whose probe window is exhausted either
        raises (default — size the capacity >= ~2x the working set) or,
        with ``best_effort=True``, is skipped: the caller gets the
        per-row placed mask back and decides where unplaced rows live
        (the tiered cache demotes them to host RAM — a CACHE must never
        hard-fail because 16 consecutive slots happened to cluster)."""
        import jax
        import jax.numpy as jnp
        ids = jnp.asarray(ids, jnp.int64).reshape(-1)
        rows = jnp.asarray(rows, self.values.dtype).reshape(
            ids.shape[0], self.dim)
        slots = self._slots(ids)
        placed0 = jnp.zeros((ids.shape[0],), bool)

        def body(i, carry):
            keys, values, placed_vec = carry
            cand = slots[i]
            kcand = keys[cand]
            match = kcand == ids[i]
            usable = (kcand == _EMPTY) | match
            # prefer the MATCHING slot over an earlier empty one: after a
            # remove() opened a hole in this id's probe chain, landing in
            # the hole would leave a stale duplicate further down the
            # window that could resurface after the fresh copy is evicted
            j = jnp.where(match.any(), jnp.argmax(match), jnp.argmax(usable))
            slot = cand[j]
            placed = usable.any()
            keys = keys.at[slot].set(jnp.where(placed, ids[i], keys[slot]))
            values = values.at[slot].set(
                jnp.where(placed, rows[i], values[slot]))
            return keys, values, placed_vec.at[i].set(placed)

        keys, values, placed_vec = jax.lax.fori_loop(
            0, ids.shape[0], body, (self.keys, self.values, placed0))
        placed_np = np.asarray(placed_vec)
        if not best_effort and not placed_np.all():
            raise RuntimeError(
                f"DeviceHashTable over capacity ({self.capacity} slots, "
                f"{self.max_probes} probes) — grow it or evict")
        self.keys, self.values = keys, values
        self._count = int(np.sum(np.asarray(keys) != _EMPTY))
        return placed_np if best_effort else self

    def remove(self, ids):
        """Vectorized batch remove: present ids' slots flip back to
        EMPTY (values left in place — unreachable once the key is gone,
        because lookup masks by `found`). Absent ids are ignored."""
        import jax.numpy as jnp
        ids = jnp.asarray(ids, jnp.int64).reshape(-1)
        if ids.shape[0] == 0:
            return self
        slots = self._slots(ids)
        hit = self.keys[slots] == ids[:, None]
        found = np.asarray(hit.any(axis=1))
        idx = jnp.argmax(hit, axis=1)
        sel = np.asarray(jnp.take_along_axis(slots, idx[:, None],
                                             axis=1)[:, 0])
        # scatter ONLY the found rows' slots: an absent id's bogus slot-0
        # candidate may alias a present id's slot, and a duplicate-index
        # scatter writing {EMPTY, old-key} to one slot resolves in
        # unspecified order — the removed key could resurrect
        if found.any():
            self.keys = self.keys.at[jnp.asarray(sel[found])].set(_EMPTY)
            # incremental count (unique slots: robust to duplicate ids)
            # instead of re-scanning the whole keys array to host on
            # every LRU-eviction batch
            self._count -= len(np.unique(sel[found]))
        return self

    def __len__(self):
        return self._count


class HeterPSCache:
    """Tiered read-through device cache over a PSClient sparse table.

    `capacity` bounds the DEVICE tier's resident rows (None -> the
    PADDLE_PS_HETER_CACHE_ROWS flag); `host_rows` bounds the host tier
    (None -> PADDLE_PS_HETER_HOST_ROWS, 0 disables it). All state is
    serialized under one reentrant lock, so a background prefetch pull
    and the trainer's push cannot interleave a stale row into a tier.
    """

    def __init__(self, client, table, dim, capacity=None, max_probes=16,
                 host_rows=None):
        self.client = client
        self.table = table
        self.dim = int(dim)
        self._bound = int(_flag("PADDLE_PS_HETER_CACHE_ROWS")
                          if capacity is None else capacity)
        self._host_bound = int(_flag("PADDLE_PS_HETER_HOST_ROWS")
                               if host_rows is None else host_rows)
        self._max_probes = int(max_probes)
        # device slots ~2x the row bound: linear probing needs headroom
        self.dev = DeviceHashTable(max(2 * self._bound, 64), dim,
                                   max_probes)
        self._lru: OrderedDict[int, bool] = OrderedDict()   # device ids
        self._host: OrderedDict[int, np.ndarray] = OrderedDict()
        self._lock = threading.RLock()
        self._invalidate_pending = False
        self._valid_epoch = self._epoch()
        self.hits = 0
        self.misses = 0
        # membership-change coherence: any shard-map adoption on the
        # client (promotion, eviction, stale redirect) nukes both tiers
        if hasattr(client, "add_map_listener"):
            client.add_map_listener(self._on_map_change)

    # ------------------------------------------------------------- helpers
    def _epoch(self):
        m = getattr(self.client, "shard_map", None)
        return getattr(m, "epoch", 0)

    def _on_map_change(self, _new_map):
        # DEFERRED, not inline: the adoption may fire on a fan-out
        # worker that this cache's in-flight pull is itself waiting on —
        # taking the cache lock here would deadlock. Serving only ever
        # happens through pull(), and pull() applies the pending
        # invalidation before reading a single row, so no pre-change hit
        # can be served after the membership change.
        self._invalidate_pending = True

    def _revalidate(self):
        """Caller holds self._lock. Two triggers, one clear: the
        listener's pending flag, AND a synchronous epoch comparison —
        the listener fires OUTSIDE the client's map lock, so another
        thread's adoption can complete (map swapped) a beat before the
        flag lands; reading the epoch here cannot lag the swap, so an
        adoption that happened-before this call always invalidates
        before a single row is read."""
        e = self._epoch()
        if self._invalidate_pending or e != self._valid_epoch:
            self._invalidate_pending = False
            self._valid_epoch = e
            self._clear_tiers()
            _monitor.stat_add("ps.heter.invalidations")

    def __len__(self):
        with self._lock:
            return len(self._lru)

    @property
    def host_len(self):
        with self._lock:
            return len(self._host)

    def _host_put(self, i, row):
        """Caller holds self._lock; bounded host-tier upsert."""
        if self._host_bound <= 0:
            return
        self._host[int(i)] = np.asarray(row, np.float32).copy()
        self._host.move_to_end(int(i))
        while len(self._host) > self._host_bound:
            self._host.popitem(last=False)

    def _insert_device(self, ids, rows):
        """Caller holds self._lock. Best-effort device insert: rows
        whose probe window is exhausted demote to the host tier instead
        of failing the pull (`ps.heter.probe_drops`). Returns the ids
        that are actually device-resident."""
        placed = self.dev.insert(ids, rows, best_effort=True)
        if not placed.all():
            _monitor.stat_add("ps.heter.probe_drops",
                              int((~placed).sum()))
            for k in np.nonzero(~placed)[0]:
                self._host_put(ids[k], rows[k])
        return ids[placed]

    def _touch(self, ids):
        """Mark device-resident ids as most-recently-used and evict past
        the bound (device -> host tier demotion)."""
        for i in ids:
            i = int(i)
            self._lru[i] = True
            self._lru.move_to_end(i)
        n_evict = len(self._lru) - self._bound
        if n_evict <= 0:
            return
        victims = [self._lru.popitem(last=False)[0] for _ in range(n_evict)]
        varr = np.asarray(victims, np.int64)
        if self._host_bound > 0:
            rows, found = self.dev.lookup(varr)
            rows = np.asarray(rows, np.float32)
            found = np.asarray(found)
            for k, i in enumerate(victims):
                if found[k]:
                    self._host_put(i, rows[k])
        self.dev.remove(varr)
        _monitor.stat_add("ps.heter.evictions", n_evict)

    # ---------------------------------------------------------------- pull
    def pull(self, ids):
        """ids any-shape ints -> rows [n_unique, dim] (device), index
        mapping like SparseEmbedding.pull. Misses fetch host tier first,
        then the sharded PS (one batched deduped fan-out), and populate
        the device table."""
        import jax.numpy as jnp
        ids_np = np.asarray(ids, np.int64).reshape(-1)
        uniq, inv = np.unique(ids_np, return_inverse=True)
        with self._lock:
            self._revalidate()
            epoch0 = self._epoch()
            rows, found = self.dev.lookup(uniq)
            found_np = np.asarray(found)
            miss = uniq[~found_np]
            n_hits = int(found_np.sum())
            self.hits += n_hits
            # cache efficiency next to the transport's ps.rpc.* flakiness
            # counters: a miss storm after a PS reconnect shows up here
            _monitor.stat_add("ps.heter.hits", n_hits)
            if len(miss):
                fetched = np.empty((len(miss), self.dim), np.float32)
                host_mask = np.zeros(len(miss), bool)
                for k, i in enumerate(miss):
                    row = self._host.pop(int(i), None)
                    if row is not None:
                        fetched[k] = row
                        host_mask[k] = True
                n_host = int(host_mask.sum())
                n_ps = len(miss) - n_host
                self.misses += n_ps
                _monitor.stat_add("ps.heter.host_hits", n_host)
                _monitor.stat_add("ps.heter.misses", n_ps)
                if n_ps:
                    fetched[~host_mask] = np.asarray(
                        self.client.pull_sparse(self.table,
                                                miss[~host_mask]),
                        np.float32)
                if self._epoch() == epoch0:
                    resident = self._insert_device(miss, fetched)
                    self._touch(np.concatenate([uniq[found_np],
                                                resident]))
                # else: the shard map moved UNDER this pull (a failover
                # resolved it) — serve the rows, but don't let a
                # pre-change fetch populate the post-change cache
                rows = jnp.asarray(rows).at[jnp.asarray(~found_np)].set(
                    jnp.asarray(fetched, self.dev.values.dtype))
            else:
                self._touch(uniq)
        return rows, inv.reshape(np.shape(ids))

    # ---------------------------------------------------------------- push
    def push_grad(self, ids, grads):
        """Push grads to the PS (authoritative update), then refresh the
        cached copies with the server's post-update rows."""
        ids_np = np.asarray(ids, np.int64).reshape(-1)
        if ids_np.size == 0:
            return              # no-op, same contract as the client layer
        # duplicate-id merging (MergeAdd) is the CLIENT's job — one
        # implementation of the bitwise-sensitive merge, not three; the
        # cache only needs the unique set for its refresh pull and tiers
        uniq = np.unique(ids_np)
        with self._lock:
            self._revalidate()
            epoch0 = self._epoch()
            self.client.push_sparse_grad(self.table, ids_np, grads)
            fresh = np.asarray(self.client.pull_sparse(self.table, uniq),
                               np.float32)
            # pushed ids leave the host tier: the device copy is now the
            # freshest cached one, and a later demotion re-parks it
            for i in uniq:
                self._host.pop(int(i), None)
            if self._epoch() == epoch0:
                self._touch(self._insert_device(uniq, fresh))

    # --------------------------------------------------------------- admin
    def _clear_tiers(self):
        """Caller holds self._lock."""
        self.dev = DeviceHashTable(self.dev.capacity, self.dev.dim,
                                   self.dev.max_probes)
        self._lru.clear()
        self._host.clear()

    def invalidate(self):
        """Drop BOTH tiers (membership change / external writer). Every
        next pull re-reads through the sharded PS."""
        with self._lock:
            self._invalidate_pending = False
            self._valid_epoch = self._epoch()
            self._clear_tiers()
        _monitor.stat_add("ps.heter.invalidations")
        return self
