"""HeterPS — accelerator-resident embedding cache over the host PS.

Reference tier: framework/fleet/heter_ps/hashtable.h + heter_comm.h (a
GPU-resident concurrent hashtable caching hot embedding rows, backed by
the CPU parameter server). TPU redesign: the table is a pair of jnp
arrays (open-addressing keys [cap] i64 + values [cap, dim]) living in
HBM, with LOOKUP as a fully vectorized fixed-probe gather that jits into
the training step, and INSERT as a lax.fori_loop of dynamic updates (runs
once per batch on the miss set, off the hot path). No device hashtable
kernels to hand-write — XLA lowers both to gathers/scatters.

Semantics: read-through cache with push-through writes —
  rows = cache.pull(ids)        # device hits + host PS misses
  ...                           # grads computed on device
  cache.push_grad(ids, grads)   # goes to the PS (server accessor owns
                                # the update rule), cached copies refresh
so the server stays authoritative (same division of labor as the
reference: hashtable.h caches, the DownpourPsClient owns optimizer state).
"""
from __future__ import annotations

import numpy as np

__all__ = ["DeviceHashTable", "HeterPSCache"]

_EMPTY = np.int64(-1)


def _mix(h):
    """splitmix64 finalizer — good avalanche for sequential ids."""
    import jax.numpy as jnp
    h = (h ^ (h >> 30)) * jnp.int64(-4658895280553007687)   # 0xbf58476d1ce4e5b9
    h = (h ^ (h >> 27)) * jnp.int64(-7723592293110705685)   # 0x94d049bb133111eb
    return h ^ (h >> 31)


class DeviceHashTable:
    """Fixed-capacity open-addressing (linear probe) id -> row table as a
    functional pytree of device arrays."""

    def __init__(self, capacity, dim, max_probes=16, dtype="float32"):
        import jax.numpy as jnp
        self.capacity = int(capacity)
        self.dim = int(dim)
        self.max_probes = int(max_probes)
        self.keys = jnp.full((self.capacity,), _EMPTY, jnp.int64)
        self.values = jnp.zeros((self.capacity, self.dim), dtype)
        self._count = 0

    # ---- pure kernels ----------------------------------------------------
    def _slots(self, ids):
        """[n, max_probes] candidate slots per query id."""
        import jax.numpy as jnp
        h = _mix(ids.astype(jnp.int64)) % self.capacity
        probe = jnp.arange(self.max_probes, dtype=jnp.int64)
        return (h[:, None] + probe[None, :]) % self.capacity

    def lookup(self, ids):
        """ids [n] -> (rows [n, dim], found [n] bool). Jit-safe: static
        shapes, no host sync; missing ids read zeros."""
        import jax.numpy as jnp
        ids = jnp.asarray(ids, jnp.int64).reshape(-1)
        slots = self._slots(ids)                       # [n, P]
        slot_keys = self.keys[slots]                   # [n, P]
        hit = slot_keys == ids[:, None]
        found = hit.any(axis=1)
        # first hit slot (or slot 0 — masked out below)
        idx = jnp.argmax(hit, axis=1)
        sel = jnp.take_along_axis(slots, idx[:, None], axis=1)[:, 0]
        rows = self.values[sel] * found[:, None].astype(self.values.dtype)
        return rows, found

    def insert(self, ids, rows):
        """Functional batch insert (linear probing; existing keys are
        overwritten). Raises if the probe window is exhausted — size the
        capacity >= ~2x the working set."""
        import jax
        import jax.numpy as jnp
        ids = jnp.asarray(ids, jnp.int64).reshape(-1)
        rows = jnp.asarray(rows, self.values.dtype).reshape(
            ids.shape[0], self.dim)
        slots = self._slots(ids)

        def body(i, carry):
            keys, values, ok = carry
            cand = slots[i]
            kcand = keys[cand]
            usable = (kcand == _EMPTY) | (kcand == ids[i])
            j = jnp.argmax(usable)
            slot = cand[j]
            placed = usable.any()
            keys = keys.at[slot].set(jnp.where(placed, ids[i], keys[slot]))
            values = values.at[slot].set(
                jnp.where(placed, rows[i], values[slot]))
            return keys, values, ok & placed

        keys, values, ok = jax.lax.fori_loop(
            0, ids.shape[0], body,
            (self.keys, self.values, jnp.asarray(True)))
        if not bool(ok):
            raise RuntimeError(
                f"DeviceHashTable over capacity ({self.capacity} slots, "
                f"{self.max_probes} probes) — grow it or evict")
        self.keys, self.values = keys, values
        self._count = int(np.sum(np.asarray(keys) != _EMPTY))
        return self

    def __len__(self):
        return self._count


class HeterPSCache:
    """Read-through device cache over a PSClient sparse table."""

    def __init__(self, client, table, dim, capacity=1 << 16,
                 max_probes=16):
        self.client = client
        self.table = table
        self.dev = DeviceHashTable(capacity, dim, max_probes)
        self.hits = 0
        self.misses = 0

    def pull(self, ids):
        """ids any-shape ints -> rows [n_unique, dim] (device), index
        mapping like SparseEmbedding.pull. Misses fetch from the host PS
        and populate the device table."""
        import jax.numpy as jnp
        from ...core import monitor
        ids_np = np.asarray(ids, np.int64).reshape(-1)
        uniq, inv = np.unique(ids_np, return_inverse=True)
        rows, found = self.dev.lookup(uniq)
        found_np = np.asarray(found)
        miss = uniq[~found_np]
        self.hits += int(found_np.sum())
        self.misses += len(miss)
        # cache efficiency next to the transport's ps.rpc.* flakiness
        # counters: a miss storm after a PS reconnect shows up here
        monitor.stat_add("ps.heter.hits", int(found_np.sum()))
        monitor.stat_add("ps.heter.misses", len(miss))
        if len(miss):
            fetched = np.asarray(self.client.pull_sparse(self.table, miss),
                                 np.float32)
            self.dev.insert(miss, fetched)
            rows = jnp.asarray(rows).at[jnp.asarray(~found_np)].set(
                jnp.asarray(fetched, self.dev.values.dtype))
        return rows, inv.reshape(np.shape(ids))

    def push_grad(self, ids, grads):
        """Push grads to the PS (authoritative update), then refresh the
        cached copies with the server's post-update rows."""
        ids_np = np.asarray(ids, np.int64).reshape(-1)
        uniq, inv = np.unique(ids_np, return_inverse=True)
        g = np.asarray(grads, np.float32).reshape(len(uniq), -1) \
            if len(ids_np) == len(uniq) else None
        if g is None:
            # merge duplicate-id grads before the wire (MergeAdd)
            flat = np.asarray(grads, np.float32).reshape(len(ids_np), -1)
            g = np.zeros((len(uniq), flat.shape[1]), np.float32)
            np.add.at(g, inv, flat)
        self.client.push_sparse_grad(self.table, uniq, g)
        fresh = np.asarray(self.client.pull_sparse(self.table, uniq),
                           np.float32)
        self.dev.insert(uniq, fresh)

    def invalidate(self):
        self.dev = DeviceHashTable(self.dev.capacity, self.dev.dim,
                                   self.dev.max_probes)
        return self
