"""Async embedding prefetch over the sharded PS — bitwise-safe overlap.

Every sparse pull used to be synchronous on the training hot path: the
step stalls for one full PS round trip per batch (worse under a slow or
failing-over shard). This module overlaps the NEXT batch's sparse pulls
with the CURRENT dense step, the way the reference's HeterPS pipeline
prefetches embedding rows ahead of the GPU pass — without giving up the
repo's robustness bar: results are provably BITWISE-equal to the
synchronous path, chaos included.

Machinery:

- pulls run on a single background thread (issue order == program
  order), each one dispatched through a PR 9 `InflightDriver`
  (static/pipeline_runner.py), so the prefetch stage inherits the
  bounded in-flight window (`PADDLE_PS_PREFETCH_DEPTH`), lazy
  `FetchHandle` materialization, `PipelineStepError` naming the failed
  prefetch step (with a flight-recorder dump), per-step dispatch/retire
  spans, and elastic liveness pulses — a prefetching trainer renders in
  obs_report exactly like a pipelined one.

- **conflict fix-up is what makes the overlap bitwise-safe.** A
  prefetched pull may race the current step's `push_grad`: the rows it
  fetched for ids the push touched are stale the moment the push lands.
  The prefetcher keeps a per-id version counter, bumped on every push
  routed through it; `get()` compares each id's version against the
  snapshot taken at `prefetch()` time and synchronously RE-PULLS just
  the conflicted ids (tiny set in practice — consecutive batches rarely
  overlap much), splicing the fresh rows in. Unconflicted ids were
  untouched by any push between snapshot and materialization, so their
  prefetched value IS the synchronous value; conflicted ids are re-read
  after the push, which is exactly when the synchronous path would have
  read them. Chaos, failover and cache invalidation ride underneath
  unchanged: the pull itself goes through the same PSClient /
  HeterPSCache stack as a synchronous call.

Contract: route pushes for the table through `push_grad` (or call
`note_pushed(ids)` after an out-of-band push) — an invisible writer
defeats conflict tracking exactly as it would defeat any cache.

Overlap accounting (`stats()` / `overlap_ratio`): per-pull wall time is
measured on the background thread, exposed wait at `get()` on the
caller — `1 - wait/pull` is the fraction of PS latency the dense step
absorbed (`bench.py BENCH_MODE=sparse` reports it).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...core import monitor as _monitor
from ...core.flags import flag as _flag

__all__ = ["EmbeddingPrefetcher"]


class _PendingPull:
    """Future-backed fetch leaf: quacks like a device array for the
    InflightDriver (`block_until_ready` re-raises the pull's error;
    `__array__` materializes the rows), so the driver's retire /
    failure-ordering machinery applies to host RPCs unchanged."""

    __slots__ = ("_future",)

    def __init__(self, future):
        self._future = future

    def block_until_ready(self):
        self._future.result()
        return self

    def rows(self):
        return self._future.result()

    def __array__(self, dtype=None, copy=None):
        arr = self._future.result()
        return arr.astype(dtype) if dtype is not None else arr


class _Pending:
    __slots__ = ("ids", "versions", "handle", "pending")

    def __init__(self, ids, versions, handle, pending):
        self.ids = ids
        self.versions = versions
        self.handle = handle
        self.pending = pending


class EmbeddingPrefetcher:
    """Prefetch stage over a `PSClient` (pass `table=`) or a
    `HeterPSCache` (table implied; pulls ride the tiered cache and its
    membership-change invalidation).

        pf = EmbeddingPrefetcher(cache)            # or (client, table=..)
        pf.prefetch(ids_of_batch_0)
        for step in range(n):
            rows = pf.get(batch_ids(step))         # [len(ids), dim]
            pf.prefetch(batch_ids(step + 1))       # overlaps the rest
            grads = dense_step(rows)               # of this iteration
            pf.push_grad(batch_ids(step), grads)
        pf.close()

    `get()` on ids that were never prefetched (cold start, resumed
    loop) degrades to a synchronous pull — same values, no overlap.
    """

    def __init__(self, source, table=None, depth=None,
                 name="ps.embed/prefetch"):
        from ...static.pipeline_runner import InflightDriver
        self._source = source
        self._table = table
        is_cache = hasattr(source, "push_grad") and hasattr(source, "dev")
        if not is_cache and table is None:
            raise ValueError(
                "EmbeddingPrefetcher over a raw client needs table=")
        self._is_cache = is_cache
        self._depth = int(_flag("PADDLE_PS_PREFETCH_DEPTH")
                          if depth is None else depth)
        self._name = name
        self._driver = InflightDriver(name=name, max_inflight=self._depth)
        # ONE puller thread: pulls execute in submission order, so the
        # window drains oldest-first exactly like the training pipeline
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ps-embed-prefetch")
        self._queue: deque[_Pending] = deque()
        self._versions: dict[int, int] = {}
        self._vlock = threading.Lock()
        self._closed = False
        # overlap accounting
        self._n_prefetched = 0
        self._n_sync = 0
        self._conflict_rows = 0
        self._wait_s = 0.0
        self._pull_s = 0.0

    # ------------------------------------------------------------ plumbing
    def _pull_rows(self, ids):
        """Input-order [len(ids), dim] rows from the source."""
        if self._is_cache:
            rows, inv = self._source.pull(ids)
            return np.asarray(rows, np.float32)[
                np.asarray(inv).reshape(-1)]
        return np.asarray(self._source.pull_sparse(self._table, ids),
                          np.float32)

    def _timed_pull(self, ids):
        t0 = time.perf_counter()
        rows = self._pull_rows(ids)
        self._pull_s += time.perf_counter() - t0
        return rows

    # ------------------------------------------------------------- the API
    def prefetch(self, ids):
        """Queue an async pull of `ids` (any int shape; flattened). The
        bounded window applies backpressure: past
        PADDLE_PS_PREFETCH_DEPTH in-flight batches, this blocks on the
        oldest one."""
        if self._closed:
            raise RuntimeError("EmbeddingPrefetcher is closed")
        ids = np.asarray(ids, np.int64).reshape(-1).copy()
        entry = _Pending(ids, None, None, None)
        with self._vlock:
            # snapshot + window-open are ONE atomic step: a concurrent
            # note_pushed (Communicator thread) must either land in this
            # snapshot or see the queue non-empty and version-bump — a
            # gap between the two would let a push slip past both and
            # serve its pre-push rows
            entry.versions = {int(i): self._versions.get(int(i), 0)
                              for i in dict.fromkeys(int(x) for x in ids)}
            self._queue.append(entry)
        try:
            future = self._pool.submit(self._timed_pull, ids)
            entry.pending = _PendingPull(future)
            _, handles = self._driver.submit(
                lambda: (None, [entry.pending]), ids=int(ids.size))
            entry.handle = handles[0]
        except BaseException:
            with self._vlock:
                if entry in self._queue:
                    self._queue.remove(entry)
            raise
        self._n_prefetched += 1
        _monitor.stat_add("ps.embed.prefetches")
        return entry.handle

    def get(self, ids):
        """Rows for `ids`, bitwise-equal to a synchronous pull NOW.
        Consumes the oldest prefetched batch matching `ids`; queued
        batches the trainer skipped past are ABANDONED (FIFO: they will
        never be asked for again — leaving them would pin the window
        head and kill overlap for the rest of the run), and an empty /
        non-matching queue degrades to a synchronous pull. Raises
        PipelineStepError (naming the prefetch step) if the async pull
        died — the queue is then drained and the driver rebuilt, so the
        caller may retry synchronously and later prefetches start on a
        clean window."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._vlock:
            while self._queue and not np.array_equal(self._queue[0].ids,
                                                     ids):
                self._queue.popleft()
                _monitor.stat_add("ps.embed.abandoned")
            entry = self._queue[0] if self._queue else None
            if entry is None:
                self._versions.clear()         # no snapshots left
        if entry is None:
            self._n_sync += 1
            _monitor.stat_add("ps.embed.sync_pulls")
            return self._pull_rows(ids)
        t0 = time.perf_counter()
        try:
            entry.handle.block_until_ready()   # PipelineStepError here
            rows = entry.pending.rows()
        except BaseException:
            # the failure is SURFACED right here; every other queued
            # batch rides the same poisoned driver (InflightDriver
            # failures are sticky by design), so drain them and start a
            # fresh window — one transient pull error must not turn
            # every later prefetch into a dead handle
            with self._vlock:
                self._queue.clear()
                self._versions.clear()
            self._driver = type(self._driver)(name=self._name,
                                              max_inflight=self._depth)
            raise
        self._wait_s += time.perf_counter() - t0
        # conflict fix-up: ids pushed since the prefetch snapshot are
        # stale in `rows` — re-pull exactly those, synchronously. The
        # entry leaves the queue only WITH its stale check, atomically:
        # note_pushed must keep recording versions for as long as this
        # snapshot can still be compared, else a concurrent Communicator
        # push could slip between a pop and the check and its pre-push
        # rows would be served
        with self._vlock:
            stale = [i for i, v in entry.versions.items()
                     if self._versions.get(i, 0) != v]
            self._queue.popleft()              # window closes HERE
            if not self._queue:
                # steady-state bound: the canonical get -> prefetch ->
                # push loop empties the queue at every pop, so the
                # version table resets each step instead of growing
                # toward the vocab
                self._versions.clear()
            elif len(self._versions) > 64 + 8 * sum(
                    len(e.versions) for e in self._queue):
                # deep-window bound: drop keys no live snapshot can
                # compare against (a future snapshot re-reads 0 and
                # bumps only grow, so no stale comparison can pass)
                live = set()
                for e in self._queue:
                    live.update(e.versions)
                self._versions = {i: v for i, v in self._versions.items()
                                  if i in live}
        if stale:
            fresh = self._pull_rows(np.asarray(stale, np.int64))
            lookup = {i: k for k, i in enumerate(stale)}
            sel = np.asarray([lookup.get(int(i), -1) for i in ids],
                             np.int64)
            mask = sel >= 0
            rows = rows.copy()
            rows[mask] = fresh[sel[mask]]
            self._conflict_rows += int(mask.sum())
            _monitor.stat_add("ps.embed.conflict_repulls", len(stale))
        return rows

    def push_grad(self, ids, grads):
        """Push through the underlying stack, then version-bump the ids
        so any in-flight prefetch that saw their pre-push value gets
        fixed up at get()."""
        if self._is_cache:
            self._source.push_grad(ids, grads)
        else:
            self._source.push_sparse_grad(self._table, ids, grads)
        self.note_pushed(ids)

    def note_pushed(self, ids):
        """Record an out-of-band push of `ids` (a Communicator batch, a
        peer worker you synchronize with, ...) for conflict tracking."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._vlock:
            if not self._queue:
                # no in-flight prefetch snapshot can reference any
                # version, so none needs recording — and the stale table
                # can go. This bounds _versions by the ids pushed inside
                # one prefetch window, not by the (pod-scale) vocab.
                self._versions.clear()
                return
            for i in ids:
                i = int(i)
                self._versions[i] = self._versions.get(i, 0) + 1

    # ------------------------------------------------------------- admin
    def sync(self):
        """Materialize every in-flight prefetch (PipelineStepError on
        the first failure, naming its step)."""
        self._driver.sync()

    def stats(self):
        return {"prefetched": self._n_prefetched,
                "sync_pulls": self._n_sync,
                "conflict_rows": self._conflict_rows,
                "wait_s": self._wait_s,
                "pull_s": self._pull_s,
                "overlap_ratio": self.overlap_ratio}

    @property
    def overlap_ratio(self):
        """Fraction of background pull time the caller did NOT wait for
        (1.0 = pulls fully hidden behind the dense step)."""
        if self._pull_s <= 0.0:
            return 0.0
        return max(0.0, 1.0 - self._wait_s / self._pull_s)

    def close(self):
        if self._closed:
            return
        self._closed = True
        try:
            self.sync()
        finally:
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
