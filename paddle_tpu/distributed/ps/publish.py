"""Versioned embedding snapshot publish — the train→serve half of the
online-learning loop (docs/online_learning.md).

The publish mechanism IS the replica tier's rejoin machinery reused
read-only: `replica_fetch` returns, per table, a gate-consistent
(state, seq) pair — every delta the primary acked is in the state, and
`seq` is the exact mutation cursor of the cut. The publisher walks the
shard map, fetches each shard's primary snapshot (riding the client's
failover re-route, so a mid-publish primary kill lands on the promoted
backup), filters the state to the rows the shard actually OWNS
(`_filter_sparse_state` — a primary's table also carries rows it backs
for others), and stamps the union with a monotonically increasing
version number.

The per-shard `seq` cursor is the publish-side cutoff: a shard whose
cursor has not moved since the last publish contributes its cached rows
without re-serializing the table — incremental publishes cost only the
shards that trained.

On every publish the attached `HeterPSCache` (if any) is invalidated —
the same protocol that covers membership changes covers a snapshot
becoming the served truth, so no cached pre-publish row can shadow it.

Unreplicated clusters (no `enable_replication`) degrade to
`table_state` per shard primary: same rows, no consistency gate and no
cutoff cursor (every publish refetches everything).
"""
from __future__ import annotations

import numpy as np

from .replica import _filter_sparse_state

__all__ = ["EmbeddingSnapshotPublisher"]


class EmbeddingSnapshotPublisher:
    """Publish versioned embedding snapshots out of a sharded PS table.

        pub = EmbeddingSnapshotPublisher(client, table="emb")
        version, rows = pub.publish()        # rows: {id: float32[dim]}
        serve_loop.publish_weights(version, {"wte.weight":
            pub.materialize(current_wte)})   # dense [vocab, dim]

    `cache=` takes the serving side's HeterPSCache; it is invalidated
    on every publish.
    """

    def __init__(self, client, table, cache=None, start_version=0):
        self.client = client
        self.table = str(table)
        self.cache = cache
        self.version = int(start_version)
        self._seqs = {}        # shard -> seq cursor of last fetch
        self._shard_rows = {}  # shard -> {id: row} as of that cursor
        self._rows = {}        # union of the last publish

    def publish(self):
        """Fetch every shard's consistent snapshot and cut a new
        version. Returns (version, {id: float32[dim] row}) — only ids
        the table has materialized appear. Raises if any shard is
        unreachable through failover (a half-fetched snapshot is never
        published)."""
        from ...core import monitor as _monitor
        from ...core import trace as _trace
        m = self.client._map
        rows = {}
        refetched = 0
        with _trace.span("ps/publish", table=self.table,
                         shards=m.n_shards):
            for shard in range(m.n_shards):
                entry, seq = self._fetch_shard(shard)
                if seq is not None and self._seqs.get(shard) == seq:
                    # cutoff cursor: nothing applied on that server
                    # since the last publish — reuse the cached rows
                    rows.update(self._shard_rows[shard])
                    continue
                st = _filter_sparse_state(entry, shard, m.n_shards)
                ids = np.asarray(st["ids"], np.int64).reshape(-1)
                vals = np.asarray(st["values"], np.float32)
                if ids.size:
                    vals = vals.reshape(ids.size, -1)
                shard_rows = {int(i): vals[k].copy()
                              for k, i in enumerate(ids)}
                self._shard_rows[shard] = shard_rows
                if seq is not None:
                    self._seqs[shard] = seq
                refetched += 1
                rows.update(shard_rows)
            self.version += 1
            self._rows = rows
            if self.cache is not None:
                self.cache.invalidate()
        _monitor.stat_add("ps.publish.publishes")
        _monitor.stat_add("ps.publish.shards_refetched", refetched)
        _monitor.stat_set_many({"ps.publish.version": self.version,
                                "ps.publish.rows": len(rows)})
        return self.version, rows

    def _fetch_shard(self, shard):
        """(table state, seq cursor) of one shard's primary. Rides
        `_routed` so a dead primary fails over to the promoted backup
        mid-publish; falls back to the ungated `table_state` (seq=None)
        when replication is off."""
        try:
            snap = self.client._routed(shard, "replica_fetch")
        except RuntimeError as e:
            if "replication" not in str(e):
                raise
            st = self.client._routed(shard, "table_state",
                                     table=self.table)
            return st, None
        entry = snap.get(self.table)
        if entry is None:
            raise KeyError(f"table {self.table!r} is not replicated on "
                           f"shard {shard}'s primary (got "
                           f"{sorted(snap)})")
        return entry["state"], int(entry["seq"])

    def materialize(self, base):
        """Dense [vocab, dim] matrix of the LAST published version:
        a copy of `base` (the currently served weights) with every
        published row overwritten — rows serve traffic never trained
        keep serving their current values."""
        out = np.array(base, np.float32)
        for i, row in self._rows.items():
            if 0 <= i < out.shape[0]:
                out[i] = row
        return out
