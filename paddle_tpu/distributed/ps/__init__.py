"""paddle.distributed.ps — parameter-server training.

TPU-native re-design of the reference PS stack (SURVEY.md §2.1 N20-N22,
hard part 5): N20 operators/distributed/ (RPC ops, Communicator,
parameter_send row splitting, large_scale_kv), N21
paddle/fluid/distributed/ (PSClient/PSServer + table layer), N22
framework/fleet/fleet_wrapper.h (sync/async sparse/dense pull-push).

The design split:
- servers (table.py / server.py) are host-only numpy KV processes — no
  JAX, no TPU; update rules run server-side on push (accessors).
- workers keep ALL dense math on the TPU as usual; only the unbounded
  sparse vocab goes through the PS. `SparseEmbedding` is the seam: pull
  the rows a batch touches into a dense [n, dim] block (MXU-friendly),
  run the jitted step, push back just those rows' grads — optionally
  through the async `Communicator`.
- `fleet.init(role_maker, is_collective=False)` + `strategy.a_sync`
  selects this mode (reference fleet/runtime/the_one_ps.py).
"""
from __future__ import annotations

import numpy as np

from .client import Communicator, PSClient
from .embedding import EmbeddingPrefetcher
from .heter import DeviceHashTable, HeterPSCache
from .publish import EmbeddingSnapshotPublisher
from .replica import ReplicaManager
from .rpc import AuthError, ConnectRefused, DeadlineExceeded, FrameError
from .server import PSServer
from .shard_map import ShardMap, ShardMapStale
from .table import (BarrierTable, DenseTable, GeoSparseTable, SparseTable,
                    make_table)

__all__ = ["PSServer", "PSClient", "Communicator", "DenseTable",
           "SparseTable", "GeoSparseTable", "BarrierTable", "make_table",
           "SparseEmbedding", "DeviceHashTable", "HeterPSCache",
           "EmbeddingPrefetcher", "EmbeddingSnapshotPublisher",
           "DeadlineExceeded", "FrameError", "AuthError", "ConnectRefused",
           "ShardMap", "ShardMapStale", "ReplicaManager"]


class SparseEmbedding:
    """PS-backed embedding for vocabularies too large for device HBM.

    Reference analog: `lookup_table` with remote prefetch
    (operators/distributed/parameter_prefetch.cc) + sparse push of
    SelectedRows grads (fleet_wrapper.h push_sparse). Here the lookup is
    an explicit pull/push pair around the jitted step, keeping the step
    itself static-shaped and host-callback-free:

        emb = ps.SparseEmbedding(client, table="w2v", dim=64)
        rows = emb.pull(ids)              # paddle Tensor [n_unique, dim]
        ...                               # use rows inside fwd/bwd
        loss.backward()
        emb.push_grad(rows)               # sends rows.grad for those ids

    Duplicate ids in a batch are uniqued on pull; gather back to batch
    positions happens on-device via the returned `index` (so the TPU does
    the [n_unique, dim] -> [batch, dim] gather, and the reverse scatter
    lands in rows.grad through the normal tape).
    """

    def __init__(self, client, table: str, dim: int,
                 communicator: Communicator | None = None):
        self.client = client
        self.table = table
        self.dim = int(dim)
        self.communicator = communicator
        self._last_ids = None

    def pull(self, ids):
        """ids: int array-like, any shape -> (rows Tensor [n_unique, dim]
        with stop_gradient=False, index int Tensor of ids.shape mapping
        each position to its row)."""
        from ... import core
        ids_np = np.asarray(getattr(ids, "numpy", lambda: ids)(),
                            dtype=np.int64)
        uniq, inv = np.unique(ids_np.reshape(-1), return_inverse=True)
        rows_np = self.client.pull_sparse(self.table, uniq)
        self._last_ids = uniq
        rows = core.Tensor(rows_np, stop_gradient=False)
        index = core.Tensor(inv.reshape(ids_np.shape).astype(np.int64))
        return rows, index

    def push_grad(self, rows):
        """Push rows.grad (from the last backward) for the pulled ids."""
        if self._last_ids is None:
            raise RuntimeError("push_grad before pull")
        if rows.grad is None:
            raise RuntimeError(
                "rows has no grad — call loss.backward() first (and use "
                "the rows tensor inside the loss computation)")
        g = np.asarray(rows.grad.numpy() if hasattr(rows.grad, "numpy")
                       else rows.grad, np.float32)
        if self.communicator is not None:
            self.communicator.push_sparse(self.table, self._last_ids, g)
        else:
            self.client.push_sparse_grad(self.table, self._last_ids, g)
        self._last_ids = None
