"""PSClient + Communicator — the worker side of the PS stack.

Analogs: reference N21 PSClient (distributed/service/ps_client.h:
pull_dense/push_dense/pull_sparse/push_sparse futures), N20 row splitting
across servers (operators/distributed/parameter_send.cc: rows hashed to
sections, one RPC per server) and the background-send Communicator
(operators/distributed/communicator.cc: AsyncCommunicator merges grads in
queues and flushes every send_wait_times; GeoCommunicator pushes deltas).

Sharding is owned by a cached, versioned `ShardMap` (shard_map.py):
sparse ids hash onto shards with `id % n_shards`, dense AND barrier
tables with `crc32(name) % n_shards`, and every data call routes to the
shard's PRIMARY, stamped with the map's epoch. Against an unreplicated
cluster the default map makes this bit-identical to the legacy
`id % n_servers` rule. Against a replicated cluster the client fails
over: a `ShardMapStale` redirect installs the server's newer map and
re-routes; a dead endpoint (ConnectRefused / exhausted transport)
triggers a map refresh from the surviving servers and a bounded
re-route loop (`PADDLE_PS_FAILOVER_RETRIES` x
`PADDLE_PS_FAILOVER_BACKOFF_S`) that rides out a heartbeat-driven
promotion. Replay ids for mutating calls are minted by the CLIENT (not
the connection), so the retry that lands on the promoted backup dedupes
against the forward the dead primary already delivered — exactly-once
holds across failover, not just across resends.
"""
from __future__ import annotations

import queue
import threading
import time
import uuid
import weakref
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...core import monitor as _monitor
from ...core import trace as _trace
from ...core.flags import flag as _flag
from .rpc import ConnectRefused, Connection
from .shard_map import ShardMap, ShardMapStale

__all__ = ["PSClient", "Communicator"]


class PSClient:
    """Every fan-out routes through the retrying `rpc.Connection`, and
    mutating calls (push_*/set_dense/barrier) are stamped for idempotent
    replay — a retried push after a lost response applies exactly once.
    `**rpc_opts` (timeout, max_retries, backoff_base, ...) override the
    PADDLE_PS_* flag defaults per client."""

    # Communicator probes this before threading request_keys through
    # push_* (test doubles with bare push signatures stay valid)
    supports_request_keys = True

    def __init__(self, server_endpoints, shard_map=None, client_id=None,
                 **rpc_opts):
        if isinstance(server_endpoints, str):
            server_endpoints = server_endpoints.split(",")
        self.endpoints = list(server_endpoints)
        self._rpc_opts = dict(rpc_opts)
        # one client is shared between the trainer thread and the
        # Communicator send thread; every _conns read-modify (and any
        # iteration) holds this lock — Connection.call serializes itself
        self._conns_lock = threading.Lock()
        self._conns: dict[str, Connection | None] = {}
        errors = []
        for ep in self.endpoints:
            try:
                self._conns[ep] = Connection(ep, **rpc_opts)
            except (ConnectionError, OSError) as e:
                # a dead member of a replicated cluster must not keep a
                # fresh worker from joining; the map routes around it.
                # All-dead still fails loudly below.
                self._conns[ep] = None
                errors.append(e)
        if errors and len(errors) == len(self.endpoints):
            raise errors[0]
        # client-owned replay-id namespace: stable across failover
        # re-routes of one logical call (connection ids are not)
        self._client_id = client_id or uuid.uuid4().hex
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._map_lock = threading.Lock()
        # shard-map change listeners (HeterPSCache invalidation rides
        # these) + the lazy per-shard fan-out pool for batched lookups
        self._listeners: list = []
        self._fanout_pool: ThreadPoolExecutor | None = None
        self._fanout_lock = threading.Lock()
        if shard_map is not None:
            self._map = shard_map if isinstance(shard_map, ShardMap) \
                else ShardMap.from_dict(shard_map)
        else:
            self._map = ShardMap.default(self.endpoints)
            self.refresh_shard_map()

    # ----------------------------------------------------------- shard map
    @property
    def shard_map(self) -> ShardMap:
        return self._map

    @property
    def n_servers(self):
        return len(self.endpoints)

    def _adopt(self, map_dict):
        """Install a map if it is newer; newest epoch always wins."""
        if not map_dict:
            return False
        new = ShardMap.from_dict(map_dict)
        with self._map_lock:
            if new.epoch <= self._map.epoch:
                return False
            self._map = new
        if new.epoch > 0 or any(new.backups(s)
                                for s in range(new.n_shards)):
            self._enable_fail_fast()
        # a membership change invalidates every derived caching layer:
        # listeners fire OUTSIDE the map lock (an invalidation may pull)
        for ref in list(self._listeners):
            fn = ref()
            if fn is None:
                try:       # owner died: the weak registration self-prunes
                    self._listeners.remove(ref)
                except ValueError:
                    pass
                continue
            try:
                fn(new)
            except Exception:  # noqa: BLE001 — listeners must not block
                pass           # adoption (routing correctness comes first)
        return True

    def add_map_listener(self, fn):
        """Register fn(new_map), called after every shard-map adoption
        (stale redirect, failover refresh, epoch gossip). The sharded
        caching tier registers its invalidation here so a stale cached
        row can never survive a membership change. Bound methods are
        held WEAKLY — a discarded cache unregisters itself instead of
        being pinned (and fired) for the client's whole lifetime."""
        try:
            ref = weakref.WeakMethod(fn)
        except TypeError:
            # plain function/lambda: no owner to outlive, pin it
            ref = (lambda f=fn: f)
        self._listeners.append(ref)
        return fn

    def _enable_fail_fast(self):
        # with backups in the map a refused dial means "fail over NOW",
        # not "wait out the connect window"
        with self._conns_lock:
            conns = list(self._conns.values())
        for c in conns:
            if c is not None:
                c.fail_fast_refused = True

    def refresh_shard_map(self):
        """Ask every reachable server for its map; adopt the newest.
        Returns True if the map advanced. Endpoints that were dead at
        construction (conn is None) are skipped — re-dialing them here
        would stall every refresh by their connect window; the failover
        loop re-dials them when the map actually routes there."""
        advanced = False
        with self._conns_lock:
            live = [ep for ep, c in self._conns.items() if c is not None]
        for ep in live:
            try:
                md = self._conn(ep).call("get_shard_map", _timeout=5.0)
            except (RuntimeError, ConnectionError, OSError):
                continue
            if self._adopt(md):
                advanced = True
        return advanced

    def _conn(self, ep):
        with self._conns_lock:
            c = self._conns.get(ep)
        if c is not None:
            return c
        # re-dial a previously-dead initial endpoint, or dial a server
        # that joined after this client was built (rejoin on a fresh
        # endpoint) — short window: failover handles failure. The dial
        # runs OUTSIDE the lock (it can block for the connect window);
        # a racing dial for the same endpoint keeps the first winner.
        c = Connection(ep, **{**self._rpc_opts,
                              "connect_retry_s": 2.0,
                              "fail_fast_refused": True})
        with self._conns_lock:
            cur = self._conns.get(ep)
            if cur is not None:
                won = cur
            else:
                won = self._conns[ep] = c
        if won is not c:
            c.close()
        return won

    def _drop_conn(self, ep):
        with self._conns_lock:
            c = self._conns.pop(ep, None)
        if c is not None:
            c.close()

    # ------------------------------------------------- replay identity
    def replay_state(self):
        """The (client_id, seq) replay identity, checkpointable: a
        restarted trainer that restores this and re-sends its
        in-doubt mutations under the SAME keys dedupes server-side
        across process death — exactly-once survives SIGKILL, not just
        lost responses (docs/fault_tolerance.md "Trainer recovery")."""
        with self._seq_lock:
            return {"client_id": self._client_id, "seq": int(self._seq)}

    def load_replay_state(self, state):
        cid = state["client_id"]
        if isinstance(cid, (bytes, np.ndarray)):
            cid = np.asarray(cid, np.uint8).tobytes().decode("ascii")
        with self._seq_lock:
            self._client_id = str(cid)
            self._seq = int(state.get("seq", 0))

    def _next_rid(self, key=None):
        if key is not None:
            return (self._client_id, key)
        with self._seq_lock:
            self._seq += 1
            return (self._client_id, self._seq)

    def _routed(self, shard, method, _mutating=False, _key=None,
                _timeout=None, **kw):
        """One logical call against a shard's primary, riding out stale
        maps and dead endpoints. The replay id is minted HERE, once, so
        every re-route of this call carries the same identity."""
        rid = self._next_rid(_key) if _mutating else None
        attempts = int(_flag("PADDLE_PS_FAILOVER_RETRIES")) + 1
        backoff = float(_flag("PADDLE_PS_FAILOVER_BACKOFF_S"))
        last = None
        for attempt in range(attempts):
            m = self._map
            ep = m.primary(shard)
            try:
                return self._conn(ep).call(
                    method, _mutating=_mutating, _rid=rid,
                    _timeout=_timeout, __epoch__=m.epoch,
                    __shard__=int(shard), **kw)
            except ShardMapStale as e:
                _monitor.stat_add("ps.replica.stale_maps")
                last = e
                if not self._adopt(e.shard_map_dict):
                    # the server is BEHIND us — teach it our map, then
                    # retry (it may still be the right primary)
                    try:
                        self._conn(ep).call(
                            "install_shard_map",
                            shard_map=self._map.to_dict())
                    except (RuntimeError, ConnectionError, OSError):
                        pass
            except (ConnectRefused, ConnectionError, OSError) as e:
                last = e
                self._drop_conn(ep)
                advanced = self.refresh_shard_map()
                # a parallel fan-out sibling (or a stale-map redirect on
                # another thread) may have adopted the post-promotion map
                # already: refresh reports no advance, but the shard no
                # longer routes HERE — that is a re-route, not a dead end
                moved = self._map.primary(shard) != ep
                if not advanced and not moved \
                        and not self._map.backups(shard):
                    # nowhere to fail over to (unreplicated map, or the
                    # shard lost its last backup): keep the transport's
                    # original fail-loud contract
                    raise
                if moved:
                    continue       # the new primary is live: no pacing
                if attempt < attempts - 1:
                    # a promotion needs a heartbeat deadline to pass —
                    # linear backoff paces the re-route loop across it
                    time.sleep(backoff * (1 + min(attempt, 3)))
        raise last

    @staticmethod
    def _rkey(request_key, method, table):
        # outer-retry-stable replay key: one merged batch can push several
        # tables (and both dense+sparse of the same name) to one server,
        # so the method and table disambiguate within the batch key.
        # Sharded calls add the shard so each slice applies once.
        return None if request_key is None else (request_key, method, table)

    # --------------------------------------------------------------- dense
    def pull_dense(self, table):
        shard = self._map.shard_of_name(table)
        return self._routed(shard, "pull_dense", table=table)

    def push_dense_grad(self, table, grad, request_key=None):
        shard = self._map.shard_of_name(table)
        self._routed(shard, "push_dense_grad", _mutating=True,
                     _key=self._rkey(request_key, "pdg", table),
                     table=table, grad=np.asarray(grad, np.float32))

    def set_dense(self, table, value):
        shard = self._map.shard_of_name(table)
        self._routed(shard, "set_dense", _mutating=True, table=table,
                     value=np.asarray(value, np.float32))

    # -------------------------------------------------------------- sparse
    def _fanout(self, shards, call_one):
        """Run call_one(shard) for every shard in `shards` — in parallel
        from the fan-out pool when there is more than one shard (a batch
        costs max(shard latency), not the sum), serially otherwise or
        when PADDLE_PS_FANOUT_THREADS is 1. Shard slices are disjoint,
        so results are bitwise-independent of the execution order.

        READS ONLY. Mutations keep the serial per-shard loop: a primary
        holds its per-table gate across the synchronous forward to its
        backups, so one client pushing several shard chains CONCURRENTLY
        can close a circular wait across the chained cluster (server i
        holds its gate waiting on server i+1, whose handler waits on the
        gate... all the way around). Serial pushes make that cycle
        impossible by construction — a client never holds two chains."""
        n_threads = int(_flag("PADDLE_PS_FANOUT_THREADS"))
        if len(shards) <= 1 or n_threads <= 1:
            for s in shards:
                call_one(int(s))
            return
        with self._fanout_lock:
            if self._fanout_pool is None:
                self._fanout_pool = ThreadPoolExecutor(
                    max_workers=n_threads,
                    thread_name_prefix="ps-client-fanout")
            pool = self._fanout_pool
        futures = [pool.submit(call_one, int(s)) for s in shards]
        err = None
        for f in futures:
            try:
                f.result()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err = err or e
        if err is not None:
            raise err

    def pull_sparse(self, table, ids):
        """Gather rows for (possibly duplicated) ids; returns
        [len(ids), dim] in input order. Reads always hit the primary.

        The batch is deduped BEFORE the wire (`SparseTable._ensure`'s
        order-preserving dedupe generalized to the cross-shard
        scatter/gather): a batch like [5, 9, 5] costs one row per shard
        regardless of routing, and the per-shard slices fan out in
        parallel (PADDLE_PS_FANOUT_THREADS). The inverse mapping gathers
        unique rows back to input positions, so the caller sees exactly
        the legacy per-position contract."""
        ids_in = np.asarray(ids, np.int64).reshape(-1)
        if ids_in.size == 0:
            # empty batch: route like a dense table (any shard can
            # answer) so the caller still gets a [0, dim]-shaped block
            shard = self._map.shard_of_name(table)
            return np.asarray(self._routed(shard, "pull_sparse",
                                           table=table, ids=ids_in),
                              np.float32)
        uniq, inv = np.unique(ids_in, return_inverse=True)
        _monitor.stat_add("ps.client.pull_ids", int(ids_in.size))
        _monitor.stat_add("ps.client.pull_unique_rows", int(uniq.size))
        uniq, owner = self._map.shard_of_ids(uniq)
        shards = np.unique(owner)
        per_shard: dict[int, np.ndarray] = {}

        def pull_one(s):
            rows = np.asarray(self._routed(int(s), "pull_sparse",
                                           table=table,
                                           ids=uniq[owner == s]),
                              np.float32)
            _monitor.stat_add("ps.client.pull_rpcs")
            per_shard[s] = rows     # disjoint keys: no cross-thread race

        self._fanout(shards, pull_one)
        dim = next(iter(per_shard.values())).shape[1]
        out = np.empty((len(uniq), dim), np.float32)
        for s, rows in per_shard.items():
            out[owner == s] = rows
        return out[inv]

    def push_sparse_grad(self, table, ids, grads, request_key=None):
        """Duplicate ids are MERGED client-side before the wire
        (reference MergeAdd over SelectedRows), bitwise-identical to the
        server-side merge it used to ride: np.unique yields the same
        sorted unique set and np.add.at accumulates rows in the same
        input order either side of the wire."""
        ids, owner, merged = self._merged(ids, grads)
        if ids is None:
            return

        for s in np.unique(owner):
            mask = owner == s
            key = self._rkey(request_key, "psg", table)
            self._routed(int(s), "push_sparse_grad", _mutating=True,
                         _key=None if key is None else key + (int(s),),
                         table=table, ids=ids[mask], grads=merged[mask])

    def push_sparse_delta(self, table, ids, deltas, request_key=None):
        ids, owner, merged = self._merged(ids, deltas)
        if ids is None:
            return

        for s in np.unique(owner):
            mask = owner == s
            key = self._rkey(request_key, "psd", table)
            self._routed(int(s), "push_sparse_delta", _mutating=True,
                         _key=None if key is None else key + (int(s),),
                         table=table, ids=ids[mask], deltas=merged[mask])

    def _merged(self, ids, grads):
        """(unique ids, owner shards, merged grads) for a sparse push —
        (None, None, None) for an empty batch (nothing to send)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.size == 0:
            return None, None, None
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        uniq, inv = np.unique(ids, return_inverse=True)
        if len(uniq) != len(ids):
            merged = np.zeros((len(uniq), grads.shape[1]), np.float32)
            np.add.at(merged, inv, grads)
        else:
            merged = grads[np.argsort(ids, kind="stable")]
        uniq, owner = self._map.shard_of_ids(uniq)
        return uniq, owner, merged

    # --------------------------------------------------------------- misc
    def barrier(self, table, trainer_id, timeout=120.0):
        # the barrier table routes like a dense table — owned by its
        # shard's primary (it used to pin server 0: a SPOF the shard map
        # now owns). The RPC deadline must outlast the barrier's own
        # server-side wait or every long barrier would look stalled.
        shard = self._map.shard_of_name(table)
        return self._routed(shard, "barrier", _mutating=True,
                            _timeout=float(timeout) + 30.0,
                            table=table, trainer_id=trainer_id,
                            timeout=timeout)

    def ping(self):
        """Probe every server's transport (pre-auth health method);
        returns one latency in seconds per endpoint — None for a dead
        endpoint instead of raising, so supervisors see per-server
        health even mid-outage."""
        out = []
        for ep in self.endpoints:
            t0 = time.perf_counter()
            try:
                self._conn(ep).ping(timeout=5.0)
                out.append(time.perf_counter() - t0)
            except (ConnectionError, OSError):
                self._drop_conn(ep)
                out.append(None)
        return out

    def table_state(self, table, server=0):
        return self._server_conn(server).call("table_state", table=table)

    def table_applied(self, table, server=0):
        """How many mutating pushes a server's table has APPLIED (replayed
        retries don't count) — the observable for exactly-once tests."""
        return self._server_conn(server).call("table_applied", table=table)

    def _server_conn(self, server):
        return self._conn(self.endpoints[server])

    def save_snapshot(self, path):
        """Ask every server to snapshot its tables to server-local disk
        (file per server: {path}.s{i}); mid-train fault tolerance
        (reference large_scale_kv.h checkpointing)."""
        return [self._server_conn(i).call("save_snapshot",
                                          path=f"{path}.s{i}")
                for i in range(len(self.endpoints))]

    def load_snapshot(self, path):
        return [self._server_conn(i).call("load_snapshot",
                                          path=f"{path}.s{i}")
                for i in range(len(self.endpoints))]

    def stop_servers(self):
        for ep in {*self.endpoints, *self._map.servers}:
            try:
                self._conn(ep).call("stop")
            except (ConnectionError, OSError):
                pass

    def close(self):
        self._listeners.clear()
        with self._fanout_lock:
            pool, self._fanout_pool = self._fanout_pool, None
        if pool is not None:
            pool.shutdown(wait=True)
        with self._conns_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            if c is not None:
                c.close()


class Communicator:
    """Async gradient channel (reference communicator.cc AsyncCommunicator:
    per-var bounded queues, a background thread that MERGES queued grads
    — MergeAdd for sparse — and sends every batch; workers never block on
    the push). flush() drains synchronously; used at barriers/epoch ends.
    """

    def __init__(self, client: PSClient, send_every=4, max_queue=64,
                 max_delay_s=0.05):
        self._client = client
        self._send_every = int(send_every)
        self._max_delay_s = float(max_delay_s)
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._error: BaseException | None = None
        # per-merged-batch replay key: outer send retries reuse it, so a
        # batch that half-landed (server 0 applied, server 1 reset) is
        # finished rather than double-applied on the servers that took
        # it. Namespaced by a per-Communicator id — batch numbers restart
        # at 1 in every instance, and two communicators over one client
        # must not collide in the server's replay cache
        self._comm_id = uuid.uuid4().hex[:16]
        self._batch_no = 0
        self._keyed = bool(getattr(client, "supports_request_keys", False))
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- worker
    def _check_alive(self):
        """Surface a background send failure to the caller instead of the
        r03 failure mode: thread dies silently, queue fills, push_* blocks
        forever in Queue.put."""
        if self._error is not None:
            raise RuntimeError(
                "ps communicator send thread died") from self._error
        if not self._thread.is_alive() and not self._stop.is_set():
            raise RuntimeError("ps communicator send thread is not running")

    def _put(self, item):
        self._check_alive()
        while True:
            try:
                self._q.put(item, timeout=1.0)
                return
            except queue.Full:
                self._check_alive()   # don't hang on a dead consumer

    def push_sparse(self, table, ids, grads):
        self._put(("sparse", table, np.asarray(ids, np.int64).reshape(-1),
                   np.asarray(grads, np.float32)))

    def push_dense(self, table, grad):
        self._put(("dense", table, None, np.asarray(grad, np.float32)))

    # --------------------------------------------------------- background
    def _loop(self):
        # drain-tracking rides the queue's task accounting: task_done only
        # fires AFTER a batch lands on the servers, so flush()'s join-style
        # wait can't slip past a produced-but-unsent item (an Event toggled
        # on a momentary empty poll could)
        pending = []
        first_ts = None
        try:
            while not self._stop.is_set() or not self._q.empty() or pending:
                try:
                    pending.append(self._q.get(timeout=0.05))
                    if first_ts is None:
                        first_ts = time.monotonic()
                except queue.Empty:
                    pass
                # batch trigger: enough items for a merge, a stop/drain, or
                # the oldest item aging past max_delay — NOT momentary
                # queue emptiness, which under normal pacing fires every
                # iteration and defeats send_every/MergeAdd batching
                aged = (first_ts is not None
                        and time.monotonic() - first_ts >= self._max_delay_s)
                if pending and (len(pending) >= self._send_every
                                or self._stop.is_set() or aged):
                    try:
                        self._send_with_retry(pending)
                    finally:
                        for _ in pending:
                            self._q.task_done()
                    pending = []
                    first_ts = None
        except BaseException as e:  # noqa: BLE001 — re-raised to callers
            self._error = e
            # the send thread is the PS stack's pulse: its death is a
            # transport death — flight-record the span/metric history
            # (no-op unless PADDLE_TPU_DUMP_DIR is set)
            from ...core import flight_recorder as _fr
            _fr.dump("ps_communicator_death", e)
            # NOTE: _send_merged's finally already task_done'd `pending`;
            # only drain what's still queued so flush() raises instead of
            # timing out (double-accounting raises 'task_done called too
            # many times')
            while True:
                try:
                    self._q.get_nowait()
                    self._q.task_done()
                except queue.Empty:
                    break

    def _send_with_retry(self, items):
        """One more layer of patience on top of the per-call transport
        retries: back off and re-send the merged batch (under its stable
        replay key — exactly-once holds across these retries too) before
        declaring the send thread dead."""
        self._batch_no += 1
        key = (self._comm_id, self._batch_no) if self._keyed else None
        attempts = int(_flag("PADDLE_PS_SEND_RETRIES")) + 1
        backoff = float(_flag("PADDLE_PS_BACKOFF_BASE_S"))
        ceiling = float(_flag("PADDLE_PS_BACKOFF_MAX_S"))
        from ...core import flight_recorder as _fr
        for attempt in range(attempts):
            try:
                with _trace.span("ps.comm/send_batch", items=len(items),
                                 batch_no=self._batch_no,
                                 attempt=attempt):
                    if attempt < attempts - 1:
                        # this layer will retry: an inner per-call
                        # exhaustion is not yet transport death — only
                        # the LAST attempt may declare it
                        with _fr.suppressed("ps_transport_death"):
                            self._send_merged(items, key)
                    else:
                        self._send_merged(items, key)
                return
            except OSError:
                # ConnectionError / DeadlineExceeded / FrameError — the
                # transport already burned its own retry budget
                if attempt == attempts - 1:
                    raise
                _monitor.stat_add("ps.communicator.send_retries")
                # 4x the transport's base so the outer layer backs off
                # slower than the inner one, same configurable ceiling
                time.sleep(min(ceiling, backoff * (2 ** attempt) * 4))

    def _send_merged(self, items, request_key=None):
        sparse: dict[str, list] = {}
        dense: dict[str, np.ndarray] = {}
        for kind, table, ids, grads in items:
            if kind == "sparse":
                sparse.setdefault(table, []).append((ids, grads))
            else:
                if table in dense:
                    dense[table] = dense[table] + grads
                else:
                    dense[table] = grads
        kw = {"request_key": request_key} if self._keyed else {}
        for table, parts in sparse.items():
            ids = np.concatenate([p[0] for p in parts])
            grads = np.concatenate(
                [p[1].reshape(len(p[0]), -1) for p in parts])
            # duplicate merging (reference MergeAdd) happens ONCE, in
            # PSClient._merged, before the wire — not re-implemented here
            self._client.push_sparse_grad(table, ids, grads, **kw)
        for table, grad in dense.items():
            self._client.push_dense_grad(table, grad, **kw)

    def flush(self, timeout=60.0):
        deadline = time.monotonic() + timeout
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                if self._error is not None:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("communicator failed to drain")
                self._q.all_tasks_done.wait(min(remaining, 0.5))
        self._check_alive()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=60.0)
        if self._error is not None:
            raise RuntimeError(
                "ps communicator send thread died") from self._error
