"""PSServer — hosts tables, serves pull/push, optionally replicates.

Analog of reference N21 PSServer (distributed/service/brpc_ps_server.cc:
service handlers pull_dense/push_dense_param/push_sparse/...; table map
from ps.proto) and N20 listen_and_serv_op (operators/pscore/
listen_and_serv_op.cc server loop). The server is compute-free: update
rules live in the tables (table.py), the RPC layer is rpc.py, and the
replicated-storage protocols (shard-map routing, primary->backup
forwarding, heartbeat failover, catch-up) live in replica.py — enabled
per-server with `enable_replication()` after `start()`.
"""
from __future__ import annotations

import threading

import numpy as np

from .replica import REPLICATED_MUTATIONS
from .rpc import ReplayCache, serve
from .table import SparseTable, make_table

__all__ = ["PSServer"]


class PSServer:
    def __init__(self, endpoint="127.0.0.1:0", tables: dict | None = None,
                 replica: dict | None = None):
        """tables: name -> table spec dict (see table.make_table) or a
        ready table object. replica: optional kwargs for
        `enable_replication`, applied automatically once `start()` has
        bound the port (the manager needs the real endpoint)."""
        self._tables = {}
        for name, spec in (tables or {}).items():
            self.add_table(name, spec)
        self._stop = threading.Event()
        self._endpoint = endpoint
        self._thread = None
        self.port = None
        # shared with serve() AND the replica catch-up path, which
        # registers delta-log rids so live forwards dedupe against them
        self.replay = ReplayCache()
        self._replica = None
        self._replica_cfg = dict(replica) if replica else None

    # -------------------------------------------------------------- admin
    def add_table(self, name, spec):
        self._tables[name] = spec if not isinstance(spec, dict) \
            else make_table(spec)

    def table(self, name):
        return self._tables[name]

    @property
    def replica(self):
        return self._replica

    def start(self):
        self.port, self._thread = serve(self._endpoint, self._handle,
                                        self._stop, replay=self.replay)
        host = self._endpoint.rsplit(":", 1)[0]
        self.endpoint = f"{host}:{self.port}"
        if self._replica_cfg is not None:
            self.enable_replication(**self._replica_cfg)
        return self.endpoint

    def enable_replication(self, **kwargs):
        """Attach a replica.ReplicaManager (call after start(); the
        manager identifies this server by its bound endpoint). kwargs:
        shard_map, peers, n_backups, heartbeat_s, heartbeat_timeout_s,
        rpc_opts, rejoin — see ReplicaManager."""
        if self._thread is None:
            raise RuntimeError("enable_replication() requires a started "
                               "server (the bound endpoint is its id)")
        from .replica import ReplicaManager
        self._replica = ReplicaManager(self, self.endpoint, **kwargs)
        return self._replica

    def run(self):
        """Block until a peer calls stop (reference fleet.run_server)."""
        if self._thread is None:
            self.start()
        self._stop.wait()

    def shutdown(self):
        self._stop.set()
        if self._replica is not None:
            self._replica.close()
        # join the accept loop so the port is RELEASED when we return —
        # an elastic restart rebinds the same endpoint immediately
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)

    # ----------------------------------------------------------- handlers
    def _apply_table_op(self, t, method, req):
        """One table operation — shared by the live request path and the
        replica catch-up delta replay."""
        if method == "pull_dense":
            return t.pull()
        if method == "push_dense_grad":
            t.push_grad(req["grad"])
            return True
        if method == "set_dense":
            t.set(req["value"])
            return True
        if method == "pull_sparse":
            return t.pull(req["ids"])
        if method == "push_sparse_grad":
            t.push_grad(req["ids"], req["grads"])
            return True
        if method == "push_sparse_delta":
            t.push_delta(req["ids"], req["deltas"])
            return True
        if method == "barrier":
            return t.wait(req["trainer_id"], req.get("timeout", 120.0))
        if method == "table_state":
            return t.state()
        if method == "table_applied":
            # how many pushes this table has APPLIED — replayed retries
            # don't re-apply, so chaos tests can assert exactly-once
            # through the public RPC surface
            return int(getattr(t, "applied", 0))
        if method == "load_table_state":
            t.load_state(req["state"])
            return True
        if method == "table_size":
            return len(t) if isinstance(t, SparseTable) else \
                int(np.prod(t.param.shape))
        raise ValueError(f"unknown PS method {method!r}")

    def _handle(self, method, req, rid=None):
        if method == "stop":
            self._stop.set()
            return True
        if method == "ping":
            return "pong"
        if method == "list_tables":
            return {n: type(t).__name__ for n, t in self._tables.items()}
        if method == "get_shard_map":
            return self._replica.map_dict() if self._replica else None
        if method == "install_shard_map":
            if self._replica is None:
                return False
            return self._replica.install(req["shard_map"])
        if method == "replica_beat":
            if self._replica is None:
                return {"epoch": -1}
            return self._replica.on_beat(req["from"], req.get("epoch", 0))
        if method == "replica_fetch":
            if self._replica is None:
                raise RuntimeError("replication is not enabled here")
            return self._replica.fetch()
        if method == "replica_attach":
            if self._replica is None:
                raise RuntimeError("replication is not enabled here")
            return self._replica.attach(req["endpoint"], req["shard"],
                                        req.get("seqs", {}))
        if method == "save_snapshot":
            # mid-train fault-tolerance snapshot (reference
            # operators/distributed/large_scale_kv.h SaveToSelectedRows /
            # table checkpointing): every table's full state to local disk,
            # written atomically (tmp + rename)
            import os
            import pickle
            path = req["path"]
            state = {n: t.state() for n, t in self._tables.items()
                     if hasattr(t, "state")}
            tmp = f"{path}.tmp"
            with open(tmp, "wb") as f:
                pickle.dump(state, f, protocol=4)
            os.replace(tmp, path)
            return sorted(state)
        if method == "load_snapshot":
            import pickle
            with open(req["path"], "rb") as f:
                state = pickle.load(f)  # noqa: S301 — server-local file
            for n, st in state.items():
                if n in self._tables and hasattr(self._tables[n],
                                                 "load_state"):
                    self._tables[n].load_state(st)
            return sorted(state)

        # ---- data path: shard-map routing check, apply, replicate ----
        mgr = self._replica
        shard = is_forward = None
        if mgr is not None:
            shard, is_forward = mgr.check(method, req)
        else:
            # unreplicated server: drop routing keys a shard-map client
            # may still stamp (mixed clusters during rollout)
            req.pop("__shard__", None)
            req.pop("__epoch__", None)
            req.pop("__fwd__", None)
        tname = req.pop("table")
        t = self._tables[tname]
        if mgr is not None and method in REPLICATED_MUTATIONS \
                and mgr.replicates(tname):
            # apply + log + forward atomically per table: per-table
            # forwards leave in sequence order over the serialized
            # backup connection, and the ack returns only after the
            # write is durable on the quorum
            with mgr.gate(tname):
                # a quorum-failure retry re-enters under its ORIGINAL
                # rid with the mutation already applied+logged here:
                # skip the apply, re-run forward+quorum only
                replayed = rid is not None and mgr.seen(tname, rid)
                result = None if replayed \
                    else self._apply_table_op(t, method, req)
                mgr.record_and_forward(tname, shard, method, req, rid,
                                       bool(is_forward),
                                       log_entry=not replayed)
            return result
        return self._apply_table_op(t, method, req)
