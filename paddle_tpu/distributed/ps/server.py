"""PSServer — hosts tables, serves pull/push.

Analog of reference N21 PSServer (distributed/service/brpc_ps_server.cc:
service handlers pull_dense/push_dense_param/push_sparse/...; table map
from ps.proto) and N20 listen_and_serv_op (operators/pscore/
listen_and_serv_op.cc server loop). The server is compute-free: update
rules live in the tables (table.py), the RPC layer is rpc.py.
"""
from __future__ import annotations

import threading

import numpy as np

from .rpc import serve
from .table import BarrierTable, DenseTable, GeoSparseTable, SparseTable, \
    make_table

__all__ = ["PSServer"]


class PSServer:
    def __init__(self, endpoint="127.0.0.1:0", tables: dict | None = None):
        """tables: name -> table spec dict (see table.make_table) or a
        ready table object."""
        self._tables = {}
        for name, spec in (tables or {}).items():
            self.add_table(name, spec)
        self._stop = threading.Event()
        self._endpoint = endpoint
        self._thread = None
        self.port = None

    # -------------------------------------------------------------- admin
    def add_table(self, name, spec):
        self._tables[name] = spec if not isinstance(spec, dict) \
            else make_table(spec)

    def table(self, name):
        return self._tables[name]

    def start(self):
        self.port, self._thread = serve(self._endpoint, self._handle,
                                        self._stop)
        host = self._endpoint.rsplit(":", 1)[0]
        self.endpoint = f"{host}:{self.port}"
        return self.endpoint

    def run(self):
        """Block until a peer calls stop (reference fleet.run_server)."""
        if self._thread is None:
            self.start()
        self._stop.wait()

    def shutdown(self):
        self._stop.set()
        # join the accept loop so the port is RELEASED when we return —
        # an elastic restart rebinds the same endpoint immediately
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)

    # ----------------------------------------------------------- handlers
    def _handle(self, method, req):
        if method == "stop":
            self._stop.set()
            return True
        if method == "ping":
            return "pong"
        if method == "list_tables":
            return {n: type(t).__name__ for n, t in self._tables.items()}
        if method == "save_snapshot":
            # mid-train fault-tolerance snapshot (reference
            # operators/distributed/large_scale_kv.h SaveToSelectedRows /
            # table checkpointing): every table's full state to local disk,
            # written atomically (tmp + rename)
            import os
            import pickle
            path = req["path"]
            state = {n: t.state() for n, t in self._tables.items()
                     if hasattr(t, "state")}
            tmp = f"{path}.tmp"
            with open(tmp, "wb") as f:
                pickle.dump(state, f, protocol=4)
            os.replace(tmp, path)
            return sorted(state)
        if method == "load_snapshot":
            import pickle
            with open(req["path"], "rb") as f:
                state = pickle.load(f)  # noqa: S301 — server-local file
            for n, st in state.items():
                if n in self._tables and hasattr(self._tables[n],
                                                 "load_state"):
                    self._tables[n].load_state(st)
            return sorted(state)
        t = self._tables[req.pop("table")]
        if method == "pull_dense":
            return t.pull()
        if method == "push_dense_grad":
            t.push_grad(req["grad"])
            return True
        if method == "set_dense":
            t.set(req["value"])
            return True
        if method == "pull_sparse":
            return t.pull(req["ids"])
        if method == "push_sparse_grad":
            t.push_grad(req["ids"], req["grads"])
            return True
        if method == "push_sparse_delta":
            t.push_delta(req["ids"], req["deltas"])
            return True
        if method == "barrier":
            return t.wait(req["trainer_id"], req.get("timeout", 120.0))
        if method == "table_state":
            return t.state()
        if method == "table_applied":
            # how many pushes this table has APPLIED — replayed retries
            # don't re-apply, so chaos tests can assert exactly-once
            # through the public RPC surface
            return int(getattr(t, "applied", 0))
        if method == "load_table_state":
            t.load_state(req["state"])
            return True
        if method == "table_size":
            return len(t) if isinstance(t, SparseTable) else \
                int(np.prod(t.param.shape))
        raise ValueError(f"unknown PS method {method!r}")
