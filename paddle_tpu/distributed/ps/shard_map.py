"""Versioned shard map for the replicated PS storage tier.

PR 2 made the PS *transport* survive faults, but placement was still the
hard-coded `id % n_servers` rule: every shard lived on exactly one server
and a permanent server death lost it. This module makes placement an
explicit, versioned object (the reference's ps.proto table placement +
the TensorFlow paper's variable-placement maps play the same role):

- ``ShardMap``: shard -> primary endpoint + ordered backup endpoints,
  for sparse shards AND dense tables (dense tables hash onto shards with
  ``shard_of_name``; sparse ids with ``shard_of_id``). The *default* map
  (``ShardMap.default``) reproduces the legacy modulo routing bit-for-bit
  (n_shards == n_servers, shard i's primary is server i, no backups), so
  unreplicated clusters behave exactly as before.
- **Epoch**: every mutation of the map (promotion, eviction, backup
  attach) bumps a monotonically increasing epoch. Clients cache the map
  and stamp requests with their epoch; a server whose epoch differs
  answers with a ``ShardMapStale`` redirect carrying its own map instead
  of silently serving from (or applying to) the wrong placement. Newer
  epoch always wins on adoption, so maps gossip forward through
  redirects, heartbeats and install broadcasts.

The map is deliberately a plain-container value object (dict/list/str/
int only) so it can ride the restricted-unpickler RPC transport and be
compared/copied trivially.
"""
from __future__ import annotations

import zlib

import numpy as np

__all__ = ["ShardMap", "ShardMapStale"]


class ShardMapStale(RuntimeError):
    """Routing rejection: the caller's shard-map epoch does not match the
    server's (or the server is not the primary the caller thinks it is).
    Carries the server's current map so one redirect round-trip is enough
    for the client to re-route. Never cached in the replay cache and
    never retried blindly by the transport — the *client* re-routes."""

    def __init__(self, map_dict, reason="shard map is stale"):
        epoch = (map_dict or {}).get("epoch")
        super().__init__(f"{reason} (server epoch {epoch})")
        self.shard_map_dict = map_dict


class ShardMap:
    """shard -> (primary, backups) placement, versioned by ``epoch``.

    ``shards`` is a list of ``{"primary": endpoint, "backups": [eps]}``;
    ``servers`` is the member list (stable construction order — clients
    keep using it for per-server admin fan-outs like snapshots)."""

    def __init__(self, shards, servers, epoch=0):
        self.shards = [{"primary": s["primary"],
                        "backups": list(s.get("backups", ()))}
                       for s in shards]
        self.servers = list(servers)
        self.epoch = int(epoch)

    # ------------------------------------------------------- constructors
    @classmethod
    def default(cls, endpoints):
        """Legacy-equivalent map: one shard per server, no backups. With
        this map every routing decision below reproduces the pre-replica
        `id % n_servers` / `crc32(name) % n_servers` rules exactly."""
        eps = list(endpoints)
        return cls([{"primary": ep, "backups": []} for ep in eps], eps, 0)

    @classmethod
    def create(cls, endpoints, n_backups=1):
        """Replicated map: shard i's primary is server i, its backups the
        next ``n_backups`` servers round-robin (the classic chained
        primary/backup layout — every server primaries one shard and
        backs up its neighbours'). Starts at epoch 1 so it strictly
        supersedes the synthetic epoch-0 default map a shard-map-naive
        client builds before asking the cluster."""
        eps = list(endpoints)
        n = len(eps)
        k = max(0, min(int(n_backups), n - 1))
        shards = [{"primary": eps[i],
                   "backups": [eps[(i + 1 + j) % n] for j in range(k)]}
                  for i in range(n)]
        return cls(shards, eps, 1)

    @classmethod
    def from_dict(cls, d):
        return cls(d["shards"], d["servers"], d.get("epoch", 0))

    def to_dict(self):
        return {"epoch": self.epoch,
                "servers": list(self.servers),
                "shards": [{"primary": s["primary"],
                            "backups": list(s["backups"])}
                           for s in self.shards]}

    # ------------------------------------------------------------ routing
    @property
    def n_shards(self):
        return len(self.shards)

    def primary(self, shard):
        return self.shards[int(shard)]["primary"]

    def backups(self, shard):
        return list(self.shards[int(shard)]["backups"])

    def members(self, shard):
        s = self.shards[int(shard)]
        return [s["primary"]] + list(s["backups"])

    def shard_of_id(self, i):
        return int(i) % self.n_shards

    def shard_of_ids(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        return ids, ids % np.int64(self.n_shards)

    def shard_of_name(self, name):
        # crc32, NOT hash(): str hash is per-process randomized and every
        # worker must route a dense/barrier table to the same shard
        return zlib.crc32(name.encode()) % self.n_shards

    # ------------------------------------------------------- reconfiguring
    def without(self, endpoint):
        """New map (epoch+1) with ``endpoint`` removed everywhere: shards
        it primaried promote their first surviving backup; shards it
        backed up just drop it. Shards with no surviving replica keep the
        dead primary listed (calls to them keep failing loudly rather
        than silently rehoming to an empty table)."""
        shards = []
        for s in self.shards:
            backups = [b for b in s["backups"] if b != endpoint]
            primary = s["primary"]
            if primary == endpoint:
                if backups:
                    primary = backups.pop(0)
                # else: unrecoverable shard; leave the tombstone primary
            shards.append({"primary": primary, "backups": backups})
        servers = [ep for ep in self.servers if ep != endpoint]
        return ShardMap(shards, servers, self.epoch + 1)

    def with_backup(self, shard, endpoint):
        """New map (epoch+1) with ``endpoint`` appended to ``shard``'s
        backups (rejoin/catch-up completion)."""
        shards = [{"primary": s["primary"], "backups": list(s["backups"])}
                  for s in self.shards]
        s = shards[int(shard)]
        if endpoint != s["primary"] and endpoint not in s["backups"]:
            s["backups"].append(endpoint)
        servers = list(self.servers)
        if endpoint not in servers:
            servers.append(endpoint)
        return ShardMap(shards, servers, self.epoch + 1)

    def under_replicated(self, n_backups):
        """Shard indices carrying fewer than ``n_backups`` backups — the
        slots a rejoining server offers itself to."""
        return [i for i, s in enumerate(self.shards)
                if len(s["backups"]) < int(n_backups)]

    def shards_primaried_by(self, endpoint):
        return [i for i, s in enumerate(self.shards)
                if s["primary"] == endpoint]

    # ---------------------------------------------------------------- misc
    def __eq__(self, other):
        return isinstance(other, ShardMap) and \
            self.to_dict() == other.to_dict()

    def __repr__(self):
        return (f"ShardMap(epoch={self.epoch}, n_shards={self.n_shards}, "
                f"servers={self.servers})")
