"""Parameter-server tables.

TPU-native re-design of the reference PS table layer (N21:
paddle/fluid/distributed/table/ — CommonDenseTable common_dense_table.cc,
CommonSparseTable common_sparse_table.cc, SparseGeoTable
sparse_geo_table.cc, BarrierTable barrier_table.cc; accessor update rules
from table/depends/sparse.h + the optimizer ops they mirror).

Design deltas (SURVEY.md §2.1 N20-N22, hard part 5):
- Tables are host-resident numpy state. The TPU never sees the full
  (unbounded) sparse vocab: workers pull just the rows a batch touches,
  the jitted step computes row gradients, and workers push those rows
  back. That is the "host-KV + gather" sharded-embedding design — the
  MXU works on dense [n_ids, dim] blocks, the hash map stays host-side.
- Update rules run server-side on push (reference "accessor" semantics),
  so async workers never hold optimizer slots for sparse params.
- Rows are created lazily on first touch (reference large_scale_kv.h
  auto-grown entries) with per-table initializers.
"""
from __future__ import annotations

import threading
import zlib

import numpy as np

__all__ = ["DenseTable", "SparseTable", "GeoSparseTable", "BarrierTable",
           "make_table"]


# ---------------------------------------------------------------- accessors

def _sgd_init(shape, dtype):
    return {}


def _sgd_apply(param, grad, slots, lr):
    param -= lr * grad
    return param


def _adagrad_init(shape, dtype):
    return {"moment": np.zeros(shape, dtype)}


def _adagrad_apply(param, grad, slots, lr, eps=1e-6):
    m = slots["moment"]
    m += grad * grad
    param -= lr * grad / (np.sqrt(m) + eps)
    return param


def _adam_init(shape, dtype):
    return {"m": np.zeros(shape, dtype), "v": np.zeros(shape, dtype),
            "t": np.zeros(shape[:-1] + (1,), np.int64) if len(shape) > 1
            else np.zeros((1,), np.int64)}


def _adam_apply(param, grad, slots, lr, beta1=0.9, beta2=0.999, eps=1e-8):
    slots["t"] += 1
    t = slots["t"]
    m, v = slots["m"], slots["v"]
    m *= beta1
    m += (1 - beta1) * grad
    v *= beta2
    v += (1 - beta2) * grad * grad
    mhat = m / (1 - beta1 ** t)
    vhat = v / (1 - beta2 ** t)
    param -= lr * mhat / (np.sqrt(vhat) + eps)
    return param


_ACCESSORS = {
    "sgd": (_sgd_init, _sgd_apply),
    "adagrad": (_adagrad_init, _adagrad_apply),
    "adam": (_adam_init, _adam_apply),
}


def _splitmix64(x):
    """Vectorized splitmix64 over uint64 arrays (wrapping arithmetic)."""
    x = x + np.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _initializer(kind, dim, seed):
    """Per-ID deterministic row initializer: rows(ids) -> [len(ids), dim].

    A row's initial value is a pure function of (seed, id, column) — a
    counter-based hash stream, not a shared sequential RNG. That makes
    materialization ORDER-INDEPENDENT, which the replicated storage tier
    requires: a promoted backup (or a rejoined server) materializes a
    never-pushed row on first pull, and it must get bit-identical values
    to the row the dead primary would have served, no matter how many
    rows either side created in between."""
    if kind == "zeros":
        return lambda ids: np.zeros((len(ids), dim), np.float32)
    if kind not in ("uniform", "normal"):
        raise ValueError(f"unknown initializer {kind!r}")
    base = np.uint64(seed) * np.uint64(0x2545F4914F6CDD1D) \
        ^ np.uint64(zlib.crc32(kind.encode()))

    def rows(ids):
        ids_u = np.asarray(ids, np.int64).reshape(-1, 1).view(np.uint64)
        cols = np.arange(dim, dtype=np.uint64).reshape(1, -1)
        h = _splitmix64(ids_u * np.uint64(0x100000001B3) ^ cols ^ base)
        # top 53 bits -> uniform [0, 1)
        u = (h >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))
        if kind == "uniform":
            scale = 1.0 / np.sqrt(dim)
            return ((u * 2.0 - 1.0) * scale).astype(np.float32)
        # normal: Box-Muller from two independent hash streams
        h2 = _splitmix64(h ^ np.uint64(0xD6E8FEB86659FD93))
        u2 = (h2 >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))
        u = np.maximum(u, 2.0 ** -53)          # log(0) guard
        z = np.sqrt(-2.0 * np.log(u)) * np.cos(2.0 * np.pi * u2)
        return (z * 0.01).astype(np.float32)

    return rows


# ------------------------------------------------------------------ tables

class DenseTable:
    """Whole-parameter block with a server-side update rule (reference
    common_dense_table.cc: values_ + per-rule slots, pull_dense returning
    the block, push_dense applying sgd/adam/"sum")."""

    def __init__(self, shape, optimizer="sgd", lr=0.01, init="zeros",
                 seed=0):
        shape = tuple(int(s) for s in shape)
        if init == "zeros":
            self.param = np.zeros(shape, np.float32)
        else:
            rng = np.random.RandomState(seed)
            self.param = (rng.randn(*shape) *
                          (0.01 if init == "normal"
                           else 1.0 / np.sqrt(shape[-1]))).astype(np.float32)
        slot_init, self._apply = _ACCESSORS[optimizer]
        self._slots = slot_init(shape, np.float32)
        self.lr = float(lr)
        self._lock = threading.Lock()
        # count of APPLIED mutations (not replayed retries) — the
        # observable behind the exactly-once chaos assertions
        self.applied = 0

    def pull(self):
        with self._lock:
            return self.param.copy()

    def push_grad(self, grad):
        grad = np.asarray(grad, np.float32).reshape(self.param.shape)
        with self._lock:
            self.param = self._apply(self.param, grad, self._slots, self.lr)
            self.applied += 1

    def set(self, value):
        with self._lock:
            # np.array, not asarray: RPC payloads arrive as READ-ONLY
            # views over pickle-5 buffers, and the accessors update
            # self.param in place
            self.param = np.array(value, np.float32).reshape(
                self.param.shape)
            self.applied += 1

    def state(self):
        with self._lock:
            return {"param": self.param.copy(),
                    "slots": {k: v.copy() for k, v in self._slots.items()},
                    "lr": self.lr}

    def load_state(self, st):
        with self._lock:
            # np.array copies: state arriving over RPC (load_table_state)
            # is a read-only pickle-5 buffer view, and accessors mutate
            # param/slots in place
            self.param = np.array(st["param"], np.float32)
            self._slots = {k: np.array(v) for k, v in st["slots"].items()}
            self.lr = float(st.get("lr", self.lr))


class SparseTable:
    """Auto-growing id -> row KV store (reference common_sparse_table.cc +
    operators/distributed/large_scale_kv.h: rows materialize on first
    access; pull_sparse gathers, push_sparse applies the accessor rule to
    just the touched rows). ids are arbitrary int64 — no dense vocab bound.

    Storage is array-backed (one [n, dim] block + an id->index map +
    per-slot blocks), so pull is one fancy-index gather and push applies
    the accessor rule to the whole touched block at once — the vectorized
    form of the reference's per-shard value blocks (common_sparse_table.cc
    shard_values_), with geometric capacity growth. Measured ~8x
    end-to-end over the per-row-dict design (tools/ps_load_test.py:
    ~0.83M rows/sec aggregate on 4 local workers).
    """

    def __init__(self, dim, optimizer="adagrad", lr=0.05, init="uniform",
                 seed=0):
        self.dim = int(dim)
        self._index: dict[int, int] = {}
        slot_init, self._apply = _ACCESSORS[optimizer]
        self._slot_init = lambda n: slot_init((n, self.dim), np.float32)
        self._data = np.zeros((0, self.dim), np.float32)
        self._slots = self._slot_init(0)
        self._init_rows = _initializer(init, self.dim, seed)
        self.lr = float(lr)
        self._lock = threading.Lock()
        self.applied = 0  # applied mutations; see DenseTable.applied

    def __len__(self):
        return len(self._index)

    def _ensure(self, ids):
        # dedupe while preserving first-seen order: a batch like
        # [5, 9, 5] must materialize id 5 ONCE, or the duplicate would
        # claim two rows and corrupt _index for every later id
        missing = [i for i in dict.fromkeys(ids) if i not in self._index]
        if not missing:
            return
        base = len(self._index)
        need = base + len(missing)
        cap = len(self._data)
        if need > cap:  # geometric growth: amortized O(new rows)
            new_cap = max(need, cap * 2, 1024)

            def grow(arr):
                out = np.zeros((new_cap,) + arr.shape[1:], arr.dtype)
                out[:len(arr)] = arr
                return out

            self._data = grow(self._data)
            self._slots = {k: grow(v) for k, v in self._slots.items()}
        self._data[base:need] = self._init_rows(missing)
        fresh = self._slot_init(len(missing))
        for k in self._slots:
            self._slots[k][base:need] = fresh[k]
        for k, i in enumerate(missing):
            self._index[i] = base + k

    def _idx(self, ids):
        ix = self._index
        return np.fromiter((ix[i] for i in ids), np.int64, count=len(ids))

    def pull(self, ids):
        ids = [int(i) for i in np.asarray(ids).reshape(-1)]
        with self._lock:
            self._ensure(ids)
            if not ids:
                return np.zeros((0, self.dim), np.float32)
            return self._data[self._idx(ids)].copy()

    def push_grad(self, ids, grads):
        """Duplicate ids in one push are accumulated first (reference
        MergeAdd over SelectedRows before the rule applies)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        merged = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(merged, inv, grads)
        keys = [int(i) for i in uniq]
        with self._lock:
            self._ensure(keys)
            idx = self._idx(keys)
            block = self._data[idx]
            slot_block = {k: v[idx] for k, v in self._slots.items()}
            block = self._apply(block, merged, slot_block, self.lr)
            self._data[idx] = block
            for k, v in slot_block.items():
                self._slots[k][idx] = v
            self.applied += 1

    def state(self):
        with self._lock:
            n = len(self._index)
            ids = np.zeros(n, np.int64)
            for i, pos in self._index.items():
                ids[pos] = i
            return {"ids": ids, "values": self._data[:n].copy(),
                    "lr": self.lr,
                    "slots": {int(i): {k: self._slots[k][pos].copy()
                                       for k in self._slots}
                              for i, pos in self._index.items()}}

    def load_state(self, st, merge=False):
        """merge=False resets the table to exactly `st`; merge=True
        UPSERTS `st`'s rows over the existing ones (rows absent from
        `st` keep their values) — the replica catch-up path merges one
        shard's rows at a time without clobbering rows it already holds
        for other shards."""
        with self._lock:
            ids = [int(i) for i in st["ids"]]
            if merge:
                self._ensure(ids)
                if ids:
                    idx = self._idx(ids)
                    self._data[idx] = np.array(
                        st["values"], np.float32).reshape(len(ids),
                                                          self.dim)
            else:
                self._index = {i: pos for pos, i in enumerate(ids)}
                # np.array copies — see DenseTable.load_state
                self._data = np.array(st["values"], np.float32).reshape(
                    len(ids), self.dim)
                self._slots = self._slot_init(len(ids))
            for i, s in (st.get("slots", {}) or {}).items():
                pos = self._index.get(int(i))
                if pos is None:
                    continue
                for k, v in s.items():
                    self._slots[k][pos] = np.asarray(v)
            self.lr = float(st.get("lr", self.lr))


class GeoSparseTable(SparseTable):
    """Geo-SGD variant (reference sparse_geo_table.cc + communicator.cc
    GeoCommunicator): workers train LOCAL embedding copies and
    periodically push the delta vs their last sync; the server folds
    deltas in and hands back fresh rows. push is plain addition — the
    worker already applied its own optimizer."""

    def __init__(self, dim, lr=1.0, init="uniform", seed=0):
        super().__init__(dim, optimizer="sgd", lr=lr, init=init, seed=seed)

    def push_delta(self, ids, deltas):
        ids = np.asarray(ids, np.int64).reshape(-1)
        deltas = np.asarray(deltas, np.float32).reshape(len(ids), self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        merged = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(merged, inv, deltas)
        keys = [int(i) for i in uniq]
        with self._lock:
            self._ensure(keys)
            self._data[self._idx(keys)] += merged
            self.applied += 1


class BarrierTable:
    """Worker-count barrier (reference barrier_table.cc: trigger when all
    trainers arrive)."""

    def __init__(self, trainer_num):
        self.trainer_num = int(trainer_num)
        self._cond = threading.Condition()
        self._arrived = set()
        self._generation = 0

    def wait(self, trainer_id, timeout=120.0):
        with self._cond:
            gen = self._generation
            self._arrived.add(int(trainer_id))
            if len(self._arrived) >= self.trainer_num:
                self._arrived.clear()
                self._generation += 1
                self._cond.notify_all()
                return True
            ok = self._cond.wait_for(lambda: self._generation > gen,
                                     timeout=timeout)
            if not ok:
                raise TimeoutError(
                    f"barrier: {len(self._arrived)}/{self.trainer_num} "
                    f"trainers after {timeout}s")
            return True


def make_table(spec: dict):
    """Build a table from a config dict (reference ps.proto TableParameter:
    table type + accessor + common params)."""
    kind = spec.get("type", "sparse")
    if kind == "dense":
        return DenseTable(spec["shape"], spec.get("optimizer", "sgd"),
                          spec.get("lr", 0.01), spec.get("init", "zeros"),
                          spec.get("seed", 0))
    if kind == "sparse":
        return SparseTable(spec["dim"], spec.get("optimizer", "adagrad"),
                           spec.get("lr", 0.05), spec.get("init", "uniform"),
                           spec.get("seed", 0))
    if kind == "geo_sparse":
        return GeoSparseTable(spec["dim"], spec.get("lr", 1.0),
                              spec.get("init", "uniform"),
                              spec.get("seed", 0))
    if kind == "barrier":
        return BarrierTable(spec.get("trainer_num", 1))
    raise ValueError(f"unknown table type {kind!r}")
